"""On-TPU Pallas kernel smoke suite (VERDICT r4 item 2).

Every Pallas kernel in the tree, Mosaic-compiled on real TPU hardware and
numerically checked against its XLA reference formulation — the failure
surface CI's interpret-mode runs cannot reach (tiling/layout errors only
appear under the Mosaic compiler). Target: < 5 min wall-clock on one chip.

Counterpart of the reference's kernel unit tests
(tests/unit/ops/transformer/inference, tests/unit/inference/kernels/
ragged_ops) which likewise run only where the hardware is.

Usage: ``python tpu_smoke.py`` (exits 1 unless all checks pass on TPU).
Writes ``TPU_SMOKE_r05.json`` with per-kernel pass/fail + timings.
"""

import json
import os
import sys
import threading
import time

# Same tunnel-failure hardening as bench.py: a wedged axon tunnel must
# produce a clean artifact, not a hang. SMOKE_TIMEOUT_S=0 disables.
_TIMEOUT_S = int(os.environ.get("SMOKE_TIMEOUT_S", "1200"))
_done = threading.Event()


def _fail_artifact(error):
    art = {"ok": False, "error": error, "checks": RESULTS}
    with open("TPU_SMOKE_r05.json", "w") as f:
        json.dump(art, f, indent=1)
        f.write("\n")
    print(json.dumps({"ok": False, "error": error}), flush=True)


def _watchdog():
    if not _done.wait(_TIMEOUT_S):
        _fail_artifact(f"smoke timed out after {_TIMEOUT_S}s "
                       "(wedged TPU tunnel?)")
        os._exit(1)


if _TIMEOUT_S > 0:
    threading.Thread(target=_watchdog, daemon=True).start()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def devices_with_retry(attempts=6, base_delay=20):
    """jax.devices() with backoff on transient tunnel UNAVAILABLE
    (bench.py's recovery pattern)."""
    for i in range(attempts):
        try:
            return jax.devices()
        except RuntimeError as e:
            if "UNAVAILABLE" not in str(e) or i == attempts - 1:
                raise
            delay = base_delay * (2 ** i)
            print(f"# backend UNAVAILABLE (attempt {i + 1}/{attempts}); "
                  f"retrying in {delay}s", file=sys.stderr, flush=True)
            try:
                from jax.extend.backend import clear_backends
            except ImportError:
                clear_backends = getattr(jax, "clear_backends", lambda: None)
            clear_backends()
            time.sleep(delay)


RESULTS = []


def check(name):
    def deco(fn):
        def run():
            t0 = time.perf_counter()
            try:
                detail = fn() or {}
                RESULTS.append({"check": name, "ok": True,
                                "seconds": round(time.perf_counter() - t0, 2),
                                **detail})
                print(f"PASS {name} ({RESULTS[-1]['seconds']}s)", flush=True)
            except Exception as e:
                RESULTS.append({"check": name, "ok": False,
                                "seconds": round(time.perf_counter() - t0, 2),
                                "error": f"{type(e).__name__}: {str(e)[:300]}"})
                print(f"FAIL {name}: {RESULTS[-1]['error']}", flush=True)
        run.check_name = name
        CHECKS.append(run)
        return run
    return deco


CHECKS = []


# ------------------------------------------------------------------ flash

@check("flash_fwd_bwd_gqa_fp32softmax")
def _flash():
    from deepspeed_tpu.ops.flash_attention import (flash_attention,
                                                   _attention_xla)
    rng = np.random.default_rng(0)
    B, T, H, KH, D = 2, 1024, 16, 4, 64
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, KH, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, KH, D)), jnp.float32)

    def loss_pallas(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(_attention_xla(q, k, v, True, 0) ** 2)

    out = flash_attention(q, k, v, causal=True)
    ref = _attention_xla(q, k, v, True, 0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)
    g = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g, gr, "q k v".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-2, rtol=5e-2, err_msg=f"d{nm}")
    return {"shape": [B, T, H, D], "gqa_group": H // KH}


@check("flash_sliding_window_fwd_bwd")
def _flash_window():
    from deepspeed_tpu.ops.flash_attention import (flash_attention,
                                                   _attention_xla)
    rng = np.random.default_rng(1)
    B, T, H, KH, D, W = 1, 2048, 8, 8, 64, 256
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, KH, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, KH, D)), jnp.float32)
    out = flash_attention(q, k, v, causal=True, window=W)
    ref = _attention_xla(q, k, v, True, W)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)
    g = jax.grad(lambda *a: jnp.sum(
        flash_attention(*a, causal=True, window=W) ** 2), (0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(
        _attention_xla(*a, True, W) ** 2), (0, 1, 2))(q, k, v)
    for a, b, nm in zip(g, gr, "q k v".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-2, rtol=5e-2, err_msg=f"d{nm}")
    return {"window": W, "seq": T}


# ------------------------------------------------------------------ paged

def _paged_case(rng, N, C, H, KH, D, bs, MB, NB, ctx_lens):
    q = jnp.asarray(rng.standard_normal((N, C, H, D)), jnp.float32)
    k_pool = jnp.asarray(rng.standard_normal((NB, KH, bs, D)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((NB, KH, bs, D)), jnp.float32)
    perm = rng.permutation(NB)
    tables = np.full((N, MB), -1, np.int64)
    pos, start_pos, n_tokens = 0, [], []
    for i, ctx in enumerate(ctx_lens):
        nblk = -(-ctx // bs)
        tables[i, :nblk] = perm[pos:pos + nblk]
        pos += nblk
        n_tok = min(C, ctx)
        start_pos.append(ctx - n_tok)
        n_tokens.append(n_tok)
    return (q, k_pool, v_pool, jnp.asarray(tables, jnp.int32),
            jnp.asarray(start_pos, jnp.int32), jnp.asarray(n_tokens, jnp.int32))


@check("flash_unscaled_attention")
def _flash_unscaled():
    """r5 attn_scale threading (GPT-Neo's scale-1.0 softmax): the Pallas
    kernel with sm_scale=1.0 matches the XLA reference on hardware."""
    from deepspeed_tpu.ops.flash_attention import (flash_attention,
                                                   _attention_xla)
    rng = np.random.default_rng(5)
    B, T, H, D = 1, 1024, 8, 64
    q = jnp.asarray(0.1 * rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(0.1 * rng.standard_normal((B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    out = flash_attention(q, k, v, causal=True, sm_scale=1.0)
    ref = _attention_xla(q, k, v, True, 0, 1.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)
    return {"sm_scale": 1.0}


@check("paged_decode_blocktables_gqa")
def _paged():
    from deepspeed_tpu.ops import paged_attention as pa
    rng = np.random.default_rng(2)
    for case in [(3, 1, 8, 2, 64, 16, 8, 32, [5, 33, 100]),     # decode GQA
                 (2, 8, 4, 2, 64, 16, 8, 32, [8, 40]),          # prefill chunk
                 (4, 4, 4, 1, 128, 8, 8, 32, [4, 7, 30, 64])]:  # MQA ragged
        args = _paged_case(rng, *case)
        out = pa.paged_attention(*args)
        ref = pa.paged_attention_xla(*args)
        for i in range(case[0]):
            valid = int(args[5][i])
            np.testing.assert_allclose(np.asarray(out)[i, :valid],
                                       np.asarray(ref)[i, :valid],
                                       atol=2e-2, rtol=2e-2,
                                       err_msg=f"case {case} seq {i}")


@check("paged_alibi_and_window")
def _paged_alibi_window():
    from deepspeed_tpu.ops import paged_attention as pa
    rng = np.random.default_rng(3)
    N, C, H, KH, D, bs, MB, NB = 3, 1, 8, 2, 64, 16, 8, 32
    args = _paged_case(rng, N, C, H, KH, D, bs, MB, NB, [5, 33, 100])
    slopes = jnp.asarray(2.0 ** (-np.arange(1, H + 1)), jnp.float32)
    out = pa.paged_attention(*args, alibi_slopes=slopes)
    ref = pa.paged_attention_xla(*args, alibi_slopes=slopes)
    for i in range(N):
        np.testing.assert_allclose(np.asarray(out)[i, :1],
                                   np.asarray(ref)[i, :1],
                                   atol=2e-2, rtol=2e-2, err_msg="alibi")
    out = pa.paged_attention(*args, window=32)
    ref = pa.paged_attention_xla(*args, window=32)
    for i in range(N):
        np.testing.assert_allclose(np.asarray(out)[i, :1],
                                   np.asarray(ref)[i, :1],
                                   atol=2e-2, rtol=2e-2, err_msg="window")


@check("decode_latency_flat_in_context")
def _decode_latency():
    """The headline v1-decode claim (test_inference.py:248, skipped off-TPU):
    per-token decode time ~flat in context length (dead blocks cost no DMA
    or compute)."""
    import dataclasses
    from deepspeed_tpu.models.transformer import CausalLM, TINY_TEST

    model = CausalLM(dataclasses.replace(
        TINY_TEST, max_seq_len=4096, vocab_size=512))
    params = model.init(jax.random.PRNGKey(0))
    cache, tables = model.init_paged_cache(1, 4096, 128)
    tok = jnp.zeros((1,), jnp.int32)
    step = jax.jit(model.decode_step_paged)

    def timed(pos):
        logits, _ = step(params, cache, tables, tok, jnp.asarray([pos]))
        jax.block_until_ready(logits)
        t0 = time.perf_counter()
        for _ in range(20):
            logits, _ = step(params, cache, tables, tok, jnp.asarray([pos]))
        jax.block_until_ready(logits)
        return (time.perf_counter() - t0) / 20

    t_short, t_long = timed(64), timed(4000)
    assert t_long < 5 * t_short, (t_short, t_long)
    return {"per_token_ms_ctx64": round(t_short * 1e3, 3),
            "per_token_ms_ctx4000": round(t_long * 1e3, 3),
            "ratio": round(t_long / t_short, 2)}


# -------------------------------------------------------------- quantizer

@check("quantizer_int8_int4")
def _quant():
    from deepspeed_tpu.ops import quantizer as qz
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((256, 1024)), jnp.float32)
    for bits, tol in [(8, 2e-2), (4, 2e-1)]:
        q, s = qz.quantize_blockwise(x, bits=bits, block=128)
        ref_q, ref_s = qz._quantize_xla(x, bits, 128)
        np.testing.assert_array_equal(np.asarray(q), np.asarray(ref_q))
        np.testing.assert_allclose(np.asarray(s), np.asarray(ref_s),
                                   rtol=1e-6)
        y = qz.dequantize_blockwise(q, s, block=128)
        err = float(jnp.max(jnp.abs(y - x)))
        scale_bound = float(jnp.max(s))
        assert err <= scale_bound + tol, (bits, err, scale_bound)
        if bits == 4:
            packed = qz.pack_int4(q)
            np.testing.assert_array_equal(np.asarray(qz.unpack_int4(packed)),
                                          np.asarray(q))


# ------------------------------------------------------------ ring / 1-bit

@check("ring_attention_window_1dev")
def _ring():
    from deepspeed_tpu.parallel import topology as topo
    from deepspeed_tpu.sequence.ring_attention import ring_attention_sharded
    from deepspeed_tpu.ops.flash_attention import _attention_xla

    topo.reset_topology()
    topo.MeshTopology.build(sequence=1)
    rng = np.random.default_rng(5)
    B, T, H, D, W = 1, 512, 4, 64, 128
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    out = ring_attention_sharded(q, k, v, causal=True, window=W)
    ref = _attention_xla(q, k, v, True, W)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)
    topo.reset_topology()


@check("onebit_packed_wire_1dev")
def _onebit():
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    from deepspeed_tpu.ops.onebit import _sign_compress_two_phase, _seg_len

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    rng = np.random.default_rng(6)
    n = 4096
    c = jnp.asarray(rng.standard_normal((1, n)), jnp.float32)
    e_srv = jnp.zeros((1, _seg_len(n, 1)), jnp.float32)

    def worker(c, e):
        avg, err, e_new = _sign_compress_two_phase(c[0], e[0], 1)
        return avg[None], err[None], e_new[None]

    fn = shard_map(worker, mesh=mesh,
                   in_specs=(P("data"), P("data")),
                   out_specs=(P("data"), P("data"), P("data")),
                   check_vma=False)
    avg, err, e_new = jax.jit(fn)(c, e_srv)
    # worker error-feedback identity: c = sign(c)*scale + err
    scale = float(jnp.mean(jnp.abs(c)))
    recon = np.where(np.asarray(c[0]) >= 0, scale, -scale) + np.asarray(err[0])
    np.testing.assert_allclose(recon, np.asarray(c[0]), atol=1e-5, rtol=1e-5)
    assert np.isfinite(np.asarray(avg)).all()
    # second-phase reconstruction + server error covers the first-phase mean
    seg_avg = np.where(np.asarray(c[0]) >= 0, scale, -scale)
    np.testing.assert_allclose(np.asarray(avg[0]) + np.asarray(e_new[0])[:n],
                               seg_avg, atol=1e-5, rtol=1e-5)


def main():
    dev = devices_with_retry()[0]
    if dev.platform != "tpu":
        _fail_artifact(f"not on TPU (platform={dev.platform})")
        sys.exit(1)
    t0 = time.perf_counter()
    for run in CHECKS:
        run()
    total = round(time.perf_counter() - t0, 1)
    ok = all(r["ok"] for r in RESULTS)
    art = {"ok": ok, "device": str(dev), "total_seconds": total,
           "checks": RESULTS}
    with open("TPU_SMOKE_r05.json", "w") as f:
        json.dump(art, f, indent=1)
        f.write("\n")
    print(json.dumps({"ok": ok, "n_checks": len(RESULTS),
                      "total_seconds": total}))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        _done.set()
        raise
    except Exception as e:  # artifact on any crash, never a bare traceback
        import traceback
        traceback.print_exc()
        _fail_artifact(f"{type(e).__name__}: {str(e)[:400]}")
        _done.set()
        raise SystemExit(1)
    _done.set()
