"""Compression primitives: STE quantizers and magnitude binarizers.

Counterpart of reference ``compression/utils.py`` (``SymQuantizer``,
``AsymQuantizer``, ``TernaryQuantizer``, ``BinaryQuantizer``,
``TopKBinarizer`` — torch autograd.Functions with straight-through
backward). The TPU-native form is ``jax.custom_vjp`` functions: forward
quantizes/masks, backward passes gradients straight through to the fp32
master weights, so the whole QAT step stays inside one jitted program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _ste(fwd_fn):
    """Wrap an elementwise transform with a straight-through gradient."""

    @jax.custom_vjp
    def f(x, *args):
        return fwd_fn(x, *args)

    def f_fwd(x, *args):
        return fwd_fn(x, *args), len(args)

    def f_bwd(n_args, g):
        return (g,) + (None,) * n_args

    f.defvjp(f_fwd, f_bwd)
    return f


def _group_reshape(x, num_groups):
    """[*, n] → [num_groups, n//num_groups] view over the flattened array."""
    flat = x.reshape(-1)
    pad = (-flat.size) % num_groups
    if pad:
        raise ValueError(
            f"size {flat.size} not divisible into {num_groups} groups")
    return flat.reshape(num_groups, -1)


def _sym_quant(x, bits, num_groups):
    q = 2.0 ** (bits - 1) - 1
    g = _group_reshape(x, num_groups)
    scale = jnp.max(jnp.abs(g), axis=-1, keepdims=True) / q
    scale = jnp.where(scale == 0, 1.0, scale)
    return (jnp.round(g / scale) * scale).reshape(x.shape)


def _asym_quant(x, bits, num_groups):
    q = 2.0 ** bits - 1
    g = _group_reshape(x, num_groups)
    lo = jnp.min(g, axis=-1, keepdims=True)
    hi = jnp.max(g, axis=-1, keepdims=True)
    scale = jnp.where(hi > lo, (hi - lo) / q, 1.0)
    return (jnp.round((g - lo) / scale) * scale + lo).reshape(x.shape)


def _ternary(x, num_groups):
    g = _group_reshape(x, num_groups)
    thresh = 0.7 * jnp.mean(jnp.abs(g), axis=-1, keepdims=True)
    mask = jnp.abs(g) > thresh
    alpha = jnp.sum(jnp.abs(g) * mask, axis=-1, keepdims=True) / \
        jnp.maximum(1, jnp.sum(mask, axis=-1, keepdims=True))
    return (jnp.sign(g) * alpha * mask).reshape(x.shape)


def _binary(x, num_groups):
    g = _group_reshape(x, num_groups)
    alpha = jnp.mean(jnp.abs(g), axis=-1, keepdims=True)
    return (jnp.sign(g) * alpha).reshape(x.shape)


sym_quantize = _ste(_sym_quant)
asym_quantize = _ste(_asym_quant)
ternary_quantize = _ste(_ternary)
binary_quantize = _ste(_binary)


def quantizer_for(bits: int, mode: str = "symmetric"):
    if bits == 1:
        return lambda x, groups: binary_quantize(x, groups)
    if bits == 2:
        return lambda x, groups: ternary_quantize(x, groups)
    fn = sym_quantize if mode == "symmetric" else asym_quantize
    return lambda x, groups: fn(x, bits, groups)


def _topk_mask(x, ratio):
    """Keep the top-``ratio`` fraction by |value| (reference TopKBinarizer:
    the mask itself; gradients pass through via the STE wrapper)."""
    flat = jnp.abs(x).reshape(-1)
    k = jnp.maximum(1, jnp.round(ratio * flat.size)).astype(jnp.int32)
    # threshold = k-th largest magnitude
    thresh = jnp.sort(flat)[jnp.maximum(0, flat.size - k)]
    return (jnp.abs(x) >= thresh).astype(x.dtype)


def topk_binarize(x, ratio):
    return _ste(lambda v, r: v * _topk_mask(v, r))(x, ratio)


def quantize_activation(x, bits: int = 8, mode: str = "symmetric"):
    """Dynamic-range activation fake-quant (reference QuantAct with dynamic
    calibration; the momentum-updated static range is an inference-time
    latency trick that does not apply to an XLA-fused fake-quant)."""
    q = 2.0 ** (bits - 1) - 1 if mode == "symmetric" else 2.0 ** bits - 1
    if mode == "symmetric":
        scale = jnp.max(jnp.abs(x)) / q
        scale = jnp.where(scale == 0, 1.0, scale)
        return _ste(lambda v, s: jnp.round(v / s) * s)(x, scale)
    lo, hi = jnp.min(x), jnp.max(x)
    scale = jnp.where(hi > lo, (hi - lo) / q, 1.0)
    return _ste(lambda v, s, l: jnp.round((v - l) / s) * s + l)(x, scale, lo)
