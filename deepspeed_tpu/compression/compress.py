"""Config-driven compression: QAT + pruning over the parameter pytree.

Counterpart of reference ``compression/compress.py`` (``init_compression``
:100, ``redundancy_clean`` :148) and ``compression/scheduler.py``
(schedule offsets). The reference walks the nn.Module graph replacing
layers with ``*_Compress`` subclasses; the TPU-native design compiles the
techniques into ONE pure function ``transform(params, global_step) →
params`` that the engine applies to the master weights inside the jitted
micro step — QAT/pruning become part of the forward program, gradients
reach the fp32 masters through the STE/mask, and nothing is mutated.

Config surface (reference ``compression_training`` schema kept):

    "compression_training": {
      "weight_quantization": {
        "shared_parameters": {"enabled": true, "schedule_offset": 0, ...},
        "different_groups": {
          "wq1": {"params": {"target_bits": 8, "quantization_period": 0},
                   "modules": ["layers.*"]}}},
      "sparse_pruning":  {"shared_parameters": {...}, "different_groups":
          {"sp1": {"params": {"dense_ratio": 0.5}, "modules": ["..."]}}},
      "row_pruning" / "head_pruning" / "channel_pruning": same shape
    }

``modules`` patterns are matched (fnmatch) against dotted pytree paths
(e.g. ``layers.wq``); ``["*"]`` matches every ≥2-D leaf.
"""

from __future__ import annotations

import fnmatch
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.logging import logger
from .basic_transforms import (channel_prune, head_prune, quantize_weight,
                               row_prune, sparse_prune)

TECHNIQUES = ("weight_quantization", "sparse_pruning", "row_pruning",
              "head_pruning", "channel_pruning")


def _leaf_path(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return ".".join(parts)


def _matches(path: str, patterns: List[str]) -> bool:
    return any(fnmatch.fnmatch(path, pat) or pat in path
               for pat in patterns)


class CompressionTransform:
    """One compiled plan: leaf path → ordered list of (offset, fn)."""

    def __init__(self, config: Dict[str, Any]):
        self.config = config.get("compression_training", config) or {}
        self.plans: List[Tuple[str, int, List[str], Callable]] = []
        for technique in TECHNIQUES:
            tc = self.config.get(technique)
            if not tc:
                continue
            shared = tc.get("shared_parameters", {})
            if not shared.get("enabled", False):
                continue
            offset = int(shared.get("schedule_offset", 0))
            for gname, group in (tc.get("different_groups") or {}).items():
                params = group.get("params", {})
                modules = group.get("modules", ["*"])
                fn = self._technique_fn(technique, params)
                self.plans.append((technique, offset, modules, fn))
                logger.info(f"compression: {technique}/{gname} offset="
                            f"{offset} modules={modules}")

    @staticmethod
    def _technique_fn(technique: str, p: Dict[str, Any]) -> Callable:
        if technique == "weight_quantization":
            bits = int(p.get("target_bits", 8))
            mode = p.get("quantization_type", "symmetric")
            groups = int(p.get("quantize_groups", 1))
            return lambda w: quantize_weight(w, bits, mode, groups)
        if technique == "sparse_pruning":
            ratio = 1.0 - float(p.get("dense_ratio", 0.5))
            method = p.get("method", "l1")
            return lambda w: sparse_prune(w, ratio, method)
        if technique == "row_pruning":
            ratio = 1.0 - float(p.get("dense_ratio", 0.5))
            return lambda w: row_prune(w, ratio)
        if technique == "channel_pruning":
            ratio = 1.0 - float(p.get("dense_ratio", 0.5))
            return lambda w: channel_prune(w, ratio)
        if technique == "head_pruning":
            ratio = 1.0 - float(p.get("dense_ratio", 0.5))
            heads = int(p["num_heads"])
            axis = p.get("axis", "in")
            return lambda w: head_prune(w, ratio, heads, axis)
        raise ValueError(technique)

    def __bool__(self) -> bool:
        return bool(self.plans)

    def __call__(self, params, global_step):
        """Apply every matching technique whose offset has passed; the
        step gate is a traced jnp.where so one compiled program serves the
        whole run (reference scheduler.py check_compress_schedule)."""
        if not self.plans:
            return params
        flat = jax.tree_util.tree_flatten_with_path(params)
        leaves, treedef = flat[0], flat[1]
        out = []
        for path, leaf in leaves:
            name = _leaf_path(path)
            new = leaf
            if hasattr(leaf, "ndim") and leaf.ndim >= 2:
                for technique, offset, modules, fn in self.plans:
                    if _matches(name, modules):
                        applied = fn(new)
                        gate = jnp.asarray(global_step >= offset)
                        new = jnp.where(gate, applied, new)
            out.append(new)
        return jax.tree_util.tree_unflatten(treedef, out)

    def clean(self, params):
        """Permanently bake the compression into the weights (reference
        redundancy_clean :148 — post-training cleanup for export)."""
        flat = jax.tree_util.tree_flatten_with_path(params)
        leaves, treedef = flat[0], flat[1]
        out = []
        for path, leaf in leaves:
            name = _leaf_path(path)
            new = leaf
            if hasattr(leaf, "ndim") and leaf.ndim >= 2:
                for technique, _offset, modules, fn in self.plans:
                    if _matches(name, modules):
                        new = fn(new)
            out.append(new)
        return jax.tree_util.tree_unflatten(treedef, out)


def init_compression(engine_or_config, deepspeed_config: Optional[Dict] = None):
    """Reference compress.py:100. Pass an engine (attaches the transform to
    its step programs) or a config dict (returns the bare transform)."""
    if deepspeed_config is None:
        return CompressionTransform(engine_or_config)
    transform = CompressionTransform(deepspeed_config)
    engine = engine_or_config
    engine.set_compression(transform)
    return transform


def redundancy_clean(params, deepspeed_config: Dict[str, Any]):
    """Reference compress.py:148: apply the configured masks/quantization
    permanently to a parameter pytree."""
    return CompressionTransform(deepspeed_config).clean(params)


def student_initialization(teacher_params, keep_layers: List[int],
                           layers_key: str = "layers"):
    """Layer-reduction distillation init (reference compression
    ``layer_reduction`` / helper.py student_initialization): build a
    shallower student by keeping the listed teacher layer indices. With the
    stacked-layer layout ([L, ...] leaves under ``layers``) this is one
    gather per leaf instead of a module-graph rewrite."""
    idx = jnp.asarray(keep_layers, dtype=jnp.int32)

    def take(leaf):
        return jnp.take(leaf, idx, axis=0)

    out = dict(teacher_params)
    out[layers_key] = jax.tree.map(take, teacher_params[layers_key])
    return out
