"""Structured/unstructured pruning + weight QAT as pure weight transforms.

Counterpart of reference ``compression/basic_layer.py`` (LinearLayer_Compress
:121 — sparse/row/head/channel pruning masks + weight quantization inside
``forward``). The torch version subclasses nn.Linear and mutates modules;
the TPU-native form is a pure function per technique applied to the weight
pytree inside the jitted step (masks are recomputed from the live fp32
masters each application, exactly like the reference's per-forward
``get_mask``; gradients reach the masters through the mask product and the
quantizer STE).

All transforms treat the trailing two axes as (in_features, out_features)
and broadcast over leading axes — the stacked-layer [L, in, out] layout of
models/transformer.py works unchanged.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .utils import quantizer_for, topk_binarize


def quantize_weight(w, bits: int, mode: str = "symmetric",
                    num_groups: int = 1):
    """QAT fake-quant (reference LinearLayer_Compress weight_quantization)."""
    return quantizer_for(bits, mode)(w, num_groups)


def sparse_prune(w, ratio: float, method: str = "l1"):
    """Unstructured magnitude pruning keeping the top (1-ratio) fraction
    (reference sparse_pruning_enabled path; method 'topk' learns through
    the STE, 'l1' is the plain magnitude mask)."""
    keep = 1.0 - ratio
    if method not in ("l1", "topk"):
        raise ValueError(f"sparse pruning method {method!r} (want l1|topk)")
    return topk_binarize(w, keep)


def row_prune(w, ratio: float):
    """Structured row pruning: zero the lowest-L1 input rows (reference
    row_pruning; rows = axis -2)."""
    norms = jnp.sum(jnp.abs(w), axis=-1, keepdims=True)       # [..., in, 1]
    n_rows = w.shape[-2]
    k = max(1, int(round((1.0 - ratio) * n_rows)))
    thresh = jnp.sort(norms, axis=-2)[..., n_rows - k:n_rows - k + 1, :]
    return w * (norms >= thresh).astype(w.dtype)


def channel_prune(w, ratio: float):
    """Structured output-channel pruning (reference channel_pruning;
    channels = axis -1)."""
    norms = jnp.sum(jnp.abs(w), axis=-2, keepdims=True)       # [..., 1, out]
    n_ch = w.shape[-1]
    k = max(1, int(round((1.0 - ratio) * n_ch)))
    thresh = jnp.sort(norms, axis=-1)[..., :, n_ch - k:n_ch - k + 1]
    return w * (norms >= thresh).astype(w.dtype)


def head_prune(w, ratio: float, num_heads: int, axis: str = "in"):
    """Attention-head pruning (reference head_pruning on the attention
    output projection): group the chosen axis into heads, zero the
    lowest-L1 heads."""
    if axis not in ("in", "out"):
        raise ValueError("head_prune axis must be 'in' or 'out'")
    dim = -2 if axis == "in" else -1
    size = w.shape[dim]
    if size % num_heads:
        raise ValueError(f"axis size {size} not divisible by "
                         f"{num_heads} heads")
    head_dim = size // num_heads
    lead = w.shape[:-2]
    if axis == "in":
        g = w.reshape(*lead, num_heads, head_dim, w.shape[-1])
        norms = jnp.sum(jnp.abs(g), axis=(-2, -1), keepdims=True)
        head_axis = -3
    else:
        g = w.reshape(*lead, w.shape[-2], num_heads, head_dim)
        norms = jnp.sum(jnp.abs(g), axis=(-3, -1), keepdims=True)
        head_axis = -2
    k = max(1, int(round((1.0 - ratio) * num_heads)))
    sorted_norms = jnp.sort(norms, axis=head_axis)
    idx = [slice(None)] * norms.ndim
    idx[head_axis] = slice(num_heads - k, num_heads - k + 1)
    thresh = sorted_norms[tuple(idx)]
    mask = (norms >= thresh).astype(w.dtype)
    return (g * mask).reshape(w.shape)
