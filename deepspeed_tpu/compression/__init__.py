"""Compression suite (reference ``deepspeed/compression/``): QAT,
sparse/row/head/channel pruning, scheduler, post-training cleanup."""

from .compress import (CompressionTransform, init_compression,
                       redundancy_clean, student_initialization)
from .basic_transforms import (channel_prune, head_prune, quantize_weight,
                               row_prune, sparse_prune)
from .utils import (asym_quantize, binary_quantize, quantize_activation,
                    sym_quantize, ternary_quantize, topk_binarize)

__all__ = [
    "CompressionTransform", "init_compression", "redundancy_clean",
    "student_initialization",
    "quantize_weight", "sparse_prune", "row_prune", "head_prune",
    "channel_prune", "sym_quantize", "asym_quantize", "ternary_quantize",
    "binary_quantize", "topk_binarize", "quantize_activation",
]
