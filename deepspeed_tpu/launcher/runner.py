"""`dstpu` CLI — multi-host TPU launcher.

Counterpart of reference ``launcher/runner.py:389`` (``deepspeed`` CLI:
hostfile parsing :201, ``--include/--exclude`` resource filters :256, ssh
check, PDSH/MPI/SLURM multinode runners) and per-node ``launcher/launch.py:132``.

The TPU process model is simpler than the reference's: ONE process per host
(the PJRT client owns all local chips), not one per accelerator, so the
per-node launcher forks a single training process with rendezvous env vars
(``COORDINATOR_ADDRESS``/``RANK``/``WORLD_SIZE`` → ``jax.distributed``)
instead of `launch.py`'s N-rank fork + RANK/LOCAL_RANK bookkeeping.

Modes:
- single host: exec the script directly (world_size 1).
- ``--hostfile``: ssh/pdsh to each host, set env, run the same command
  (reference MultiNodeRunner, multinode_runner.py:18,51).
- under SLURM (``SLURM_PROCID`` set) or GKE/TPU-pod env
  (``TPU_WORKER_ID``/``MEGASCALE_SLICE_ID``): derive rank/world/coordinator
  from the environment and exec in-place.
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
from typing import Dict, List

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"


def fetch_hostfile(path: str) -> Dict[str, int]:
    """Parse ``host slots=N`` lines (reference launcher/runner.py:201)."""
    if not os.path.isfile(path):
        return {}
    hosts: Dict[str, int] = {}
    with open(path) as fh:
        for line in fh:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=")[1])
            if host in hosts:
                raise ValueError(f"Duplicate host {host} in hostfile")
            hosts[host] = slots
    return hosts


def parse_resource_filter(hosts: Dict[str, int], include: str = "",
                          exclude: str = "") -> Dict[str, int]:
    """``--include host1@host2`` / ``--exclude host3`` (reference :256).
    Per-slot filters (host:0,1) are not meaningful on TPU (1 proc/host) and
    raise."""
    if include and exclude:
        raise ValueError("--include and --exclude are mutually exclusive")
    spec = include or exclude
    if not spec:
        return dict(hosts)
    names = []
    for part in spec.split("@"):
        part = part.strip()
        if ":" in part:
            raise ValueError("per-slot filters are not supported on TPU "
                             "(one process per host owns all local chips)")
        if part and part not in hosts:
            raise ValueError(f"host {part!r} not in hostfile")
        if part:
            names.append(part)
    if include:
        return {h: hosts[h] for h in names}
    return {h: s for h, s in hosts.items() if h not in names}


def _env_rank_info():
    """Detect rank/world/coordinator from SLURM or TPU-pod env."""
    env = os.environ
    if "SLURM_PROCID" in env:
        rank = int(env["SLURM_PROCID"])
        world = int(env.get("SLURM_NTASKS", "1"))
        nodelist = env.get("SLURM_JOB_NODELIST", "")
        coord = env.get("COORDINATOR_ADDRESS")
        if coord is None and nodelist:
            first = subprocess.run(
                ["scontrol", "show", "hostnames", nodelist],
                capture_output=True, text=True).stdout.splitlines()
            coord = f"{first[0]}:8476" if first else None
        return rank, world, coord
    if "TPU_WORKER_ID" in env:
        rank = int(env["TPU_WORKER_ID"])
        hosts = env.get("TPU_WORKER_HOSTNAMES", "").split(",")
        world = len([h for h in hosts if h.strip()]) or 1
        coord = f"{hosts[0].strip()}:8476" if hosts and hosts[0].strip() else None
        return rank, world, coord
    return None


def build_cmd(args, rank: int, world: int, coord: str) -> List[str]:
    cmd = [sys.executable, "-u", args.user_script] + args.user_args
    return cmd


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher (TPU-pod aware; 1 process/host)")
    parser.add_argument("--hostfile", type=str, default=DLTS_HOSTFILE)
    parser.add_argument("--include", type=str, default="")
    parser.add_argument("--exclude", type=str, default="")
    parser.add_argument("--master_addr", type=str, default=None)
    parser.add_argument("--master_port", type=int, default=8476)
    parser.add_argument("--ssh_port", type=int, default=22)
    parser.add_argument("--launcher", type=str, default="ssh",
                        choices=["ssh", "pdsh", "local"])
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--autotune", "--autotuning", type=str, default=None,
                        metavar="MODEL:CONFIG.json",
                        help="run the autotuner (autotuning/autotuner.py) for "
                             "MODEL (registered name) with the given base "
                             "config instead of launching a script; prints "
                             "the best config JSON")
    parser.add_argument("user_script", type=str, nargs="?")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.autotune:
        # reference runner.py:360 run_autotuning entry. Tuning runs
        # IN-PROCESS on this host's devices — reject multi-node flags and a
        # user_script rather than silently ignoring them.
        conflicting = []
        if args.hostfile != DLTS_HOSTFILE:
            conflicting.append("--hostfile")
        if args.include or args.exclude:
            conflicting.append("--include/--exclude")
        if args.user_script:
            conflicting.append("user_script")
        if conflicting:
            parser.error(f"--autotune tunes on this host's devices and is "
                         f"incompatible with {', '.join(conflicting)}; run it "
                         "on the target hardware without a script")
        import json as _json

        from ..autotuning import autotune
        from ..models import build_model

        model_name, _, cfg_path = args.autotune.partition(":")
        base = {}
        if cfg_path:
            with open(cfg_path) as fh:
                base = _json.load(fh)
        best = autotune(build_model(model_name), base)
        print(_json.dumps(best, indent=2))
        return
    if args.user_script is None:
        parser.error("user_script is required (or pass --autotune)")

    info = _env_rank_info()
    if info is not None:
        # running inside a managed allocation: exec in place
        rank, world, coord = info
        env = os.environ
        if coord:
            env.setdefault("COORDINATOR_ADDRESS", coord)
        env.setdefault("RANK", str(rank))
        env.setdefault("WORLD_SIZE", str(world))
        os.execvpe(sys.executable, build_cmd(args, rank, world, coord), env)

    hosts = fetch_hostfile(args.hostfile)
    hosts = parse_resource_filter(hosts, args.include, args.exclude)

    if len(hosts) <= 1 and not args.force_multi:
        env = dict(os.environ)
        env.setdefault("RANK", "0")
        env.setdefault("WORLD_SIZE", "1")
        os.execvpe(sys.executable, build_cmd(args, 0, 1, None), env)
        return

    host_list = list(hosts)
    coord_host = args.master_addr or host_list[0]
    coord = f"{coord_host}:{args.master_port}"
    world = len(host_list)
    procs = []
    for rank, host in enumerate(host_list):
        envs = (f"COORDINATOR_ADDRESS={shlex.quote(coord)} RANK={rank} "
                f"WORLD_SIZE={world}")
        remote_cmd = f"cd {shlex.quote(os.getcwd())} && {envs} " + " ".join(
            shlex.quote(c) for c in build_cmd(args, rank, world, coord))
        if args.launcher == "pdsh":
            cmd = ["pdsh", "-w", host, remote_cmd]
        else:
            cmd = ["ssh", "-p", str(args.ssh_port), host, remote_cmd]
        logger.info(f"launching rank {rank} on {host}")
        procs.append(subprocess.Popen(cmd))

    rc = 0
    try:
        for p in procs:
            rc |= p.wait()
    except KeyboardInterrupt:
        for p in procs:
            p.terminate()
        raise
    sys.exit(rc)


if __name__ == "__main__":
    main()
