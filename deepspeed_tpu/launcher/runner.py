"""`dstpu` CLI — multi-host TPU launcher.

Counterpart of reference ``launcher/runner.py:389`` (``deepspeed`` CLI:
hostfile parsing :201, ``--include/--exclude`` resource filters :256, ssh
check, PDSH/MPI/SLURM multinode runners) and per-node ``launcher/launch.py:132``.

The TPU process model is simpler than the reference's: ONE process per host
(the PJRT client owns all local chips), not one per accelerator, so the
per-node launcher forks a single training process with rendezvous env vars
(``COORDINATOR_ADDRESS``/``RANK``/``WORLD_SIZE`` → ``jax.distributed``)
instead of `launch.py`'s N-rank fork + RANK/LOCAL_RANK bookkeeping.

Modes:
- single host: exec the script directly (world_size 1).
- ``--launcher local --num_local_procs N``: N rank processes on this host
  (reference launch.py's per-node fork), babysat as a group.
- ``--hostfile``: ssh/pdsh to each host, set env, run the same command
  (reference MultiNodeRunner, multinode_runner.py:18,51).
- under SLURM (``SLURM_PROCID`` set) or GKE/TPU-pod env
  (``TPU_WORKER_ID``/``MEGASCALE_SLICE_ID``): derive rank/world/coordinator
  from the environment and exec in-place.

Process lifecycle (round 4 — reference launch.py:118,132): children spawn
in their own sessions, a babysitter kills every survivor's process tree the
moment any rank fails (no more hung jobs at a dead rank's collective), and
``--max_restarts N`` wraps the whole job in a restart supervisor — scripts
reload their latest (universal) checkpoint and re-derive the elastic batch
when they come back up.
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
from typing import Dict, List, Optional

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"


def local_chip_count() -> int:
    """Number of TPU chips attached to this host (0 when none/unknown).
    TPU VMs expose one ``/dev/accel*`` (older runtimes: ``/dev/vfio/N``)
    node per chip; no PJRT client is created — probing via jax would
    *claim* the chips the spawned ranks need."""
    import glob

    return (len(glob.glob("/dev/accel[0-9]*"))
            or len(glob.glob("/dev/vfio/[0-9]*")))


def chip_assignment(chips: int, world: int, rank: int):
    """Default per-rank ``TPU_VISIBLE_CHIPS`` value for ``--launcher
    local``: an even slice of the host's chips per rank, or None when no
    sane default exists (no chips detected, or more ranks than chips).
    Without this, every spawned PJRT client tries to own ALL local chips
    and single-host multi-process mode fails out of the box on TPU."""
    if chips <= 0 or world > chips:
        return None
    per = chips // world
    return ",".join(str(i) for i in range(rank * per, (rank + 1) * per))


# libtpu's default inter-process coordination port; per-rank ports count up
# from here so N local processes never collide.
TPU_PROCESS_BASE_PORT = 8476


def tpu_process_env(world: int, rank: int,
                    base_port: int = TPU_PROCESS_BASE_PORT):
    """Per-rank libtpu multi-process env for ``--launcher local``.

    ``TPU_VISIBLE_CHIPS`` alone is not enough on real hardware: each PJRT
    client in a single-host multi-process job also needs a distinct
    coordination endpoint (``TPU_PROCESS_PORT``), the full endpoint list
    (``TPU_PROCESS_ADDRESSES``), and its task index (``CLOUD_TPU_TASK_ID``)
    — otherwise the runtimes race on the default port 8476. Values follow
    the Cloud TPU multi-process conventions (jax.distributed on TPU VMs).
    """
    addrs = ",".join(f"127.0.0.1:{base_port + r}" for r in range(world))
    return {
        "TPU_PROCESS_PORT": str(base_port + rank),
        "TPU_PROCESS_ADDRESSES": addrs,
        "CLOUD_TPU_TASK_ID": str(rank),
    }


def fetch_hostfile(path: str) -> Dict[str, int]:
    """Parse ``host slots=N`` lines (reference launcher/runner.py:201)."""
    if not os.path.isfile(path):
        return {}
    hosts: Dict[str, int] = {}
    with open(path) as fh:
        for line in fh:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=")[1])
            if host in hosts:
                raise ValueError(f"Duplicate host {host} in hostfile")
            hosts[host] = slots
    return hosts


def parse_resource_filter(hosts: Dict[str, int], include: str = "",
                          exclude: str = "") -> Dict[str, int]:
    """``--include host1@host2`` / ``--exclude host3`` (reference :256).
    Per-slot filters (host:0,1) are not meaningful on TPU (1 proc/host) and
    raise."""
    if include and exclude:
        raise ValueError("--include and --exclude are mutually exclusive")
    spec = include or exclude
    if not spec:
        return dict(hosts)
    names = []
    for part in spec.split("@"):
        part = part.strip()
        if ":" in part:
            raise ValueError("per-slot filters are not supported on TPU "
                             "(one process per host owns all local chips)")
        if part and part not in hosts:
            raise ValueError(f"host {part!r} not in hostfile")
        if part:
            names.append(part)
    if include:
        return {h: hosts[h] for h in names}
    return {h: s for h, s in hosts.items() if h not in names}


def _env_rank_info():
    """Detect rank/world/coordinator from SLURM or TPU-pod env."""
    env = os.environ
    if "SLURM_PROCID" in env:
        rank = int(env["SLURM_PROCID"])
        world = int(env.get("SLURM_NTASKS", "1"))
        nodelist = env.get("SLURM_JOB_NODELIST", "")
        coord = env.get("COORDINATOR_ADDRESS")
        if coord is None and nodelist:
            first = subprocess.run(
                ["scontrol", "show", "hostnames", nodelist],
                capture_output=True, text=True).stdout.splitlines()
            coord = f"{first[0]}:8476" if first else None
        return rank, world, coord
    if "TPU_WORKER_ID" in env:
        rank = int(env["TPU_WORKER_ID"])
        hosts = env.get("TPU_WORKER_HOSTNAMES", "").split(",")
        world = len([h for h in hosts if h.strip()]) or 1
        coord = f"{hosts[0].strip()}:8476" if hosts and hosts[0].strip() else None
        return rank, world, coord
    return None


def build_cmd(args, rank: int, world: int, coord: str) -> List[str]:
    cmd = [sys.executable, "-u", args.user_script] + args.user_args
    return cmd


# -------------------------------------------------- child monitoring / restart

def terminate_process_tree(proc: subprocess.Popen, timeout: float = 5.0):
    """SIGTERM the child's whole process group (children spawn with
    ``start_new_session=True`` so the group id == the child pid), escalate
    to SIGKILL after ``timeout`` (reference launcher/launch.py:118
    ``terminate_process_tree``)."""
    import signal

    if proc.poll() is not None:
        return
    try:
        os.killpg(proc.pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError, OSError):
        proc.terminate()
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            proc.kill()
        proc.wait()


def babysit(procs: List[subprocess.Popen], poll_interval: float = 0.3,
            on_fail=None) -> int:
    """Monitor children until all exit; on the FIRST failure, kill every
    survivor's process tree so a dead rank can't leave the job hung at a
    collective (reference launcher/launch.py:132 monitoring loop — the
    r3 'spawn and forget' gap). Returns the job's exit code.
    ``on_fail(indices)`` receives EVERY child already exited nonzero when
    the failure is detected — within one poll window a host crash and its
    collective-error cascade are indistinguishable, so the callback gets
    the full set and decides whether attribution is unambiguous."""
    import time

    import signal

    alive = list(procs)
    # SIGTERM → kill every rank tree, then exit. Children run in their own
    # sessions, so terminating the launcher alone would ORPHAN them (the
    # autotuner's experiment timeout, a scheduler's job kill, systemd stop
    # — all deliver SIGTERM to this process only).
    prev_term = signal.signal(
        signal.SIGTERM, lambda *_: (_ for _ in ()).throw(SystemExit(143)))
    try:
        while alive:
            for p in list(alive):
                rc = p.poll()
                if rc is None:
                    continue
                alive.remove(p)
                if rc != 0:
                    logger.error(
                        f"rank process {p.pid} exited rc={rc}; terminating "
                        f"{len(alive)} surviving rank(s)")
                    if on_fail is not None:
                        failed = [i for i, q in enumerate(procs)
                                  if q.poll() not in (None, 0)]
                        try:
                            on_fail(failed)
                        except Exception as e:
                            logger.warning(f"on_fail callback failed: {e}")
                    for q in alive:
                        terminate_process_tree(q)
                    return rc
            time.sleep(poll_interval)
        return 0
    except (KeyboardInterrupt, SystemExit):
        # children run in their own sessions and never see the terminal's
        # SIGINT — bring every tree down before propagating
        for q in alive:
            terminate_process_tree(q)
        raise
    finally:
        signal.signal(signal.SIGTERM, prev_term)


def supervise(spawn_fn, max_restarts: int = 0,
              between_attempts=None, on_fail=None) -> int:
    """Restart supervisor (reference elasticity/elastic_agent.py:28, TPU
    restart-based flavor): spawn + babysit; on failure relaunch the whole
    job up to ``max_restarts`` times. Training scripts are expected to
    resume from their latest (universal) checkpoint and re-derive the
    elastic batch on re-entry — the supervisor only owns the process
    lifecycle. ``between_attempts`` runs before each relaunch (remote-rank
    cleanup for the ssh/pdsh paths)."""
    attempt = 0
    while True:
        rc = babysit(spawn_fn(), on_fail=on_fail)
        if rc == 0:
            return 0
        attempt += 1
        if attempt > max_restarts:
            if max_restarts:
                logger.error(f"job failed rc={rc} after {max_restarts} "
                             "restart(s); giving up")
            return rc
        logger.warning(f"job failed rc={rc}; restarting "
                       f"({attempt}/{max_restarts})")
        if between_attempts is not None:
            try:
                between_attempts()
            except Exception as e:
                logger.warning(f"pre-restart cleanup failed: {e}")


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher (TPU-pod aware; 1 process/host)")
    parser.add_argument("--hostfile", type=str, default=DLTS_HOSTFILE)
    parser.add_argument("--include", type=str, default="")
    parser.add_argument("--exclude", type=str, default="")
    parser.add_argument("--master_addr", type=str, default=None)
    parser.add_argument("--master_port", type=int, default=8476)
    parser.add_argument("--ssh_port", type=int, default=22)
    parser.add_argument("--launcher", type=str, default="ssh",
                        choices=["ssh", "pdsh", "local"])
    parser.add_argument("--num_local_procs", type=int, default=1,
                        help="rank count for --launcher local")
    parser.add_argument("--elastic_min_world", type=int, default=0,
                        help="scale-down floor: when a restart is caused "
                        "by a failing host and the remaining hosts still "
                        "number >= this, EXCLUDE the dead host and "
                        "relaunch with a smaller world (restart-based "
                        "scale-down; scripts re-derive the elastic batch "
                        "from WORLD_SIZE and resume from checkpoint). "
                        "0 disables exclusion")
    parser.add_argument("--max_restarts", type=int, default=0,
                        help="restart the whole job up to N times after a "
                             "failure (restart supervisor; scripts resume "
                             "from their latest checkpoint)")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--autotune", "--autotuning", type=str, default=None,
                        metavar="MODEL:CONFIG.json",
                        help="run the autotuner (autotuning/autotuner.py) for "
                             "MODEL (registered name) with the given base "
                             "config instead of launching a script; prints "
                             "the best config JSON")
    parser.add_argument("user_script", type=str, nargs="?")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if args.elastic_min_world and not args.max_restarts:
        parser.error("--elastic_min_world needs --max_restarts > 0: "
                     "exclusion happens between restart attempts, so "
                     "without restarts the flag is a silent no-op")
    if args.elastic_min_world and args.launcher == "local":
        parser.error("--elastic_min_world applies to multi-host "
                     "(ssh/pdsh) jobs: there is no host to exclude in "
                     "--launcher local")

    if args.autotune:
        # reference runner.py:360 run_autotuning entry. Tuning runs
        # IN-PROCESS on this host's devices — reject multi-node flags and a
        # user_script rather than silently ignoring them.
        conflicting = []
        if args.hostfile != DLTS_HOSTFILE:
            conflicting.append("--hostfile")
        if args.include or args.exclude:
            conflicting.append("--include/--exclude")
        if args.user_script:
            conflicting.append("user_script")
        if conflicting:
            parser.error(f"--autotune tunes on this host's devices and is "
                         f"incompatible with {', '.join(conflicting)}; run it "
                         "on the target hardware without a script")
        import json as _json

        from ..autotuning import autotune
        from ..models import build_model

        model_name, _, cfg_path = args.autotune.partition(":")
        base = {}
        if cfg_path:
            with open(cfg_path) as fh:
                base = _json.load(fh)
        best = autotune(build_model(model_name), base)
        print(_json.dumps(best, indent=2))
        return
    if args.user_script is None:
        parser.error("user_script is required (or pass --autotune)")

    info = _env_rank_info()
    if info is not None:
        # running inside a managed allocation: exec in place
        rank, world, coord = info
        env = os.environ
        if coord:
            env.setdefault("COORDINATOR_ADDRESS", coord)
        env.setdefault("RANK", str(rank))
        env.setdefault("WORLD_SIZE", str(world))
        os.execvpe(sys.executable, build_cmd(args, rank, world, coord), env)

    if args.launcher == "local":
        # N local rank processes on this host (single-host multi-process
        # jobs and the supervisor's testbed; each rank sees a slice of the
        # local devices via its own env)
        world = args.num_local_procs
        coord = f"127.0.0.1:{args.master_port}"

        chips = local_chip_count()

        def spawn_local():
            procs = []
            for rank in range(world):
                env = dict(os.environ,
                           MASTER_ADDR="127.0.0.1",
                           MASTER_PORT=str(args.master_port),
                           COORDINATOR_ADDRESS=coord,
                           RANK=str(rank), LOCAL_RANK=str(rank),
                           WORLD_SIZE=str(world))
                # TPU chip ownership is per-PJRT-client: by default give
                # each rank an even slice of the local chips so N clients
                # don't contend for the same hardware. The user's env
                # (or the script itself) overrides.
                vis = None
                if "TPU_VISIBLE_CHIPS" not in os.environ:
                    vis = chip_assignment(chips, world, rank)
                    if vis is not None:
                        env["TPU_VISIBLE_CHIPS"] = vis
                # chip slicing alone (ours OR user-pinned) still collides
                # on libtpu's default coordination port — per-rank process
                # env rides along either way, per-variable overridable
                if vis is not None or "TPU_VISIBLE_CHIPS" in os.environ:
                    for k, v in tpu_process_env(world, rank).items():
                        if k not in os.environ:
                            env[k] = v
                logger.info(f"launching local rank {rank}")
                procs.append(subprocess.Popen(
                    build_cmd(args, rank, world, coord), env=env,
                    start_new_session=True))
            return procs

        sys.exit(supervise(spawn_local, args.max_restarts))

    hosts = fetch_hostfile(args.hostfile)
    hosts = parse_resource_filter(hosts, args.include, args.exclude)

    if len(hosts) <= 1 and not args.force_multi:
        env = dict(os.environ)
        env.setdefault("RANK", "0")
        env.setdefault("WORLD_SIZE", "1")
        os.execvpe(sys.executable, build_cmd(args, 0, 1, None), env)
        return

    host_list = list(hosts)
    last_failed: List[Optional[str]] = [None]

    def spawn_remote():
        # world/coordinator re-derive from the CURRENT host list — after
        # an elastic exclusion the job relaunches smaller
        world = len(host_list)
        coord_host = args.master_addr or host_list[0]
        coord = f"{coord_host}:{args.master_port}"
        procs = []
        for rank, host in enumerate(host_list):
            envs = (f"COORDINATOR_ADDRESS={shlex.quote(coord)} RANK={rank} "
                    f"WORLD_SIZE={world}")
            remote_cmd = f"cd {shlex.quote(os.getcwd())} && {envs} " \
                + " ".join(shlex.quote(c)
                           for c in build_cmd(args, rank, world, coord))
            if args.launcher == "pdsh":
                cmd = ["pdsh", "-w", host, remote_cmd]
            else:
                cmd = ["ssh", "-p", str(args.ssh_port), host, remote_cmd]
            logger.info(f"launching rank {rank} on {host}")
            # start_new_session so a failed job's ssh/pdsh trees die as a
            # group (babysit kills the group on first failure)
            procs.append(subprocess.Popen(cmd, start_new_session=True))
        return procs

    def note_failed(indices: List[int]):
        # exclusion must be UNAMBIGUOUS: a host crash whose collective
        # error has already felled other ranks within the same poll window
        # yields several failures — excluding any one of them risks
        # evicting a healthy host, so fall back to a plain restart
        last_failed[0] = host_list[indices[0]] if len(indices) == 1 else None

    def kill_remote_ranks():
        """Best-effort remote cleanup before a respawn: killing the local
        ssh/pdsh client does not reliably HUP the remote command (pdsh in
        particular), so ask each host to pkill the user script (reference
        multinode runner's remote-kill; pattern-scoped to this script).
        With ``--elastic_min_world``, the (sole) host whose rank died is
        EXCLUDED and the relaunch proceeds with a smaller world — the
        scale-down half of the reference's DSElasticAgent
        (elasticity/elastic_agent.py:28), restart-based because
        jax.distributed cannot re-rendezvous a changed world in-place."""
        # exclude FIRST: a genuinely dead host would hang its pkill ssh,
        # and the exclusion must not depend on the cleanup loop surviving
        dead = last_failed[0]
        last_failed[0] = None
        if (args.elastic_min_world and dead is not None
                and len(host_list) - 1 >= args.elastic_min_world):
            host_list.remove(dead)
            if args.master_addr == dead:
                # the pinned coordinator died with the host; fall back to
                # re-deriving it from the surviving host list
                logger.warning(
                    f"elastic scale-down: --master_addr {dead} is the "
                    f"excluded host; coordinator falls back to "
                    f"{host_list[0]}")
                args.master_addr = None
            logger.warning(
                f"elastic scale-down: excluding failed host {dead}; "
                f"relaunching with world={len(host_list)}")
        pattern = shlex.quote(args.user_script)
        for host in host_list:
            kill_cmd = (["pdsh", "-w", host] if args.launcher == "pdsh"
                        else ["ssh", "-p", str(args.ssh_port), host])
            try:
                subprocess.run(kill_cmd + [f"pkill -f {pattern} || true"],
                               timeout=30, capture_output=True)
            except subprocess.TimeoutExpired:
                logger.warning(f"remote cleanup on {host} timed out")

    sys.exit(supervise(spawn_remote, args.max_restarts,
                       between_attempts=kill_remote_ranks,
                       on_fail=note_failed))


if __name__ == "__main__":
    main()
