"""ZeRO-Infinity: parameter streaming — model bigger than HBM (+DRAM).

Counterpart of the reference's NVMe parameter swapping
(``runtime/swap_tensor/partitioned_param_swapper.py:36``
``AsyncPartitionedParameterSwapper`` + the stage-3 fetch/release hooks and
NVMe prefetch, ``partitioned_param_coordinator.py:503``): fp32 master
params and optimizer moments live on NVMe (or host DRAM); only a sliding
window of layer groups ever exists in device HBM.

The torch reference streams params through autograd hooks. A jitted
whole-model step can't do that — XLA would pin every param as a program
input — so the TPU-native design splits the *execution* instead:

- the stacked-layer CausalLM is cut into contiguous layer groups;
- forward walks the groups with one compiled ``group_fwd`` program (same
  shapes per group → one compile), double-buffered: a host thread pages
  group g+1's masters off NVMe into a reusable host buffer while the
  device computes group g (the reference's pinned-buffer prefetch,
  ``partitioned_param_swapper.py`` buffer pool);
- only group-boundary activations are kept; backward re-runs each group
  under ``jax.vjp`` in reverse (rematerialization — the streaming
  equivalent of activation checkpointing) and feeds each group's grads
  straight to the C++ SIMD host optimizer (ops/cpu_adam.py), whose
  masters/moments page back out to NVMe;
- device HBM therefore holds O(2 groups + boundary activations),
  independent of model depth.

This also supplies the ZeRO-Offload overlap story (round-2 weak #4): the
host optimizer for group g runs while the device computes group g-1's
backward.
"""

from __future__ import annotations

import concurrent.futures
import math
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import CausalLM
from ..ops.cpu_adam import DeepSpeedCPUAdam
from ..utils.logging import logger
from .swap_tensor.async_swapper import AsyncTensorSwapper


class _HostStore:
    """Per-group param/moment store: NVMe files via the aio swapper, or
    plain host arrays when device == 'cpu'. Counters prove streaming."""

    def __init__(self, device: str, nvme_path: Optional[str], n_threads: int):
        self.device = device
        self.reads = 0
        self.writes = 0
        self._mem: Dict[str, np.ndarray] = {}
        self._shapes: Dict[str, tuple] = {}
        self.swapper = None
        if device == "nvme":
            if not nvme_path:
                raise ValueError("offload_param.nvme_path required for NVMe")
            self.swapper = AsyncTensorSwapper(nvme_path)

    def put(self, key: str, arr: np.ndarray):
        self.writes += 1
        if self.swapper is not None:
            self._shapes[key] = (arr.shape, arr.dtype)
            self.swapper.swap_out(key, np.ascontiguousarray(arr))
            self.swapper.wait()
        else:
            self._mem[key] = np.array(arr, copy=True)

    def get(self, key: str, out: Optional[np.ndarray] = None) -> np.ndarray:
        self.reads += 1
        if self.swapper is not None:
            shape, dtype = self._shapes[key]
            buf = out if out is not None and out.shape == shape \
                else np.empty(shape, dtype)
            self.swapper.swap_in(key, buf)
            self.swapper.wait()
            return buf
        return self._mem[key]

    def close(self):
        if self.swapper is not None:
            self.swapper.close()


class ZeroInfinityEngine:
    """Streaming trainer for a CausalLM whose params exceed device memory.

    API subset of DeepSpeedTpuEngine: ``train_batch(batch) -> loss``,
    ``get_lr``. Constraints: stage-3 + offload_param config, untied
    embeddings, no dropout (deterministic groups), per-group grad
    clipping only.
    """

    def __init__(self, model: CausalLM, config, rng=None,
                 group_layers: Optional[int] = None):
        if model.cfg.tie_embeddings:
            raise ValueError("ZeRO-Infinity streaming requires "
                             "tie_embeddings=False (wte would need to be "
                             "resident for both embed and head groups)")
        self.module = model
        self.cfg = model.cfg
        self.config = config
        oc = config.zero_optimization.offload_param
        opt_cfg = config.optimizer
        kwargs = dict(opt_cfg.params if opt_cfg else {"lr": 1e-3})
        kwargs.pop("torch_adam", None)
        self.cpu_opt = DeepSpeedCPUAdam(adamw_mode=True, **kwargs)
        self.lr = float(kwargs.get("lr", 1e-3))
        self.store = _HostStore(str(oc.device.value), oc.nvme_path,
                                config.aio.thread_count)

        L = self.cfg.num_layers
        self.group_layers = group_layers or max(1, math.ceil(L / 4))
        self.groups: List[slice] = [
            slice(lo, min(lo + self.group_layers, L))
            for lo in range(0, L, self.group_layers)]

        # host-side init, leaf by leaf (the full model never exists on
        # device — zero.Init's promise, partition_parameters.py:734)
        rng = rng if rng is not None else jax.random.PRNGKey(config.seed)
        shapes = jax.eval_shape(model.init, rng)
        seedseq = np.random.SeedSequence(int(config.seed))
        self._layer_keys = sorted(shapes["layers"].keys())
        self.param_bytes = 0
        for gi, sl in enumerate(self.groups):
            for k in self._layer_keys:
                full = shapes["layers"][k]
                shape = (sl.stop - sl.start,) + tuple(full.shape[1:])
                arr = self._init_leaf(f"layers.{k}", shape, seedseq)
                self.store.put(f"layers.{k}.g{gi}", arr)
                self.store.put(f"opt_m.layers.{k}.g{gi}", np.zeros_like(arr))
                self.store.put(f"opt_v.layers.{k}.g{gi}", np.zeros_like(arr))
                self.param_bytes += arr.nbytes
        self._edge_params = {}   # embed/final_norm/lm_head stay resident
        for grp in ("embed", "final_norm", "lm_head"):
            if grp in shapes:
                self._edge_params[grp] = {
                    k: jnp.asarray(self._init_leaf(f"{grp}.{k}",
                                                   tuple(v.shape), seedseq))
                    for k, v in shapes[grp].items()}
        self._edge_m = jax.tree.map(np.zeros_like,
                                    jax.tree.map(np.asarray, self._edge_params))
        self._edge_v = jax.tree.map(np.zeros_like, self._edge_m)
        self.opt_step = 0
        self.global_steps = 0
        self._prefetch = concurrent.futures.ThreadPoolExecutor(1)
        self._build_programs()
        logger.info(
            f"ZeRO-Infinity: {len(self.groups)} groups × {self.group_layers} "
            f"layers, params {self.param_bytes / 1e6:.1f} MB on "
            f"{self.store.device}")

    def _init_leaf(self, name: str, shape, seedseq) -> np.ndarray:
        """Same init families as CausalLM.init (models/transformer.py:285):
        norm weights → 1, biases → 0, everything else (incl. lm_head.w,
        whose all-ones init would make dL/dx identically zero) → N(0, 0.02)."""
        rng = np.random.default_rng(seedseq.spawn(1)[0])
        if name.endswith("norm_w") or name == "final_norm.w":
            return np.ones(shape, np.float32)
        if name.endswith("_b") or name == "final_norm.b":
            return np.zeros(shape, np.float32)
        return (0.02 * rng.standard_normal(shape)).astype(np.float32)

    # ------------------------------------------------------------ programs
    def _build_programs(self):
        model = self.module
        cfg = self.cfg

        def group_fwd(gp, x, cos, sin):
            def body(carry, lp):
                y, _ = model._block(carry, lp, cos, sin,
                                    jax.random.PRNGKey(0), True)
                return y, None

            out, _ = jax.lax.scan(body, x, gp)
            return out

        def embed_fwd(ep, tokens, positions):
            x = ep["wte"][tokens].astype(cfg.dtype)
            if cfg.position == "learned":
                x = x + ep["wpe"][positions].astype(cfg.dtype)
            return x

        def head_loss(hp, x, labels):
            from ..models.transformer import _norm

            h = _norm(x, hp["w"], hp.get("b"), cfg.norm, cfg.norm_eps)
            logits = (h @ hp["lm_head_w"].astype(cfg.dtype)).astype(jnp.float32)
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, labels[..., None],
                                       axis=-1)[..., 0]
            return jnp.mean(logz - gold)

        self._group_fwd = jax.jit(group_fwd)
        self._group_bwd = jax.jit(
            lambda gp, x, cos, sin, dy: jax.vjp(
                lambda gp_, x_: group_fwd(gp_, x_, cos, sin), gp, x)[1](dy))
        self._embed_fwd = jax.jit(embed_fwd)
        self._embed_bwd = jax.jit(
            lambda ep, tokens, positions, dy: jax.vjp(
                lambda ep_: embed_fwd(ep_, tokens, positions), ep)[1](dy)[0])
        self._head_grad = jax.jit(jax.value_and_grad(head_loss, argnums=(0, 1)))

    # ------------------------------------------------------------- streaming
    def _load_group(self, gi: int) -> Dict[str, np.ndarray]:
        return {k: self.store.get(f"layers.{k}.g{gi}")
                for k in self._layer_keys}

    def _group_to_device(self, host_group):
        return {k: jnp.asarray(v) for k, v in host_group.items()}

    def _update_group(self, gi: int, host_group, dev_grads):
        """C++ host optimizer on one group's masters; page back out."""
        for k in self._layer_keys:
            g = np.ascontiguousarray(
                np.asarray(dev_grads[k], np.float32).reshape(-1))
            master = host_group[k].reshape(-1)
            m = self.store.get(f"opt_m.layers.{k}.g{gi}").reshape(-1)
            v = self.store.get(f"opt_v.layers.{k}.g{gi}").reshape(-1)
            # bias-correction counter synthesized from the engine step (one
            # shared counter; every leaf advances once per global step)
            st = {"m": m, "v": v,
                  "step": np.asarray([self.opt_step - 1], np.float32)}
            self.cpu_opt.step(master, g, st, lr=self.lr)
            self.store.put(f"layers.{k}.g{gi}", host_group[k])
            self.store.put(f"opt_m.layers.{k}.g{gi}",
                           m.reshape(host_group[k].shape))
            self.store.put(f"opt_v.layers.{k}.g{gi}",
                           v.reshape(host_group[k].shape))

    # ------------------------------------------------------------------ step
    def train_batch(self, batch) -> float:
        if isinstance(batch, dict):
            data = batch
        elif hasattr(batch, "__next__"):
            data = next(batch)
        else:
            # a fresh iter() each call would silently replay element 0
            raise TypeError(
                "train_batch expects a batch dict or an iterator; wrap "
                "lists/datasets in iter(...) so consumption is stateful")
        tokens = jnp.asarray(np.asarray(data["input_ids"]), jnp.int32)
        labels = tokens[:, 1:]
        tokens = tokens[:, :-1]
        B, T = tokens.shape
        positions = jnp.arange(T)
        cos, sin = self.module._pos_tables(T, None)
        self.opt_step += 1

        # ---- forward sweep: double-buffered group streaming
        x = self._embed_fwd(self._edge_params["embed"], tokens, positions)
        boundary = [x]
        fut = self._prefetch.submit(self._load_group, 0)
        for gi in range(len(self.groups)):
            host_group = fut.result()
            if gi + 1 < len(self.groups):          # prefetch next while we run
                fut = self._prefetch.submit(self._load_group, gi + 1)
            gp = self._group_to_device(host_group)
            x = self._group_fwd(gp, x, cos, sin)
            boundary.append(x)
            del gp

        # ---- head loss + backward seed
        hp = dict(self._edge_params["final_norm"],
                  lm_head_w=self._edge_params["lm_head"]["w"])
        (loss, (dhp, dx)) = self._head_grad(hp, boundary[-1], labels)

        # ---- backward sweep (recompute per group), host opt overlapped
        fut = self._prefetch.submit(self._load_group, len(self.groups) - 1)
        pending_update = None
        for gi in reversed(range(len(self.groups))):
            host_group = fut.result()
            if gi - 1 >= 0:
                fut = self._prefetch.submit(self._load_group, gi - 1)
            gp = self._group_to_device(host_group)
            dgp, dx = self._group_bwd(gp, boundary[gi], cos, sin, dx)
            dgp_host = {k: np.asarray(v) for k, v in dgp.items()}
            if pending_update is not None:
                pending_update.result()
            pending_update = self._prefetch.submit(
                self._update_group, gi, host_group, dgp_host)
            del gp, dgp
        if pending_update is not None:
            pending_update.result()

        # ---- resident edge params update (embed + head) on host
        d_embed = self._embed_bwd(self._edge_params["embed"], tokens,
                                  positions, dx)
        self._apply_edge("embed", d_embed)
        self._apply_edge_head(dhp)
        self.global_steps += 1
        return float(loss)

    def _apply_edge(self, grp: str, grads):
        for k, g in grads.items():
            p = np.asarray(self._edge_params[grp][k], np.float32).reshape(-1)
            self.cpu_opt.step(p, np.ascontiguousarray(
                np.asarray(g, np.float32).reshape(-1)),
                {"m": self._edge_m[grp][k].reshape(-1),
                 "v": self._edge_v[grp][k].reshape(-1),
                 "step": np.asarray([self.opt_step - 1], np.float32)},
                lr=self.lr)
            self._edge_params[grp][k] = jnp.asarray(
                p.reshape(self._edge_params[grp][k].shape))

    def _apply_edge_head(self, dhp):
        fn_grads = {k: v for k, v in dhp.items() if k != "lm_head_w"}
        self._apply_edge("final_norm", fn_grads)
        self._apply_edge("lm_head", {"w": dhp["lm_head_w"]})

    def get_lr(self):
        return [self.lr]

    def close(self):
        self._prefetch.shutdown(wait=True)
        self.store.close()
