"""ZeRO-Infinity: parameter streaming — model bigger than HBM (+DRAM).

Counterpart of the reference's NVMe parameter swapping
(``runtime/swap_tensor/partitioned_param_swapper.py:36``
``AsyncPartitionedParameterSwapper`` + the stage-3 fetch/release hooks and
NVMe prefetch, ``partitioned_param_coordinator.py:503``): fp32 master
params and optimizer moments live on NVMe (or host DRAM); only a sliding
window of layer groups ever exists in device HBM.

The torch reference streams params through autograd hooks. A jitted
whole-model step can't do that — XLA would pin every param as a program
input — so the TPU-native design splits the *execution* instead:

- the stacked-layer CausalLM is cut into contiguous layer groups;
- forward walks the groups with one compiled ``group_fwd`` program (same
  shapes per group → one compile), double-buffered: a host thread pages
  group g+1's masters off NVMe into a reusable host buffer while the
  device computes group g (the reference's pinned-buffer prefetch,
  ``partitioned_param_swapper.py`` buffer pool);
- only group-boundary activations are kept; backward re-runs each group
  under ``jax.vjp`` in reverse (rematerialization — the streaming
  equivalent of activation checkpointing) and feeds each group's grads
  straight to the C++ SIMD host optimizer (ops/cpu_adam.py), whose
  masters/moments page back out to NVMe;
- device HBM therefore holds O(2 groups + boundary activations),
  independent of model depth.

This also supplies the ZeRO-Offload overlap story (round-2 weak #4): the
host optimizer for group g runs while the device computes group g-1's
backward.
"""

from __future__ import annotations

import concurrent.futures
import math
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import CausalLM
from ..ops.cpu_adam import DeepSpeedCPUAdam
from ..utils.logging import logger
from .swap_tensor.async_swapper import AsyncTensorSwapper


class _HostStore:
    """Per-group param/moment store: NVMe files via the aio swapper, or
    plain host arrays when device == 'cpu'. Counters prove streaming —
    ``bytes_read`` lets tests assert that a mesh-sharded engine pages only
    its 1/F-sized shards, never whole leaves."""

    def __init__(self, device: str, nvme_path: Optional[str], n_threads: int):
        self.device = device
        self.reads = 0
        self.writes = 0
        self.bytes_read = 0
        self.read_keys: set = set()
        self._mem: Dict[str, np.ndarray] = {}
        self._shapes: Dict[str, tuple] = {}
        self.swapper = None
        if device == "nvme":
            if not nvme_path:
                raise ValueError("offload_param.nvme_path required for NVMe")
            self.swapper = AsyncTensorSwapper(nvme_path)

    def put(self, key: str, arr: np.ndarray):
        self.writes += 1
        if self.swapper is not None:
            self._shapes[key] = (arr.shape, arr.dtype)
            self.swapper.swap_out(key, np.ascontiguousarray(arr))
            self.swapper.wait()
        else:
            self._mem[key] = np.array(arr, copy=True)

    def get(self, key: str, out: Optional[np.ndarray] = None) -> np.ndarray:
        self.reads += 1
        self.read_keys.add(key)
        if self.swapper is not None:
            shape, dtype = self._shapes[key]
            buf = out if out is not None and out.shape == shape \
                else np.empty(shape, dtype)
            self.swapper.swap_in(key, buf)
            self.swapper.wait()
            self.bytes_read += buf.nbytes
            return buf
        arr = self._mem[key]
        self.bytes_read += arr.nbytes
        return arr

    def close(self):
        if self.swapper is not None:
            self.swapper.close()


class ZeroInfinityEngine:
    """Streaming trainer for a CausalLM whose params exceed device memory.

    API subset of DeepSpeedTpuEngine: ``train_batch(batch) -> loss``,
    ``get_lr``. Constraints: stage-3 + offload_param config, untied
    embeddings, no dropout (deterministic groups), per-group grad
    clipping only.
    """

    def __init__(self, model: CausalLM, config, rng=None,
                 group_layers: Optional[int] = None, mesh=None):
        if model.cfg.tie_embeddings:
            raise ValueError("ZeRO-Infinity streaming requires "
                             "tie_embeddings=False (wte would need to be "
                             "resident for both embed and head groups)")
        if len(model.cfg.window_segments()) > 1:
            raise ValueError(
                "ZeRO-Infinity streaming requires a uniform sliding_window: "
                "the group walk runs ONE compiled group_fwd program over "
                "every layer group, so a mixed per-layer window schedule "
                "cannot be baked in statically")
        self.module = model
        self.cfg = model.cfg
        self.config = config
        oc = config.zero_optimization.offload_param
        opt_cfg = config.optimizer
        kwargs = dict(opt_cfg.params if opt_cfg else {"lr": 1e-3})
        kwargs.pop("torch_adam", None)
        self.cpu_opt = DeepSpeedCPUAdam(adamw_mode=True, **kwargs)
        self.lr = float(kwargs.get("lr", 1e-3))
        self.store = _HostStore(str(oc.device.value), oc.nvme_path,
                                config.aio.thread_count)

        # Mesh composition (round-4: the reference's NVMe swap runs *under*
        # ZeRO-3 sharding — stage3.py:72 + partitioned_param_swapper.py:36
        # swap per-rank partitions): the device-resident layer group is
        # sharded over the ``fsdp`` axis and the batch over ``data``; the
        # host store holds per-shard files so each process pages only its
        # own 1/F of every leaf, and the host optimizer steps per shard.
        self.mesh = mesh
        self.fsdp = int(mesh.shape["fsdp"]) if mesh is not None else 1
        self.dp = int(mesh.shape["data"]) if mesh is not None else 1

        L = self.cfg.num_layers
        self.group_layers = group_layers or max(1, math.ceil(L / 4))
        self.groups: List[slice] = [
            slice(lo, min(lo + self.group_layers, L))
            for lo in range(0, L, self.group_layers)]

        # host-side init, leaf by leaf (the full model never exists on
        # device — zero.Init's promise, partition_parameters.py:734)
        rng = rng if rng is not None else jax.random.PRNGKey(config.seed)
        shapes = jax.eval_shape(model.init, rng)
        seedseq = np.random.SeedSequence(int(config.seed))
        self._layer_keys = sorted(shapes["layers"].keys())
        self._shard_axis = {
            k: self._pick_shard_axis(tuple(shapes["layers"][k].shape[1:]))
            for k in self._layer_keys}
        self.param_bytes = 0
        for gi, sl in enumerate(self.groups):
            for k in self._layer_keys:
                full = shapes["layers"][k]
                shape = (sl.stop - sl.start,) + tuple(full.shape[1:])
                arr = self._init_leaf(f"layers.{k}", shape, seedseq)
                for key, piece in self._shards(f"layers.{k}.g{gi}", k, arr):
                    self.store.put(key, piece)
                    self.store.put(f"opt_m.{key}", np.zeros_like(piece))
                    self.store.put(f"opt_v.{key}", np.zeros_like(piece))
                self.param_bytes += arr.nbytes
        self._edge_params = {}   # embed/final_norm/lm_head stay resident
        for grp in ("embed", "final_norm", "lm_head"):
            if grp in shapes:
                self._edge_params[grp] = {
                    k: self._replicate(self._init_leaf(f"{grp}.{k}",
                                                       tuple(v.shape),
                                                       seedseq))
                    for k, v in shapes[grp].items()}
        self._edge_m = jax.tree.map(np.zeros_like,
                                    jax.tree.map(np.asarray, self._edge_params))
        self._edge_v = jax.tree.map(np.zeros_like, self._edge_m)
        self.opt_step = 0
        self.global_steps = 0
        self._prefetch = concurrent.futures.ThreadPoolExecutor(1)
        self._build_programs()
        logger.info(
            f"ZeRO-Infinity: {len(self.groups)} groups × {self.group_layers} "
            f"layers, params {self.param_bytes / 1e6:.1f} MB on "
            f"{self.store.device}"
            + (f", sharded fsdp={self.fsdp} × data={self.dp}"
               if mesh is not None else ""))

    # ------------------------------------------------------- mesh sharding
    def _pick_shard_axis(self, rest_shape) -> Optional[int]:
        """Absolute axis (>=1; 0 is the stacked-layer dim) along which a
        layer leaf is split over fsdp — the largest dim divisible by F.
        None → leaf replicated (small norm weights/biases)."""
        if self.fsdp <= 1:
            return None
        best = None
        for d, extent in enumerate(rest_shape):
            if extent % self.fsdp == 0 and extent >= self.fsdp:
                if best is None or extent > rest_shape[best - 1]:
                    best = d + 1
        return best

    def _shards(self, base_key: str, leaf_key: str, arr: np.ndarray):
        """Yield (store key, host piece) pairs — one per fsdp shard for
        sharded leaves, a single full copy for replicated ones."""
        ax = self._shard_axis[leaf_key]
        if ax is None:
            yield base_key, arr
            return
        for si, piece in enumerate(np.split(arr, self.fsdp, axis=ax)):
            yield f"{base_key}.s{si}", np.ascontiguousarray(piece)

    def _leaf_sharding(self, leaf_key: str):
        from jax.sharding import NamedSharding, PartitionSpec as P

        ax = self._shard_axis[leaf_key]
        if ax is None:
            return NamedSharding(self.mesh, P())
        parts = [None] * (ax + 1)
        parts[ax] = "fsdp"
        return NamedSharding(self.mesh, P(*parts))

    def _data_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P("data"))

    def _repl_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def _replicate(self, arr):
        """Edge params live replicated on every mesh device."""
        if self.mesh is None:
            return jnp.asarray(arr)
        return jax.device_put(arr, self._repl_sharding())

    def _init_leaf(self, name: str, shape, seedseq) -> np.ndarray:
        """Same init families as CausalLM.init (models/transformer.py:285):
        norm weights → 1, biases → 0, everything else (incl. lm_head.w,
        whose all-ones init would make dL/dx identically zero) → N(0, 0.02)."""
        rng = np.random.default_rng(seedseq.spawn(1)[0])
        if name.endswith("norm_w") or name == "final_norm.w":
            return np.ones(shape, np.float32)
        if name.endswith("_b") or name == "final_norm.b":
            return np.zeros(shape, np.float32)
        return (0.02 * rng.standard_normal(shape)).astype(np.float32)

    # ------------------------------------------------------------ programs
    def _build_programs(self):
        model = self.module
        cfg = self.cfg

        # uniform across layers (mixed schedules rejected in __init__),
        # so the one shared group_fwd program bakes it in statically
        window = cfg.layer_windows()[0]

        def group_fwd(gp, x, cos, sin):
            def body(carry, lp):
                y, _ = model._block(carry, lp, cos, sin,
                                    jax.random.PRNGKey(0), True, window)
                return y, None

            out, _ = jax.lax.scan(body, x, gp)
            return out

        def embed_fwd(ep, tokens, positions):
            x = ep["wte"][tokens].astype(cfg.dtype)
            if cfg.position == "learned":
                x = x + ep["wpe"][positions].astype(cfg.dtype)
            return x

        def head_loss(hp, x, labels):
            from ..models.transformer import _norm

            h = _norm(x, hp["w"], hp.get("b"), cfg.norm, cfg.norm_eps)
            logits = (h @ hp["lm_head_w"].astype(cfg.dtype)).astype(jnp.float32)
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, labels[..., None],
                                       axis=-1)[..., 0]
            return jnp.mean(logz - gold)

        group_bwd = lambda gp, x, cos, sin, dy: jax.vjp(      # noqa: E731
            lambda gp_, x_: group_fwd(gp_, x_, cos, sin), gp, x)[1](dy)
        embed_bwd = lambda ep, tokens, positions, dy: jax.vjp(  # noqa: E731
            lambda ep_: embed_fwd(ep_, tokens, positions), ep)[1](dy)[0]
        head_grad = jax.value_and_grad(head_loss, argnums=(0, 1))

        if self.mesh is None:
            self._group_fwd = jax.jit(group_fwd)
            self._group_bwd = jax.jit(group_bwd)
            self._embed_fwd = jax.jit(embed_fwd)
            self._embed_bwd = jax.jit(embed_bwd)
            self._head_grad = jax.jit(head_grad)
            return

        # Mesh mode: activations ride the data axis, param grads land
        # reduce-scattered onto their fsdp shards, edge grads land
        # replicated (GSPMD inserts the data-axis psum / reduce-scatter to
        # satisfy the out_shardings — the ZeRO-3 grad flow).
        data_s = self._data_sharding()
        repl_s = self._repl_sharding()
        gp_s = {k: self._leaf_sharding(k) for k in self._layer_keys}
        self._group_fwd = jax.jit(group_fwd, out_shardings=data_s)
        self._group_bwd = jax.jit(group_bwd, out_shardings=(gp_s, data_s))
        self._embed_fwd = jax.jit(embed_fwd, out_shardings=data_s)
        self._embed_bwd = jax.jit(embed_bwd, out_shardings=repl_s)
        self._head_grad = jax.jit(
            head_grad,
            out_shardings=(repl_s, (repl_s, data_s)))

    # ------------------------------------------------------------- streaming
    def _local_shards(self, leaf_key: str):
        """Shard indices this process pages for a leaf: all of them in a
        single-process mesh; only the fsdp coordinates of local devices in
        a multi-process one (per-host paging of per-host shards)."""
        if self.mesh is None or self._shard_axis[leaf_key] is None:
            return [None]
        if not hasattr(self, "_local_sis"):
            # invariant for the engine's lifetime — computed once
            fa = list(self.mesh.axis_names).index("fsdp")
            self._local_sis = sorted(
                {int(np.argwhere(self.mesh.devices == d)[0][fa])
                 for d in self.mesh.local_devices})
        return self._local_sis

    def _key(self, k: str, gi: int, si) -> str:
        base = f"layers.{k}.g{gi}"
        return base if si is None else f"{base}.s{si}"

    def _load_group(self, gi: int) -> Dict[str, Dict]:
        """Page one group's masters off the store — per fsdp shard."""
        return {k: {si: self.store.get(self._key(k, gi, si))
                    for si in self._local_shards(k)}
                for k in self._layer_keys}

    def _group_to_device(self, host_group):
        if self.mesh is None:
            # single-device: the inner dict is {None: full_leaf}
            return {k: jnp.asarray(shards[None])
                    for k, shards in host_group.items()}
        out = {}
        for k, shards in host_group.items():
            ax = self._shard_axis[k]
            if ax is None:
                out[k] = jax.device_put(shards[None], self._repl_sharding())
                continue
            some = next(iter(shards.values()))
            full = list(some.shape)
            full[ax] *= self.fsdp
            shard_len = some.shape[ax]

            def cb(idx, shards=shards, ax=ax, shard_len=shard_len):
                si = (idx[ax].start or 0) // shard_len
                return shards[si]

            out[k] = jax.make_array_from_callback(
                tuple(full), self._leaf_sharding(k), cb)
        return out

    def _grads_to_host(self, dgp) -> Dict[str, Dict]:
        """Per-shard host grads: {leaf: {si: np}} — each process touches
        only its addressable shards (grads arrive fsdp-sharded and already
        data-reduced, per the out_shardings)."""
        out = {}
        for k in self._layer_keys:
            g = dgp[k]
            ax = self._shard_axis[k]
            if self.mesh is None or ax is None:
                out[k] = {None: np.asarray(g, np.float32)}
                continue
            shard_len = g.shape[ax] // self.fsdp
            d = {}
            for sh in g.addressable_shards:
                si = (sh.index[ax].start or 0) // shard_len
                if si not in d:
                    d[si] = np.asarray(sh.data, np.float32)
            out[k] = d
        return out

    def _update_group(self, gi: int, host_group, dev_grads):
        """C++ host optimizer on one group's master shards; page back out."""
        for k in self._layer_keys:
            for si, master_arr in host_group[k].items():
                key = self._key(k, gi, si)
                g = np.ascontiguousarray(
                    dev_grads[k][si].reshape(-1))
                master = master_arr.reshape(-1)
                m = self.store.get(f"opt_m.{key}").reshape(-1)
                v = self.store.get(f"opt_v.{key}").reshape(-1)
                # bias-correction counter synthesized from the engine step
                # (one shared counter; every leaf advances once per step)
                st = {"m": m, "v": v,
                      "step": np.asarray([self.opt_step - 1], np.float32)}
                self.cpu_opt.step(master, g, st, lr=self.lr)
                self.store.put(key, master_arr)
                self.store.put(f"opt_m.{key}", m.reshape(master_arr.shape))
                self.store.put(f"opt_v.{key}", v.reshape(master_arr.shape))

    # ------------------------------------------------------------------ step
    def train_batch(self, batch) -> float:
        if isinstance(batch, dict):
            data = batch
        elif hasattr(batch, "__next__"):
            data = next(batch)
        else:
            # a fresh iter() each call would silently replay element 0
            raise TypeError(
                "train_batch expects a batch dict or an iterator; wrap "
                "lists/datasets in iter(...) so consumption is stateful")
        host_tokens = np.asarray(data["input_ids"])
        labels_np = host_tokens[:, 1:]
        tokens_np = host_tokens[:, :-1]
        B, T = tokens_np.shape
        if self.mesh is None:
            tokens = jnp.asarray(tokens_np, jnp.int32)
            labels = jnp.asarray(labels_np, jnp.int32)
        else:
            if B % self.dp != 0:
                raise ValueError(f"batch {B} not divisible by data axis "
                                 f"{self.dp}")
            ds = self._data_sharding()
            tokens = jax.device_put(tokens_np.astype(np.int32), ds)
            labels = jax.device_put(labels_np.astype(np.int32), ds)
        positions = jnp.arange(T)
        cos, sin = self.module._pos_tables(T, None)
        self.opt_step += 1

        # ---- forward sweep: double-buffered group streaming
        x = self._embed_fwd(self._edge_params["embed"], tokens, positions)
        boundary = [x]
        fut = self._prefetch.submit(self._load_group, 0)
        for gi in range(len(self.groups)):
            host_group = fut.result()
            if gi + 1 < len(self.groups):          # prefetch next while we run
                fut = self._prefetch.submit(self._load_group, gi + 1)
            gp = self._group_to_device(host_group)
            x = self._group_fwd(gp, x, cos, sin)
            boundary.append(x)
            del gp

        # ---- head loss + backward seed
        hp = dict(self._edge_params["final_norm"],
                  lm_head_w=self._edge_params["lm_head"]["w"])
        (loss, (dhp, dx)) = self._head_grad(hp, boundary[-1], labels)

        # ---- backward sweep (recompute per group), host opt overlapped
        fut = self._prefetch.submit(self._load_group, len(self.groups) - 1)
        pending_update = None
        for gi in reversed(range(len(self.groups))):
            host_group = fut.result()
            if gi - 1 >= 0:
                fut = self._prefetch.submit(self._load_group, gi - 1)
            gp = self._group_to_device(host_group)
            dgp, dx = self._group_bwd(gp, boundary[gi], cos, sin, dx)
            dgp_host = self._grads_to_host(dgp)
            if pending_update is not None:
                pending_update.result()
            pending_update = self._prefetch.submit(
                self._update_group, gi, host_group, dgp_host)
            del gp, dgp
        if pending_update is not None:
            pending_update.result()

        # ---- resident edge params update (embed + head) on host
        d_embed = self._embed_bwd(self._edge_params["embed"], tokens,
                                  positions, dx)
        self._apply_edge("embed", d_embed)
        self._apply_edge_head(dhp)
        self.global_steps += 1
        return float(loss)

    def _apply_edge(self, grp: str, grads):
        for k, g in grads.items():
            p = np.asarray(self._edge_params[grp][k], np.float32).reshape(-1)
            self.cpu_opt.step(p, np.ascontiguousarray(
                np.asarray(g, np.float32).reshape(-1)),
                {"m": self._edge_m[grp][k].reshape(-1),
                 "v": self._edge_v[grp][k].reshape(-1),
                 "step": np.asarray([self.opt_step - 1], np.float32)},
                lr=self.lr)
            self._edge_params[grp][k] = self._replicate(
                p.reshape(self._edge_params[grp][k].shape))

    def _apply_edge_head(self, dhp):
        fn_grads = {k: v for k, v in dhp.items() if k != "lm_head_w"}
        self._apply_edge("final_norm", fn_grads)
        self._apply_edge("lm_head", {"w": dhp["lm_head_w"]})

    def get_lr(self):
        return [self.lr]

    def streaming_report(self) -> Dict[str, Any]:
        """Quantify the streaming-vs-resident trade (r3 weak #3): paging
        volume, measured I/O counters, and the recompute factor the
        grouped-vjp backward pays (each group's forward runs twice — the
        activation-checkpointing 4/3-step-FLOPs factor, reference
        partitioned_param_coordinator prefetch trades the same way)."""
        steps = max(self.global_steps, 1)
        return {
            "param_bytes": self.param_bytes,
            "groups": len(self.groups),
            "fsdp": self.fsdp,
            "data": self.dp,
            "store_device": self.store.device,
            "bytes_read_total": self.store.bytes_read,
            "bytes_read_per_step": self.store.bytes_read // steps,
            # fwd params once + bwd params again + both moments ≈ 4x
            "expected_bytes_per_step": 4 * self.param_bytes,
            "reads_per_step": self.store.reads // steps,
            # grouped-vjp backward recomputes each group's forward: step
            # FLOPs are ~8ND vs the resident engine's 6ND
            "recompute_flops_factor": 8 / 6,
        }

    def close(self):
        self._prefetch.shutdown(wait=True)
        self.store.close()
