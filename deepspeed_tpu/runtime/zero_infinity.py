"""ZeRO-Infinity: parameter streaming — model bigger than HBM (+DRAM).

Counterpart of the reference's NVMe parameter swapping
(``runtime/swap_tensor/partitioned_param_swapper.py:36``
``AsyncPartitionedParameterSwapper`` + the stage-3 fetch/release hooks and
NVMe prefetch, ``partitioned_param_coordinator.py:503``): fp32 master
params and optimizer moments live on NVMe (or host DRAM); only a sliding
window of layer groups ever exists in device HBM.

The torch reference streams params through autograd hooks. A jitted
whole-model step can't do that — XLA would pin every param as a program
input — so the TPU-native design splits the *execution* instead:

- the stacked-layer CausalLM is cut into contiguous layer groups;
- forward walks the groups with one compiled ``group_fwd`` program (same
  shapes per group → one compile), double-buffered: a host thread pages
  group g+1's masters off NVMe into a reusable host buffer while the
  device computes group g (the reference's pinned-buffer prefetch,
  ``partitioned_param_swapper.py`` buffer pool);
- only group-boundary activations are kept; backward re-runs each group
  under ``jax.vjp`` in reverse (rematerialization — the streaming
  equivalent of activation checkpointing) and feeds each group's grads
  straight to the C++ SIMD host optimizer (ops/cpu_adam.py), whose
  masters/moments page back out to NVMe;
- device HBM therefore holds O(2 groups + boundary activations),
  independent of model depth.

This also supplies the ZeRO-Offload overlap story (round-2 weak #4): the
host optimizer for group g runs while the device computes group g-1's
backward.
"""

from __future__ import annotations

import concurrent.futures
import math
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import CausalLM
from ..ops.cpu_adam import DeepSpeedCPUAdam
from ..utils.logging import logger
from .swap_tensor.async_swapper import AsyncTensorSwapper


class _HostStore:
    """Per-group param/moment store: NVMe files via the aio swapper, or
    plain host arrays when device == 'cpu'. Counters prove streaming —
    ``bytes_read`` lets tests assert that a mesh-sharded engine pages only
    its 1/F-sized shards, never whole leaves."""

    def __init__(self, device: str, nvme_path: Optional[str], n_threads: int):
        self.device = device
        self.reads = 0
        self.writes = 0
        self.flushes = 0
        self.bytes_read = 0
        self.read_keys: set = set()
        self._mem: Dict[str, np.ndarray] = {}
        self._shapes: Dict[str, tuple] = {}
        # in-flight async swap_outs: (key, buffer) pairs kept ALIVE until
        # flush() — the aio engine writes from the caller's memory, so
        # dropping the array before wait() would hand it freed pages
        self._pending: List[tuple] = []
        self.swapper = None
        self._read_swapper = None
        if device == "nvme":
            if not nvme_path:
                raise ValueError("offload_param.nvme_path required for NVMe")
            self.swapper = AsyncTensorSwapper(nvme_path)
            # reads get their OWN aio handle: a read's completing wait()
            # on a shared handle would drain every in-flight write too,
            # re-serializing the writes the group-boundary batching just
            # overlapped (the per-shard opt_m/opt_v reads interleave
            # with the previous shard's writes)
            self._read_swapper = AsyncTensorSwapper(nvme_path)

    def put(self, key: str, arr: np.ndarray):
        """Queue one array for NVMe (async): the write is dispatched and
        the buffer parked in ``_pending``; the single ``swapper.wait()``
        happens at the group boundary (:meth:`flush`) so a group's N
        writes overlap compute instead of each serializing against it.
        The caller must not mutate ``arr`` until the next flush."""
        self.writes += 1
        if self.swapper is not None:
            self._shapes[key] = (arr.shape, arr.dtype)
            buf = np.ascontiguousarray(arr)
            self.swapper.swap_out(key, buf)
            self._pending.append((key, buf))
        else:
            self._mem[key] = np.array(arr, copy=True)

    def flush(self):
        """Group-boundary barrier: one ``wait()`` for every in-flight
        swap_out, then release the kept-alive buffers. No-op with
        nothing pending (and on the host-RAM store)."""
        self.flushes += 1
        if self.swapper is not None and self._pending:
            self.swapper.wait()
        self._pending.clear()

    def get(self, key: str, out: Optional[np.ndarray] = None) -> np.ndarray:
        self.reads += 1
        self.read_keys.add(key)
        if self.swapper is not None:
            if any(k == key for k, _ in self._pending):
                # read-after-write: the file must be complete before the
                # pread — settle every in-flight write first
                self.flush()
            shape, dtype = self._shapes[key]
            buf = out if out is not None and out.shape == shape \
                else np.empty(shape, dtype)
            self._read_swapper.swap_in(key, buf)
            self._read_swapper.wait()
            self.bytes_read += buf.nbytes
            return buf
        arr = self._mem[key]
        self.bytes_read += arr.nbytes
        return arr

    def close(self):
        if self.swapper is not None:
            self.flush()
            self.swapper.close()
        if self._read_swapper is not None:
            self._read_swapper.close()


class ZeroInfinityEngine:
    """Streaming trainer for a CausalLM whose params exceed device memory.

    API subset of DeepSpeedTpuEngine: ``train_batch(batch) -> loss``
    (``gradient_accumulation_steps`` micro batches per call — grads
    accumulate in store-backed buffers, r5), ``get_lr``. Edge params
    (embed / final_norm / lm_head) stream through the store per fsdp
    shard like layer groups (r5). Constraints: stage-3 + offload_param
    config, untied embeddings, no dropout (deterministic groups), no
    lm_head bias / embedding LayerNorm, uniform sliding window,
    per-group grad clipping only.
    """

    def __init__(self, model: CausalLM, config, rng=None,
                 group_layers: Optional[int] = None, mesh=None):
        if model.cfg.tie_embeddings:
            raise ValueError("ZeRO-Infinity streaming requires "
                             "tie_embeddings=False (wte would need to be "
                             "resident for both embed and head groups)")
        if len(model.cfg.window_segments()) > 1:
            raise ValueError(
                "ZeRO-Infinity streaming requires a uniform sliding_window: "
                "the group walk runs ONE compiled group_fwd program over "
                "every layer group, so a mixed per-layer window schedule "
                "cannot be baked in statically")
        if model.cfg.embedding_layernorm:
            raise ValueError(
                "ZeRO-Infinity streaming does not apply embedding_layernorm "
                "(BLOOM family); loading such a model would silently skip "
                "the norm")
        if not model.cfg.tie_embeddings and model.cfg.lm_head_bias:
            raise ValueError(
                "ZeRO-Infinity streaming's head program carries no lm_head "
                "bias; rejecting rather than silently dropping it")
        self.module = model
        self.cfg = model.cfg
        self.config = config
        oc = config.zero_optimization.offload_param
        opt_cfg = config.optimizer
        kwargs = dict(opt_cfg.params if opt_cfg else {"lr": 1e-3})
        kwargs.pop("torch_adam", None)
        self.cpu_opt = DeepSpeedCPUAdam(adamw_mode=True, **kwargs)
        self.lr = float(kwargs.get("lr", 1e-3))
        self.store = _HostStore(str(oc.device.value), oc.nvme_path,
                                config.aio.thread_count)

        # Mesh composition (round-4: the reference's NVMe swap runs *under*
        # ZeRO-3 sharding — stage3.py:72 + partitioned_param_swapper.py:36
        # swap per-rank partitions): the device-resident layer group is
        # sharded over the ``fsdp`` axis and the batch over ``data``; the
        # host store holds per-shard files so each process pages only its
        # own 1/F of every leaf, and the host optimizer steps per shard.
        self.mesh = mesh
        self.fsdp = int(mesh.shape["fsdp"]) if mesh is not None else 1
        self.dp = int(mesh.shape["data"]) if mesh is not None else 1

        L = self.cfg.num_layers
        self.group_layers = group_layers or max(1, math.ceil(L / 4))
        self.groups: List[slice] = [
            slice(lo, min(lo + self.group_layers, L))
            for lo in range(0, L, self.group_layers)]

        # host-side init, leaf by leaf (the full model never exists on
        # device — zero.Init's promise, partition_parameters.py:734)
        rng = rng if rng is not None else jax.random.PRNGKey(config.seed)
        shapes = jax.eval_shape(model.init, rng)
        seedseq = np.random.SeedSequence(int(config.seed))
        self._layer_keys = sorted(shapes["layers"].keys())
        self._shard_axis = {
            k: self._pick_shard_axis(tuple(shapes["layers"][k].shape[1:]))
            for k in self._layer_keys}
        self.param_bytes = 0
        for gi, sl in enumerate(self.groups):
            for k in self._layer_keys:
                full = shapes["layers"][k]
                shape = (sl.stop - sl.start,) + tuple(full.shape[1:])
                arr = self._init_leaf(f"layers.{k}", shape, seedseq)
                for key, piece in self._shards(f"layers.{k}.g{gi}", k, arr):
                    self.store.put(key, piece)
                    self.store.put(f"opt_m.{key}", np.zeros_like(piece))
                    self.store.put(f"opt_v.{key}", np.zeros_like(piece))
                self.param_bytes += arr.nbytes
                # per-leaf flush: async batching must not pin ~3x the
                # whole model (param + both moments of EVERY leaf) in
                # host RAM at once during init
                self.store.flush()
        # Edge params (embed / final_norm / lm_head) stream through the
        # store like layer groups (r5 — the r4 design held them resident,
        # replicated fp32, with a dense host-Adam pass every step; for a
        # 70B that is ~1B params of permanent edge state per host. The
        # reference swaps these too: partitioned_param_swapper.py:36
        # swaps EVERY partitioned param, not just blocks.)
        self._edge_keys: Dict[str, List[str]] = {}
        self._edge_axis: Dict[tuple, Optional[int]] = {}
        self._edge_bytes = 0
        for grp in ("embed", "final_norm", "lm_head"):
            if grp not in shapes:
                continue
            self._edge_keys[grp] = sorted(shapes[grp].keys())
            for k in self._edge_keys[grp]:
                shape = tuple(shapes[grp][k].shape)
                self._edge_axis[(grp, k)] = self._pick_axis(shape, offset=0)
                arr = self._init_leaf(f"{grp}.{k}", shape, seedseq)
                for key, piece in self._edge_shards(grp, k, arr):
                    self.store.put(key, piece)
                    self.store.put(f"opt_m.{key}", np.zeros_like(piece))
                    self.store.put(f"opt_v.{key}", np.zeros_like(piece))
                self.param_bytes += arr.nbytes
                self._edge_bytes += arr.nbytes
                self.store.flush()          # per-leaf, as above
        self.store.flush()          # settle any straggler init writes
        self.opt_step = 0
        self.global_steps = 0
        self._prefetch = concurrent.futures.ThreadPoolExecutor(1)
        self._build_programs()
        logger.info(
            f"ZeRO-Infinity: {len(self.groups)} groups × {self.group_layers} "
            f"layers, params {self.param_bytes / 1e6:.1f} MB on "
            f"{self.store.device}"
            + (f", sharded fsdp={self.fsdp} × data={self.dp}"
               if mesh is not None else ""))

    # ------------------------------------------------------- mesh sharding
    def _pick_axis(self, shape, offset: int = 0) -> Optional[int]:
        """Axis along which a leaf is split over fsdp — the largest dim
        divisible by F, offset by ``offset`` (1 for stacked layer leaves:
        axis 0 is the layer dim). None → leaf replicated (small norms)."""
        if self.fsdp <= 1:
            return None
        best = None
        for d, extent in enumerate(shape):
            if extent % self.fsdp == 0 and extent >= self.fsdp:
                if best is None or extent > shape[best - offset]:
                    best = d + offset
        return best

    def _pick_shard_axis(self, rest_shape) -> Optional[int]:
        return self._pick_axis(rest_shape, offset=1)

    def _shards(self, base_key: str, leaf_key: str, arr: np.ndarray):
        """Yield (store key, host piece) pairs — one per fsdp shard for
        sharded leaves, a single full copy for replicated ones."""
        ax = self._shard_axis[leaf_key]
        if ax is None:
            yield base_key, arr
            return
        for si, piece in enumerate(np.split(arr, self.fsdp, axis=ax)):
            yield f"{base_key}.s{si}", np.ascontiguousarray(piece)

    def _axis_sharding(self, ax: Optional[int]):
        from jax.sharding import NamedSharding, PartitionSpec as P

        if ax is None:
            return NamedSharding(self.mesh, P())
        parts = [None] * (ax + 1)
        parts[ax] = "fsdp"
        return NamedSharding(self.mesh, P(*parts))

    def _leaf_sharding(self, leaf_key: str):
        return self._axis_sharding(self._shard_axis[leaf_key])

    # ---- edge-leaf (embed / final_norm / lm_head) sharding plumbing
    def _edge_key(self, grp: str, k: str, si) -> str:
        base = f"edge.{grp}.{k}"
        return base if si is None else f"{base}.s{si}"

    def _edge_shards(self, grp: str, k: str, arr: np.ndarray):
        ax = self._edge_axis[(grp, k)]
        if ax is None:
            yield self._edge_key(grp, k, None), arr
            return
        for si, piece in enumerate(np.split(arr, self.fsdp, axis=ax)):
            yield self._edge_key(grp, k, si), np.ascontiguousarray(piece)

    def _edge_sharding(self, grp: str, k: str):
        return self._axis_sharding(self._edge_axis[(grp, k)])

    def _data_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P("data"))

    def _repl_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def _init_leaf(self, name: str, shape, seedseq) -> np.ndarray:
        """Same init families as CausalLM.init (models/transformer.py:285):
        norm weights → 1, biases → 0, everything else (incl. lm_head.w,
        whose all-ones init would make dL/dx identically zero) → N(0, 0.02)."""
        rng = np.random.default_rng(seedseq.spawn(1)[0])
        if name.endswith("norm_w") or name == "final_norm.w":
            return np.ones(shape, np.float32)
        if name.endswith("_b") or name == "final_norm.b":
            return np.zeros(shape, np.float32)
        return (0.02 * rng.standard_normal(shape)).astype(np.float32)

    # ------------------------------------------------------------ programs
    def _build_programs(self):
        model = self.module
        cfg = self.cfg

        # uniform across layers (mixed schedules rejected in __init__),
        # so the one shared group_fwd program bakes it in statically
        window = cfg.layer_windows()[0]

        def group_fwd(gp, x, cos, sin):
            def body(carry, lp):
                y, _ = model._block(carry, lp, cos, sin,
                                    jax.random.PRNGKey(0), True, window)
                return y, None

            out, _ = jax.lax.scan(body, x, gp)
            return out

        def embed_fwd(ep, tokens, positions):
            x = ep["wte"][tokens].astype(cfg.dtype)
            if cfg.position == "learned":
                x = x + ep["wpe"][positions].astype(cfg.dtype)
            return x

        def head_loss(hp, x, labels):
            from ..models.transformer import _norm

            h = _norm(x, hp["w"], hp.get("b"), cfg.norm, cfg.norm_eps)
            logits = (h @ hp["lm_head_w"].astype(cfg.dtype)).astype(jnp.float32)
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, labels[..., None],
                                       axis=-1)[..., 0]
            return jnp.mean(logz - gold)

        group_bwd = lambda gp, x, cos, sin, dy: jax.vjp(      # noqa: E731
            lambda gp_, x_: group_fwd(gp_, x_, cos, sin), gp, x)[1](dy)
        embed_bwd = lambda ep, tokens, positions, dy: jax.vjp(  # noqa: E731
            lambda ep_: embed_fwd(ep_, tokens, positions), ep)[1](dy)[0]
        head_grad = jax.value_and_grad(head_loss, argnums=(0, 1))

        if self.mesh is None:
            self._group_fwd = jax.jit(group_fwd)
            self._group_bwd = jax.jit(group_bwd)
            self._embed_fwd = jax.jit(embed_fwd)
            self._embed_bwd = jax.jit(embed_bwd)
            self._head_grad = jax.jit(head_grad)
            return

        # Mesh mode: activations ride the data axis, param grads (layer
        # AND edge leaves) land reduce-scattered onto their fsdp shards
        # (GSPMD inserts the data-axis psum / reduce-scatter to satisfy
        # the out_shardings — the ZeRO-3 grad flow).
        data_s = self._data_sharding()
        repl_s = self._repl_sharding()
        gp_s = {k: self._leaf_sharding(k) for k in self._layer_keys}
        embed_s = {k: self._edge_sharding("embed", k)
                   for k in self._edge_keys["embed"]}
        hp_s = {k: self._edge_sharding("final_norm", k)
                for k in self._edge_keys["final_norm"]}
        hp_s["lm_head_w"] = self._edge_sharding("lm_head", "w")
        self._group_fwd = jax.jit(group_fwd, out_shardings=data_s)
        self._group_bwd = jax.jit(group_bwd, out_shardings=(gp_s, data_s))
        self._embed_fwd = jax.jit(embed_fwd, out_shardings=data_s)
        self._embed_bwd = jax.jit(embed_bwd, out_shardings=embed_s)
        self._head_grad = jax.jit(
            head_grad,
            out_shardings=(repl_s, (hp_s, data_s)))

    # ------------------------------------------------------------- streaming
    def _fsdp_local_sis(self):
        if not hasattr(self, "_local_sis"):
            # invariant for the engine's lifetime — computed once
            fa = list(self.mesh.axis_names).index("fsdp")
            self._local_sis = sorted(
                {int(np.argwhere(self.mesh.devices == d)[0][fa])
                 for d in self.mesh.local_devices})
        return self._local_sis

    def _local_shards(self, leaf_key: str):
        """Shard indices this process pages for a leaf: all of them in a
        single-process mesh; only the fsdp coordinates of local devices in
        a multi-process one (per-host paging of per-host shards)."""
        if self.mesh is None or self._shard_axis[leaf_key] is None:
            return [None]
        return self._fsdp_local_sis()

    def _edge_local_shards(self, grp: str, k: str):
        if self.mesh is None or self._edge_axis[(grp, k)] is None:
            return [None]
        return self._fsdp_local_sis()

    def _key(self, k: str, gi: int, si) -> str:
        base = f"layers.{k}.g{gi}"
        return base if si is None else f"{base}.s{si}"

    def _load_group(self, gi: int) -> Dict[str, Dict]:
        """Page one group's masters off the store — per fsdp shard."""
        return {k: {si: self.store.get(self._key(k, gi, si))
                    for si in self._local_shards(k)}
                for k in self._layer_keys}

    def _shards_to_device(self, shards, ax: Optional[int], sharding):
        """{si: np} → device array (full single-device array, replicated,
        or assembled per-shard via make_array_from_callback)."""
        if self.mesh is None:
            return jnp.asarray(shards[None])
        if ax is None:
            return jax.device_put(shards[None], self._repl_sharding())
        some = next(iter(shards.values()))
        full = list(some.shape)
        full[ax] *= self.fsdp
        shard_len = some.shape[ax]

        def cb(idx, shards=shards, ax=ax, shard_len=shard_len):
            si = (idx[ax].start or 0) // shard_len
            return shards[si]

        return jax.make_array_from_callback(tuple(full), sharding, cb)

    def _group_to_device(self, host_group):
        return {k: self._shards_to_device(
                    shards, None if self.mesh is None else self._shard_axis[k],
                    None if self.mesh is None else self._leaf_sharding(k))
                for k, shards in host_group.items()}

    def _load_edges(self) -> Dict[str, Dict]:
        """Page every edge leaf off the store — per fsdp shard."""
        return {grp: {k: {si: self.store.get(self._edge_key(grp, k, si))
                          for si in self._edge_local_shards(grp, k)}
                      for k in ks}
                for grp, ks in self._edge_keys.items()}

    def _edges_to_device(self, host_edges) -> Dict[str, Dict]:
        return {grp: {k: self._shards_to_device(
                        shards,
                        None if self.mesh is None
                        else self._edge_axis[(grp, k)],
                        None if self.mesh is None
                        else self._edge_sharding(grp, k))
                      for k, shards in d.items()}
                for grp, d in host_edges.items()}

    def _grads_by_axis(self, grads: Dict[str, Any],
                       axis_of) -> Dict[str, Dict]:
        """Per-shard host grads: {leaf: {si: np}} — each process touches
        only its addressable shards (grads arrive fsdp-sharded and already
        data-reduced, per the out_shardings). ``axis_of(k)`` → shard axis
        (None = replicated leaf)."""
        out = {}
        for k, g in grads.items():
            ax = axis_of(k)
            if self.mesh is None or ax is None:
                out[k] = {None: np.asarray(g, np.float32)}
                continue
            shard_len = g.shape[ax] // self.fsdp
            d = {}
            for sh in g.addressable_shards:
                si = (sh.index[ax].start or 0) // shard_len
                if si not in d:
                    d[si] = np.asarray(sh.data, np.float32)
            out[k] = d
        return out

    def _grads_to_host(self, dgp) -> Dict[str, Dict]:
        return self._grads_by_axis({k: dgp[k] for k in self._layer_keys},
                                   lambda k: self._shard_axis[k])

    def _edge_grads_to_host(self, grp: str, grads) -> Dict[str, Dict]:
        return self._grads_by_axis(grads,
                                   lambda k: self._edge_axis[(grp, k)])

    def _acc_shard(self, key: str, g: np.ndarray, micro: int,
                   last: bool) -> Optional[np.ndarray]:
        """Gradient-accumulation plumbing for one shard: add to the
        store-backed ``acc.{key}`` buffer on non-final micro steps (the
        accumulator pages through the same NVMe/host store as the masters
        — host RAM never holds a second full-model copy); return the
        summed gradient on the final one."""
        if micro > 0:
            g = g + self.store.get(f"acc.{key}")
        if not last:
            self.store.put(f"acc.{key}", g)
            return None
        return g

    def _opt_shard(self, key: str, master_arr: np.ndarray, g: np.ndarray):
        """C++ host optimizer on one master shard; page back out."""
        master = master_arr.reshape(-1)
        m = self.store.get(f"opt_m.{key}").reshape(-1)
        v = self.store.get(f"opt_v.{key}").reshape(-1)
        # bias-correction counter synthesized from the engine step
        # (one shared counter; every leaf advances once per step)
        st = {"m": m, "v": v,
              "step": np.asarray([self.opt_step - 1], np.float32)}
        self.cpu_opt.step(master, np.ascontiguousarray(g.reshape(-1)), st,
                          lr=self.lr)
        self.store.put(key, master_arr)
        self.store.put(f"opt_m.{key}", m.reshape(master_arr.shape))
        self.store.put(f"opt_v.{key}", v.reshape(master_arr.shape))

    def _update_group(self, gi: int, host_group, dev_grads, micro: int,
                      gas: int):
        """Accumulate or apply one group's gradients (final micro step →
        mean over ``gas`` micro batches feeds the host optimizer)."""
        last = micro == gas - 1
        for k in self._layer_keys:
            for si, master_arr in host_group[k].items():
                key = self._key(k, gi, si)
                g = self._acc_shard(key, dev_grads[k][si], micro, last)
                if g is not None:
                    self._opt_shard(key, master_arr, g / gas)
        # group boundary: ONE wait for this group's N async NVMe writes
        # (master + moments + acc shards) — the writes overlapped the
        # optimizer math above instead of each serializing against it
        self.store.flush()

    def _update_edges(self, host_edges, edge_grads, micro: int, gas: int):
        last = micro == gas - 1
        for grp, per_leaf in edge_grads.items():
            for k, shards in per_leaf.items():
                for si, g in shards.items():
                    key = self._edge_key(grp, k, si)
                    g = self._acc_shard(key, g, micro, last)
                    if g is not None:
                        self._opt_shard(key, host_edges[grp][k][si], g / gas)
        self.store.flush()          # edge-group boundary, same contract

    # ------------------------------------------------------------------ step
    def train_batch(self, batch) -> float:
        """One effective batch: ``gradient_accumulation_steps`` micro
        steps (each a full streamed fwd+bwd sweep, layer-group and edge
        grads accumulating in store-backed ``acc.*`` buffers) + one host
        optimizer update on the mean gradient. Returns the mean micro
        loss."""
        gas = int(getattr(self.config, "gradient_accumulation_steps", 1)
                  or 1)
        it = batch if hasattr(batch, "__next__") else None
        if it is None and not isinstance(batch, dict):
            # a fresh iter() each call would silently replay element 0
            raise TypeError(
                "train_batch expects a batch dict or an iterator; wrap "
                "lists/datasets in iter(...) so consumption is stateful")
        if gas > 1 and it is None:
            raise TypeError(
                f"gradient_accumulation_steps={gas} needs an iterator of "
                "micro batches, not a single batch dict")
        micro_batches = []
        for _ in range(gas):
            if it is None:
                micro_batches.append(batch)
                continue
            try:
                micro_batches.append(next(it))
            except StopIteration:
                # fail BEFORE mutating state — a bare StopIteration
                # mid-batch would leave half-accumulated acc.* buffers
                # (and PEP 479 would mangle it inside generators)
                raise ValueError(
                    f"micro-batch iterator exhausted after "
                    f"{len(micro_batches)} of {gas} accumulation steps"
                    ) from None
        self.opt_step += 1
        # edges are read once per effective batch (they only change at the
        # final micro step's update)
        host_edges = self._load_edges()
        edges_dev = self._edges_to_device(host_edges)
        losses = [self._micro_step(mb, host_edges, edges_dev, micro, gas)
                  for micro, mb in enumerate(micro_batches)]
        self.global_steps += 1
        return float(np.mean(losses))

    def _micro_step(self, data, host_edges, edges_dev, micro: int,
                    gas: int) -> float:
        host_tokens = np.asarray(data["input_ids"])
        labels_np = host_tokens[:, 1:]
        tokens_np = host_tokens[:, :-1]
        B, T = tokens_np.shape
        if self.mesh is None:
            tokens = jnp.asarray(tokens_np, jnp.int32)
            labels = jnp.asarray(labels_np, jnp.int32)
        else:
            if B % self.dp != 0:
                raise ValueError(f"batch {B} not divisible by data axis "
                                 f"{self.dp}")
            ds = self._data_sharding()
            tokens = jax.device_put(tokens_np.astype(np.int32), ds)
            labels = jax.device_put(labels_np.astype(np.int32), ds)
        positions = jnp.arange(T)
        cos, sin = self.module._pos_tables(T, None)

        # ---- forward sweep: double-buffered group streaming
        x = self._embed_fwd(edges_dev["embed"], tokens, positions)
        boundary = [x]
        fut = self._prefetch.submit(self._load_group, 0)
        for gi in range(len(self.groups)):
            host_group = fut.result()
            if gi + 1 < len(self.groups):          # prefetch next while we run
                fut = self._prefetch.submit(self._load_group, gi + 1)
            gp = self._group_to_device(host_group)
            x = self._group_fwd(gp, x, cos, sin)
            boundary.append(x)
            del gp

        # ---- head loss + backward seed
        hp = dict(edges_dev["final_norm"],
                  lm_head_w=edges_dev["lm_head"]["w"])
        (loss, (dhp, dx)) = self._head_grad(hp, boundary[-1], labels)

        # ---- backward sweep (recompute per group), host opt overlapped
        fut = self._prefetch.submit(self._load_group, len(self.groups) - 1)
        pending_update = None
        for gi in reversed(range(len(self.groups))):
            host_group = fut.result()
            if gi - 1 >= 0:
                fut = self._prefetch.submit(self._load_group, gi - 1)
            gp = self._group_to_device(host_group)
            dgp, dx = self._group_bwd(gp, boundary[gi], cos, sin, dx)
            dgp_host = self._grads_to_host(dgp)
            if pending_update is not None:
                pending_update.result()
            pending_update = self._prefetch.submit(
                self._update_group, gi, host_group, dgp_host, micro, gas)
            del gp, dgp
        if pending_update is not None:
            pending_update.result()

        # ---- edge grads (embed + head): accumulate / host-update
        d_embed = self._embed_bwd(edges_dev["embed"], tokens, positions, dx)
        edge_grads = {
            "embed": self._edge_grads_to_host("embed", d_embed),
            "final_norm": self._edge_grads_to_host(
                "final_norm",
                {k: v for k, v in dhp.items() if k != "lm_head_w"}),
            "lm_head": self._edge_grads_to_host(
                "lm_head", {"w": dhp["lm_head_w"]}),
        }
        self._update_edges(host_edges, edge_grads, micro, gas)
        return float(loss)

    def get_lr(self):
        return [self.lr]

    def gather_edges(self) -> Dict[str, Dict[str, np.ndarray]]:
        """Assemble the full edge leaves from their store shards (tests /
        checkpoint export; pages through the store like a step would).
        Single-process only: each process's store holds only its local
        fsdp shards, so a multi-host gather would silently return
        undersized arrays."""
        if jax.process_count() > 1:
            raise NotImplementedError(
                "gather_edges is single-process: this host's store holds "
                "only its local fsdp shards; export per-host and merge, "
                "or use the universal checkpoint writer")
        out: Dict[str, Dict[str, np.ndarray]] = {}
        for grp, ks in self._edge_keys.items():
            out[grp] = {}
            for k in ks:
                ax = self._edge_axis[(grp, k)]
                sis = self._edge_local_shards(grp, k)
                pieces = [self.store.get(self._edge_key(grp, k, si))
                          for si in sis]
                out[grp][k] = (pieces[0] if ax is None
                               else np.concatenate(pieces, axis=ax))
        return out

    def streaming_report(self) -> Dict[str, Any]:
        """Quantify the streaming-vs-resident trade (r3 weak #3): paging
        volume, measured I/O counters, and the recompute factor the
        grouped-vjp backward pays (each group's forward runs twice — the
        activation-checkpointing 4/3-step-FLOPs factor, reference
        partitioned_param_coordinator prefetch trades the same way)."""
        steps = max(self.global_steps, 1)
        gas = int(getattr(self.config, "gradient_accumulation_steps", 1)
                  or 1)
        layer_bytes = self.param_bytes - self._edge_bytes
        # layer groups: params fwd+bwd per micro (2·gas), moments at the
        # update (2), acc re-reads on micros > 0 (gas−1); edges: params
        # once per batch (1), moments (2), acc re-reads (gas−1)
        expected = (layer_bytes * (3 * gas + 1)
                    + self._edge_bytes * (gas + 2))
        return {
            "param_bytes": self.param_bytes,
            "edge_bytes": self._edge_bytes,
            "gradient_accumulation_steps": gas,
            "groups": len(self.groups),
            "fsdp": self.fsdp,
            "data": self.dp,
            "store_device": self.store.device,
            "bytes_read_total": self.store.bytes_read,
            "bytes_read_per_step": self.store.bytes_read // steps,
            "expected_bytes_per_step": expected,
            "reads_per_step": self.store.reads // steps,
            # grouped-vjp backward recomputes each group's forward: step
            # FLOPs are ~8ND vs the resident engine's 6ND
            "recompute_flops_factor": 8 / 6,
        }

    def close(self):
        self._prefetch.shutdown(wait=True)
        self.store.close()
