"""ZeRO-Offload / ZeRO-Offload++ / ZeRO-Infinity optimizer offload.

Counterpart of reference ZeRO offload tiers: CPU optimizer offload
(``stage_1_and_2.py:1096`` + ``csrc/adam/cpu_adam_impl.cpp``), Twin-Flow
partial offload (``ratio`` — engine.py:703, ZeRO-Offload++), and NVMe
optimizer-state swapping (``runtime/swap_tensor/partitioned_optimizer_
swapper.py`` over ``csrc/aio``).

TPU data flow per optimizer step (device = TPU HBM, host = TPU-VM DRAM):

1. the jitted finalize program unscales/clips grads on device;
2. offloaded leaves' grads stream to host; the C++ SIMD optimizer
   (ops/cpu_adam.py) updates fp32 masters in host DRAM (moments live in
   DRAM, or on NVMe via the aio swapper when ``device == "nvme"``);
3. updated masters stream back into the sharded device params;
4. non-offloaded leaves (Twin-Flow: fraction ``1 - ratio``, largest-first
   by bytes) update on device in the normal jitted path.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ..ops.cpu_adam import (DeepSpeedCPUAdam, DeepSpeedCPUAdagrad,
                            DeepSpeedCPULion)
from ..utils.logging import logger
from .swap_tensor.async_swapper import OptimizerStateSwapper

_CPU_OPTS = {
    "adam": DeepSpeedCPUAdam,
    "adamw": lambda **kw: DeepSpeedCPUAdam(adamw_mode=True, **kw),
    "fusedadam": DeepSpeedCPUAdam,
    "adagrad": DeepSpeedCPUAdagrad,
    "lion": DeepSpeedCPULion,
}


class OffloadOptimizerPlan:
    """Splits the param tree into offloaded (host/NVMe) and device-resident
    subsets and owns the host-side update."""

    def __init__(self, params, opt_type: str, opt_params: dict,
                 device: str = "cpu", ratio: float = 1.0,
                 nvme_path: Optional[str] = None, aio_threads: int = 2):
        key = opt_type.lower().replace("_", "")
        if key not in _CPU_OPTS:
            raise ValueError(
                f"optimizer {opt_type!r} has no CPU-offload implementation "
                f"(reference zero_force_ds_cpu_optimizer); "
                f"known: {sorted(_CPU_OPTS)}")
        kwargs = dict(opt_params or {})
        kwargs.pop("torch_adam", None)
        self.cpu_opt = _CPU_OPTS[key](**kwargs)
        self.device = device

        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        sizes = [int(np.prod(l.shape)) * 4 for l in leaves]
        total = sum(sizes)
        # Twin-Flow: offload the largest leaves until `ratio` of bytes
        order = sorted(range(len(leaves)), key=lambda i: -sizes[i])
        self.offloaded: List[int] = []
        acc = 0
        for i in order:
            if acc >= ratio * total:
                break
            self.offloaded.append(i)
            acc += sizes[i]
        self.offloaded_set = set(self.offloaded)
        self.kept: List[int] = [i for i in range(len(leaves))
                                if i not in self.offloaded_set]

        # host fp32 masters for offloaded leaves
        self.masters: Dict[int, np.ndarray] = {
            i: np.array(jax.device_get(leaves[i]), np.float32, copy=True)
            for i in self.offloaded}
        # moments: host DRAM, or NVMe via the swapper
        self.swapper: Optional[OptimizerStateSwapper] = None
        self.states: Dict[int, dict] = {}
        if device == "nvme":
            if not nvme_path:
                raise ValueError("offload_optimizer.nvme_path required for NVMe")
            self.swapper = OptimizerStateSwapper(nvme_path, n_threads=aio_threads)
            for i in self.offloaded:
                st = self.cpu_opt.init_state(self.masters[i].reshape(-1))
                for mk, arr in st.items():
                    self.swapper.register(f"leaf{i}_{mk}", arr.shape)
                self.states[i] = {mk: None for mk in st}
        else:
            for i in self.offloaded:
                self.states[i] = self.cpu_opt.init_state(
                    self.masters[i].reshape(-1))
        logger.info(
            f"offload plan: {len(self.offloaded)}/{len(leaves)} leaves "
            f"({acc / max(total, 1):.0%} of bytes) → {device}")

    # ------------------------------------------------------------------
    def split(self, tree):
        """tree → (kept subtree dict, offloaded leaves by index)."""
        leaves = jax.tree_util.tree_flatten(tree)[0]
        kept = {str(i): leaves[i] for i in self.kept}
        off = {i: leaves[i] for i in self.offloaded}
        return kept, off

    def merge(self, kept: Dict[str, object], off_host: Dict[int, np.ndarray],
              shardings=None):
        """Reassemble the full tree from device subtree + host leaves."""
        n = len(self.kept) + len(self.offloaded)
        leaves: List[object] = [None] * n
        for i in self.kept:
            leaves[i] = kept[str(i)]
        shard_leaves = (jax.tree_util.tree_flatten(shardings)[0]
                        if shardings is not None else [None] * n)
        for i in self.offloaded:
            arr = off_host[i]
            leaves[i] = (jax.device_put(arr, shard_leaves[i])
                         if shard_leaves[i] is not None else jax.numpy.asarray(arr))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def host_update_leaf(self, i: int, grad: np.ndarray, lr: float) -> None:
        """C++ host optimizer step for ONE offloaded leaf (the unit of the
        pipelined step — engine._offload_step overlaps leaf i's update with
        leaf i+1's device→host gradient transfer, the reference's
        stream-overlap of stage_1_and_2.py:1096 expressed as a transfer/
        compute pipeline)."""
        g = np.ascontiguousarray(grad.reshape(-1), np.float32)
        master = self.masters[i].reshape(-1)
        if self.swapper is not None:
            state = {mk: self.swapper.load(f"leaf{i}_{mk}")
                     for mk in self.states[i]}
        else:
            state = self.states[i]
        self.cpu_opt.step(master, g, state, lr=lr)
        if self.swapper is not None:
            for mk, arr in state.items():
                self.swapper.store(f"leaf{i}_{mk}", arr)

    def host_update(self, off_grads: Dict[int, np.ndarray], lr: float) -> Dict[int, np.ndarray]:
        """Run the C++ host optimizer on every offloaded leaf (serial
        convenience path; the engine uses the pipelined per-leaf form)."""
        for i in self.offloaded:
            self.host_update_leaf(i, off_grads[i], lr)
        return self.masters

    def close(self):
        if self.swapper is not None:
            self.swapper.close()
