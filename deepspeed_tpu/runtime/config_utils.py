"""Typed config base model.

Counterpart of reference ``runtime/config_utils.py`` (``DeepSpeedConfigModel``):
a pydantic base with support for deprecated field aliases, ``"auto"``
sentinels, and dict round-tripping. All feature sub-configs in
:mod:`deepspeed_tpu.runtime.config` derive from this.
"""

from __future__ import annotations

from typing import Any

from pydantic import BaseModel, ConfigDict

AUTO = "auto"


class DSConfigModel(BaseModel):
    """Base for all config blocks: ignores unknown keys (with a warning),
    allows population by field name or alias, validates on assignment."""

    model_config = ConfigDict(
        extra="allow",
        populate_by_name=True,
        validate_assignment=True,
        arbitrary_types_allowed=True,
        protected_namespaces=(),
    )

    def __init__(self, strict: bool = False, **data: Any):
        if not strict:  # replace None values with defaults
            for field_name, field_info in self.__class__.model_fields.items():
                if field_name in data and data[field_name] is None:
                    data[field_name] = field_info.get_default(call_default_factory=True)
        super().__init__(**data)

    def to_dict(self) -> dict:
        return self.model_dump()


def get_scalar_param(param_dict: dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """json.load object_pairs_hook that rejects duplicate keys
    (reference config_utils.py behavior)."""
    d = dict(ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter: dict[str, int] = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {keys}")
    return d
