"""Checkpoint save/load — universal (topology-independent) layout by default.

Counterpart of reference ``engine.py:3006 save_checkpoint`` /
``:2657 load_checkpoint`` (tag dirs + ``latest`` pointer), the universal
checkpoint (``deepspeed/checkpoint/ds_to_universal.py``: per-parameter
canonical shards re-shardable to any new DP/TP/PP), and ``zero_to_fp32.py``
export. The TPU-native design makes the *universal* layout the native
on-disk format: each leaf is stored as one full (unsharded) fp32 ``.npy``
keyed by its pytree path, so any mesh shape / ZeRO stage can load any
checkpoint — the reference's elastic/universal re-sharding machinery
(reshape_3d_utils etc.) reduces to "device_put with the new sharding".

Layout::

    <save_dir>/<tag>/manifest.json       # config snapshot, counters, client state
    <save_dir>/<tag>/params/<path>.npy
    <save_dir>/<tag>/opt/<path>.npy
    <save_dir>/latest                     # tag pointer (reference parity)
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import logger


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return ".".join(parts)


class CheckpointWriter:
    """Background disk writer (the reference's Nebula async checkpoint
    engine role, ``runtime/checkpoint_engine/nebula_checkpoint_engine.py``):
    shard bytes are snapshot to host synchronously (cheap parallel DMA,
    and required before donation invalidates the buffers), the np.save
    calls — the dominant cost — run on a worker thread so the step loop
    continues during the write."""

    def __init__(self):
        import queue
        import threading

        self._q = queue.Queue()
        self._errors = []
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            fname, arr = item
            try:
                np.save(fname, arr)
            except Exception as e:  # surfaced on wait()
                self._errors.append((fname, e))
            finally:
                self._q.task_done()

    def submit(self, fname: str, arr: np.ndarray):
        self._q.put((fname, arr))

    def finalize(self):
        """Join queued writes, stop the worker, raise any collected error.
        The worker thread is ALWAYS joined before the error surfaces —
        a failed save must not leak its writer thread — and a raised
        IOError means the commit marker was never written (the previous
        checkpoint's 'latest' stays loadable)."""
        self._q.join()
        self._q.put(None)          # terminate _run — no thread leak per save
        self._thread.join()
        if self._errors:
            errs, self._errors = self._errors, []
            raise IOError(f"checkpoint writes failed: {errs}")

    # historical name, kept for callers of the async-save path
    wait = finalize


def _fsync_dir(path: str) -> None:
    """fsync a directory so a rename into it is durable (POSIX: the
    rename itself lives in the directory's metadata). Best-effort —
    some filesystems refuse O_RDONLY-fsync on directories."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write_text(path: str, text: str) -> None:
    """Crash-safe file replace: write a temp file, fsync it, then
    ``os.replace`` (atomic on POSIX) and fsync the directory. A reader
    — or a restart after a crash at ANY point in here — sees either the
    complete old content or the complete new content, never a torn
    write. This is what makes 'latest' a real commit marker: a crash
    mid-save can never leave it pointing at a half-written tag."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        fh.write(text)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def _shard_fname(key: str, start) -> str:
    # start offsets in the name make shard files self-describing — no
    # cross-process index exchange needed for the manifest
    return f"{key}.shard_{'-'.join(str(s) for s in start)}.npy"


def _save_tree(tree, out_dir: str, writer: Optional[CheckpointWriter] = None
               ) -> Dict[str, str]:
    """Multi-host-safe sharded save: every process writes exactly the
    shards it owns (``replica_id == 0`` dedupes replicas), so nothing is
    ever gathered to one host (the reference's per-rank
    ``zero_pp_rank_X..._optim_states.pt`` layout, ``engine.py:3409``).
    Fully-replicated leaves keep the single ``<key>.npy`` form (written by
    process 0 only)."""
    os.makedirs(out_dir, exist_ok=True)
    index = {}
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in flat:
        key = _path_str(path)
        if not isinstance(leaf, jax.Array) or leaf.is_fully_replicated:
            index[key] = key + ".npy"
            if jax.process_index() == 0:
                arr = np.asarray(jax.device_get(leaf))
                if writer is not None:
                    writer.submit(os.path.join(out_dir, index[key]), arr)
                else:
                    np.save(os.path.join(out_dir, index[key]), arr)
            continue
        seen = set()
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue
            start = tuple(sl.indices(dim)[0] for sl, dim in
                          zip(shard.index, leaf.shape))
            if start in seen:     # same shard via multiple local devices
                continue
            seen.add(start)
            fname = _shard_fname(key, start)
            arr = np.asarray(shard.data)
            if writer is not None:
                writer.submit(os.path.join(out_dir, fname), arr)
            else:
                np.save(os.path.join(out_dir, fname), arr)
        index[key] = key + ".shard_*"
    return index


def _read_leaf(in_dir: str, key: str, shape, dtype) -> np.ndarray:
    """Assemble one leaf from a single file or its shard files."""
    import glob

    single = os.path.join(in_dir, key + ".npy")
    if os.path.exists(single):
        return np.load(single).astype(dtype)
    files = glob.glob(os.path.join(in_dir, key + ".shard_*.npy"))
    if not files:
        raise FileNotFoundError(f"no checkpoint data for leaf {key} in {in_dir}")
    out = np.zeros(shape, dtype)
    covered = 0
    for f in files:
        coords = os.path.basename(f)[len(key) + len(".shard_"):-len(".npy")]
        start = tuple(int(c) for c in coords.split("-"))
        block = np.load(f)
        idx = tuple(slice(s, s + b) for s, b in zip(start, block.shape))
        out[idx] = block
        covered += block.size
    # shards are disjoint, so coverage must be exact — a missing/partial
    # shard file must fail loudly, not resume from silent zeros
    expect = int(np.prod(shape)) if shape else 1
    if covered != expect:
        raise IOError(f"leaf {key}: shard files cover {covered} of {expect} "
                      f"elements — incomplete checkpoint in {in_dir}")
    return out


def _load_tree(template, shardings, in_dir: str):
    """Load leaves by path into the template's structure with shardings.

    Universal-layout property preserved: shard files reassemble to the full
    leaf regardless of the mesh that wrote them, then device_put re-shards
    to the loading mesh."""
    flat_t, treedef = jax.tree_util.tree_flatten_with_path(template)
    flat_s = jax.tree_util.tree_flatten(shardings)[0] if shardings is not None \
        else [None] * len(flat_t)
    leaves = []
    for (path, leaf), shard in zip(flat_t, flat_s):
        key = _path_str(path)
        arr = _read_leaf(in_dir, key, tuple(leaf.shape), leaf.dtype)
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(f"Checkpoint shape mismatch for {key}: "
                             f"{arr.shape} vs expected {leaf.shape}")
        leaves.append(jax.device_put(arr, shard) if shard is not None else jnp.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[dict] = None,
                    save_latest: bool = True, async_save: bool = False,
                    urgent: bool = False) -> str:
    """Sharded multi-host save: every process writes the shards it owns
    (no single-host gather — at the 70B target a consolidated save would
    push ~260 GB through one host); with ``async_save`` the disk writes run
    on a background thread and :func:`wait_pending_save` joins them.

    ``urgent=True`` is the SIGTERM-grace-window path (docs/TRAINING.md
    "Fault tolerance"): any in-flight async write is joined first (its
    failure is logged, not raised — a broken *previous* save must not
    abort the preemption save), the write completes synchronously, and
    the measured wall time lands on ``engine.last_urgent_save_s`` so the
    supervisor can judge it against the grace budget."""
    import time

    t_urgent = time.perf_counter() if urgent else None
    if urgent:
        async_save = False
    try:
        wait_pending_save(engine)   # join any prior async save before reusing
    except Exception as e:
        if not urgent:
            raise
        # the failed save's pending commit was already dropped, so its
        # 'latest' can never publish; this save proceeds on a clean slate
        logger.warning(f"urgent save: prior async save failed ({e!r}); "
                       "continuing with the urgent checkpoint")
    tag = tag if tag is not None else f"global_step{engine.global_steps}"
    ckpt_dir = os.path.join(save_dir, str(tag))
    os.makedirs(ckpt_dir, exist_ok=True)

    state = engine.state
    writer = CheckpointWriter() if async_save else None
    p_index = _save_tree(state.params, os.path.join(ckpt_dir, "params"), writer)
    o_index = _save_tree(state.opt_state.moments, os.path.join(ckpt_dir, "opt"),
                         writer)
    plan = getattr(engine, "_offload_plan", None)
    if plan is not None and jax.process_index() == 0:
        # host-side optimizer state (ZeRO-Offload masters + moments)
        off_dir = os.path.join(ckpt_dir, "offload")
        os.makedirs(off_dir, exist_ok=True)
        for i in plan.offloaded:
            np.save(os.path.join(off_dir, f"master_{i}.npy"), plan.masters[i])
            if plan.swapper is None:
                for mk, arr in plan.states[i].items():
                    np.save(os.path.join(off_dir, f"state_{i}_{mk}.npy"), arr)
    engine._pending_ckpt_writer = writer
    # the manifest snapshot is taken NOW (state may advance during async
    # writes); the manifest + 'latest' pointer are only *written* once all
    # shard bytes are durable — 'latest' is the commit marker, so a crash
    # mid-save must not leave it pointing at an incomplete tag
    manifest = {
        "tag": str(tag),
        "global_step": int(state.global_step),
        # the host step counter counts overflow/anomaly-SKIPPED steps the
        # device counter excludes; both must round-trip or a resume after
        # any skipped step replays one extra step (loss-curve fork)
        "host_global_steps": int(engine.global_steps),
        "skipped_steps": int(state.skipped_steps),
        "micro_steps": engine.micro_steps,
        "opt_step": int(state.opt_state.step),
        "loss_scale": float(state.scale_state.scale),
        "good_steps": int(state.scale_state.good_steps),
        "hysteresis": int(state.scale_state.hysteresis),
        "lr_scheduler": engine.lr_scheduler.state_dict(),
        "client_state": client_state or {},
        "params_index": p_index,
        "opt_index": o_index,
        "config": engine.config.model_dump(mode="json"),
        "format_version": 1,
    }

    def commit():
        if jax.process_index() != 0:
            return
        # both the manifest and the 'latest' pointer go through the
        # atomic temp-file + os.replace + dir-fsync path: a crash between
        # (or inside) these writes leaves the PREVIOUS checkpoint fully
        # loadable — 'latest' either still names the old tag or names a
        # tag whose manifest is complete
        _atomic_write_text(os.path.join(ckpt_dir, "manifest.json"),
                           json.dumps(manifest, indent=2, default=str))
        if save_latest:
            _atomic_write_text(os.path.join(save_dir, "latest"), str(tag))

    engine._pending_ckpt_commit = commit
    if not async_save:
        wait_pending_save(engine)
    if urgent:
        engine.last_urgent_save_s = time.perf_counter() - t_urgent
        logger.info(f"Urgent checkpoint {ckpt_dir} committed in "
                    f"{engine.last_urgent_save_s:.2f}s")
    else:
        logger.info(f"Saved checkpoint {ckpt_dir}"
                    + (" (async writes in flight)" if async_save else ""))
    return ckpt_dir


def wait_pending_save(engine):
    """Join the async writer (if any), barrier across hosts so every
    process's shards are durable, then write the manifest + 'latest'
    commit marker (reference checkpoint_engine commit() role)."""
    writer = getattr(engine, "_pending_ckpt_writer", None)
    if writer is not None:
        try:
            writer.finalize()
        except BaseException:
            # failed shard writes: drop the pending commit closure too,
            # or the NEXT save's join would run it and point 'latest' at
            # this incomplete tag
            engine._pending_ckpt_commit = None
            raise
        finally:
            engine._pending_ckpt_writer = None
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("dstpu_ckpt_save")
    commit = getattr(engine, "_pending_ckpt_commit", None)
    if commit is not None:
        engine._pending_ckpt_commit = None
        commit()


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    load_module_only: bool = False):
    if tag is None:
        latest = os.path.join(load_dir, "latest")
        if not os.path.exists(latest):
            logger.warning(f"No 'latest' file in {load_dir}; nothing loaded")
            return None, {}
        with open(latest) as fh:
            tag = fh.read().strip()
    ckpt_dir = os.path.join(load_dir, str(tag))
    with open(os.path.join(ckpt_dir, "manifest.json")) as fh:
        manifest = json.load(fh)

    state = engine.state
    params = _load_tree(state.params, engine._param_shardings,
                        os.path.join(ckpt_dir, "params"))
    new_state = state._replace(params=params)

    if load_optimizer_states and not load_module_only:
        moments = _load_tree(state.opt_state.moments,
                             engine._opt_shardings.moments,
                             os.path.join(ckpt_dir, "opt"))
        new_state = new_state._replace(
            opt_state=state.opt_state._replace(
                moments=moments,
                step=jnp.asarray(manifest["opt_step"], jnp.int32)),
            scale_state=state.scale_state._replace(
                scale=jnp.asarray(manifest["loss_scale"], jnp.float32),
                good_steps=jnp.asarray(manifest["good_steps"], jnp.int32),
                hysteresis=jnp.asarray(manifest["hysteresis"], jnp.int32)),
            global_step=jnp.asarray(manifest["global_step"], jnp.int32),
            skipped_steps=jnp.asarray(manifest["skipped_steps"], jnp.int32))
        # restore the HOST counter from its own field (older manifests
        # lack it; the device counter is then the best available value)
        engine.global_steps = manifest.get("host_global_steps",
                                           manifest["global_step"])
        engine.micro_steps = manifest.get("micro_steps", 0)
        engine.lr_scheduler.load_state_dict(manifest["lr_scheduler"])

    plan = getattr(engine, "_offload_plan", None)
    off_dir = os.path.join(ckpt_dir, "offload")
    if plan is not None and os.path.isdir(off_dir) and not load_module_only:
        for i in plan.offloaded:
            mpath = os.path.join(off_dir, f"master_{i}.npy")
            if os.path.exists(mpath):
                plan.masters[i][...] = np.load(mpath)
            if plan.swapper is None:
                for mk in plan.states[i]:
                    spath = os.path.join(off_dir, f"state_{i}_{mk}.npy")
                    if os.path.exists(spath):
                        plan.states[i][mk][...] = np.load(spath)

    engine.state = new_state
    logger.info(f"Loaded checkpoint {ckpt_dir} (step {manifest['global_step']})")
    return ckpt_dir, manifest.get("client_state", {})


def load_params_for_model(model, checkpoint_dir: str):
    """Params-only load for SERVING (docs/SERVING.md "Multi-model &
    multi-tenant serving"): build the inference weights of ``model``
    from a training checkpoint, without an engine or optimizer state.

    ``checkpoint_dir`` is either one tag directory (holds
    ``manifest.json`` directly) or a save directory whose ``latest``
    pointer is resolved first — the same two forms
    :func:`load_checkpoint` accepts. The universal layout does the rest:
    every leaf reassembles from its full ``.npy`` or shard files
    regardless of the mesh that wrote it.

    Raises :class:`FileNotFoundError` naming the manifest path when the
    directory holds no checkpoint, and :class:`ValueError` naming the
    offending leaves when the manifest's parameter set does not match
    the model (the serve_replica.py misconfiguration path — a spec
    pointing one model family at another family's weights must fail
    loudly at boot, not serve garbage)."""
    ckpt_dir = checkpoint_dir
    manifest_path = os.path.join(ckpt_dir, "manifest.json")
    if not os.path.exists(manifest_path):
        latest = os.path.join(checkpoint_dir, "latest")
        if os.path.exists(latest):
            with open(latest) as fh:
                tag = fh.read().strip()
            ckpt_dir = os.path.join(checkpoint_dir, tag)
            manifest_path = os.path.join(ckpt_dir, "manifest.json")
    if not os.path.exists(manifest_path):
        raise FileNotFoundError(
            f"no checkpoint manifest at {manifest_path} — expected a tag "
            f"directory containing manifest.json, or a save directory "
            f"with a 'latest' pointer, under {checkpoint_dir}")
    with open(manifest_path) as fh:
        manifest = json.load(fh)
    # shapes/dtypes without allocating: the template drives _load_tree
    template = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    flat = jax.tree_util.tree_flatten_with_path(template)[0]
    want = {_path_str(path) for path, _ in flat}
    have = set(manifest.get("params_index", {}))
    if have and want != have:
        missing = sorted(want - have)
        extra = sorted(have - want)
        raise ValueError(
            f"checkpoint {ckpt_dir} (tag {manifest.get('tag')!r}) does "
            f"not match the model: "
            + (f"model leaves absent from checkpoint {missing}; "
               if missing else "")
            + (f"checkpoint leaves unknown to model {extra}"
               if extra else ""))
    params = _load_tree(template, None, os.path.join(ckpt_dir, "params"))
    logger.info(f"Loaded serving params from {ckpt_dir} "
                f"({len(want)} leaves)")
    return params


def save_16bit_model(engine, save_dir: str, save_filename: str = "model.npz"):
    """Consolidated low-precision export (reference engine.py:3488
    ``save_16bit_model`` / ``_zero3_consolidated_16bit_state_dict``)."""
    os.makedirs(save_dir, exist_ok=True)
    flat = jax.tree_util.tree_flatten_with_path(engine.state.params)[0]
    out = {}
    for path, leaf in flat:
        arr = np.asarray(jax.device_get(leaf)).astype(np.float16
                                                      if engine.fp16_enabled else np.float32)
        if engine.bf16_enabled:
            arr = np.asarray(jax.device_get(leaf.astype(jnp.bfloat16)))
        out[_path_str(path)] = arr
    path = os.path.join(save_dir, save_filename)
    with open(path, "wb") as fh:   # np.savez would append .npz to a bare path
        np.savez(fh, **{k: v for k, v in out.items()})
    logger.info(f"Saved 16-bit model to {path}")
    return path
