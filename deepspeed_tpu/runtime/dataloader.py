"""Data loading: DP-sharded batches onto the device mesh.

Counterpart of reference ``runtime/dataloader.py:41`` (``DeepSpeedDataLoader``
over torch ``DistributedSampler``) and ``RepeatingLoader`` (:19). On TPU the
loader yields host batches and the engine places them with a
``(data, fsdp)``-sharded ``jax.device_put`` — the DistributedSampler role
(each DP rank sees a distinct slice) is played by sharded device placement in
the single-controller view, and by per-process slicing under multi-host
(jax.process_index-strided sampling).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

import numpy as np


class RepeatingLoader:
    """Reference runtime/dataloader.py:19 — wraps an iterator to restart it."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)

    # resume pass-through (docs/TRAINING.md "Fault tolerance"): the
    # wrapped loader owns the position; loading state drops the live
    # iterator so the next __next__ starts at the restored point.
    # Loaders without state raise NotImplementedError — the contract the
    # supervisor catches — not AttributeError from blind delegation.
    def state_dict(self):
        if not hasattr(self.loader, "state_dict"):
            raise NotImplementedError(
                f"wrapped loader {type(self.loader).__name__} has no "
                "state_dict — its position is not resumable")
        return self.loader.state_dict()

    def load_state_dict(self, sd):
        if not hasattr(self.loader, "load_state_dict"):
            raise NotImplementedError(
                f"wrapped loader {type(self.loader).__name__} has no "
                "load_state_dict — its position is not resumable")
        self.loader.load_state_dict(sd)
        self.data_iter = iter(self.loader)


class DeepSpeedTpuDataLoader:
    """Batches an indexable or iterable dataset.

    ``dataset`` may be: a dict of equal-length arrays, an array/sequence of
    examples (dict or array each), a torch Dataset (indexable), or an
    iterable of ready-made batches (then ``batch_size`` is ignored).
    Per-process sharding for multi-host uses ``process_index``-strided
    sampling so each host reads a disjoint shard (reference
    DistributedSampler semantics).
    """

    def __init__(self, dataset, batch_size: int, topology=None,
                 collate_fn: Optional[Callable] = None, seed: int = 1234,
                 shuffle: bool = True, drop_last: bool = True,
                 data_sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.seed = seed
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        # resume bookkeeping (state_dict/load_state_dict): batches yielded
        # in the CURRENT epoch, and a one-shot fast-forward count consumed
        # by the next __iter__ after load_state_dict — plain re-iteration
        # (no load) restarts the epoch exactly as before
        self._batches_yielded = 0
        self._resume_batches = 0
        # optional index-batch source (e.g. the curriculum
        # DeepSpeedDataSampler, runtime/data_pipeline/data_sampler.py) —
        # reference deepspeed_io(data_sampler=...) contract
        self.data_sampler = data_sampler
        import jax

        self.num_shards = jax.process_count()
        self.shard_id = jax.process_index()

    # -- helpers -----------------------------------------------------------
    def _len_dataset(self):
        if isinstance(self.dataset, dict):
            return len(next(iter(self.dataset.values())))
        try:
            return len(self.dataset)
        except TypeError:
            return None

    def __len__(self):
        if self.data_sampler is not None:
            # sampler length is in samples; the loader re-slices sampler
            # yields into global micro batches (__iter__), so the count is
            # samples / global-micro
            try:
                return len(self.data_sampler) // self.batch_size
            except TypeError:
                raise TypeError(
                    "data_sampler has no length (pass the sampler object, "
                    "not an iterator, when len() is needed)")
        n = self._len_dataset()
        if n is None:
            raise TypeError("iterable dataset has no length")
        per_shard = n // self.num_shards
        return per_shard // self.batch_size if self.drop_last else -(-per_shard // self.batch_size)

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    # -- resume (docs/TRAINING.md "Fault tolerance") -----------------------
    def state_dict(self):
        """Mid-epoch-resumable position: epoch + step-in-epoch. The
        shuffle RNG needs no extra state — the permutation is
        ``default_rng(seed + epoch)``, recreated per epoch, so the epoch
        number fully determines it. ``seed``/``batch_size``/``shuffle``
        travel along as a consistency stamp checked on load."""
        if self.data_sampler is not None or self._len_dataset() is None:
            raise NotImplementedError(
                "dataloader state_dict needs an indexable dataset without "
                "a data_sampler (sampler/iterable sources own their own "
                "position)")
        return {"epoch": int(self.epoch),
                "batches_yielded": int(self._batches_yielded),
                "seed": int(self.seed), "shuffle": bool(self.shuffle),
                "batch_size": int(self.batch_size),
                "drop_last": bool(self.drop_last),
                # stream identity: a position counted over the shuffled
                # order of N examples sliced order[shard_id::num_shards]
                # is meaningless for any other N or slicing — resuming
                # across a changed process count or a grown/shrunk
                # dataset must fail loudly, not silently fork the stream
                "num_shards": int(self.num_shards),
                "shard_id": int(self.shard_id),
                "dataset_len": int(self._len_dataset())}

    def load_state_dict(self, sd):
        if self.data_sampler is not None or self._len_dataset() is None:
            # same guard as state_dict: a sampler/iterable loader would
            # silently DISCARD the position (__iter__'s sampler branch
            # never consults _resume_batches) — fail loudly instead
            raise NotImplementedError(
                "dataloader load_state_dict needs an indexable dataset "
                "without a data_sampler (sampler/iterable sources own "
                "their own position)")
        checks = {key: getattr(self, key)
                  for key in ("seed", "batch_size", "shuffle", "drop_last",
                              "num_shards", "shard_id")}
        checks["dataset_len"] = self._len_dataset()
        for key, have in checks.items():
            if key in sd and sd[key] != have:
                raise ValueError(
                    f"dataloader state mismatch on {key}: checkpoint has "
                    f"{sd[key]!r}, this loader has {have!r} — "
                    "resume determinism would silently break")
        self.epoch = int(sd["epoch"])
        self._batches_yielded = int(sd.get("batches_yielded", 0))
        # consumed once by the next __iter__: skip the already-seen
        # batches of this epoch without gathering them
        self._resume_batches = self._batches_yielded

    def _gather(self, indices):
        if isinstance(self.dataset, dict):
            return {k: np.asarray(v)[indices] for k, v in self.dataset.items()}
        examples = [self.dataset[int(i)] for i in indices]
        if self.collate_fn is not None:
            return self.collate_fn(examples)
        first = examples[0]
        if isinstance(first, dict):
            return {k: np.stack([np.asarray(e[k]) for e in examples]) for k in first}
        if isinstance(first, (tuple, list)):
            return tuple(np.stack([np.asarray(e[j]) for e in examples])
                         for j in range(len(first)))
        return np.stack([np.asarray(e) for e in examples])

    def __iter__(self):
        if self.data_sampler is not None:
            # sampler yields GLOBAL-batch index arrays (micro × dp × gas,
            # difficulty-gated under curriculum learning); the loader
            # contract is one global MICRO batch per yield, so each sampler
            # yield is re-sliced into its gas micro batches — the engine's
            # train_batch then consumes exactly one sampler yield (and one
            # curriculum step) per optimizer step
            for indices in self.data_sampler:
                indices = np.asarray(indices)
                for lo in range(0, len(indices), self.batch_size):
                    yield self._gather(indices[lo:lo + self.batch_size])
            return
        n = self._len_dataset()
        if n is None:
            # iterable of prepared batches
            for batch in self.dataset:
                yield batch
            return
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        order = order[self.shard_id::self.num_shards]
        nb = len(order) // self.batch_size
        skip, self._resume_batches = self._resume_batches, 0
        self._batches_yielded = min(skip, nb + 1)
        for b in range(nb):
            if b < skip:        # resume fast-forward: no gather, no yield
                continue
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            # count BEFORE the yield: the statement after a yield only
            # runs when the consumer pulls the NEXT item, so counting
            # afterwards would understate the position by one whenever a
            # checkpoint lands right after a consumed batch
            self._batches_yielded = b + 1
            yield self._gather(idx)
        if not self.drop_last and len(order) % self.batch_size and skip <= nb:
            self._batches_yielded = nb + 1
            yield self._gather(order[nb * self.batch_size:])
        self.epoch += 1
        self._batches_yielded = 0
