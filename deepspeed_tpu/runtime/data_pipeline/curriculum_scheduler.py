"""Curriculum difficulty scheduler.

Counterpart of reference ``runtime/data_pipeline/curriculum_scheduler.py``
(``CurriculumScheduler``): maps a global step to a difficulty value under
one of four schedules — ``fixed_linear``, ``fixed_root``, ``fixed_discrete``,
``custom`` — with the same config schema (min/max difficulty,
``schedule_config`` with ``total_curriculum_step`` / ``difficulty_step`` /
``root_degree`` or ``difficulty``/``max_step`` lists). Difficulty is
quantized to ``difficulty_step`` multiples; on TPU that keeps the set of
jit-compiled sequence lengths small (the reference quantizes for Tensor
Core alignment — same knob, different hardware rationale).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional


FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    def __init__(self, config: Dict[str, Any]):
        for key in ("min_difficulty", "max_difficulty", "schedule_type"):
            if key not in config:
                raise ValueError(f"curriculum learning requires '{key}'")
        self.min_difficulty = int(config["min_difficulty"])
        self.max_difficulty = int(config["max_difficulty"])
        self.schedule_type = config["schedule_type"]
        self.current_difficulty = self.min_difficulty
        sc = dict(config.get("schedule_config", {}))
        self.custom_get_difficulty: Optional[Callable[[int], int]] = None

        if self.schedule_type == FIXED_DISCRETE:
            if not sc.get("difficulty") or "max_step" not in sc:
                raise ValueError("fixed_discrete needs schedule_config "
                                 "{difficulty: [...], max_step: [...]}")
            if len(sc["difficulty"]) != len(sc["max_step"]) + 1:
                raise ValueError("fixed_discrete: len(difficulty) must be "
                                 "len(max_step) + 1 (last difficulty holds)")
        elif self.schedule_type in (FIXED_LINEAR, FIXED_ROOT):
            if "total_curriculum_step" not in sc or "difficulty_step" not in sc:
                raise ValueError(
                    f"{self.schedule_type} needs schedule_config "
                    "{total_curriculum_step, difficulty_step}")
            if self.schedule_type == FIXED_ROOT and "root_degree" not in sc:
                raise ValueError("fixed_root needs schedule_config.root_degree")
        elif self.schedule_type != CUSTOM:
            raise ValueError(f"unknown curriculum schedule {self.schedule_type!r}")
        self.schedule_config = sc

    # -- reference API ----------------------------------------------------
    def set_custom_get_difficulty(self, fn: Callable[[int], int]) -> None:
        self.custom_get_difficulty = fn

    def get_current_difficulty(self) -> int:
        return self.current_difficulty

    def set_current_difficulty(self, difficulty: int) -> None:
        self.current_difficulty = int(difficulty)

    def get_state(self) -> Dict[str, Any]:
        return {"current_difficulty": self.current_difficulty,
                "schedule_type": self.schedule_type,
                "min_difficulty": self.min_difficulty,
                "max_difficulty": self.max_difficulty}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.current_difficulty = int(state["current_difficulty"])

    # -- schedule math ----------------------------------------------------
    def _root_difficulty(self, step: int, degree: float) -> int:
        sc = self.schedule_config
        frac = min(1.0, (step / sc["total_curriculum_step"]) ** (1.0 / degree))
        span = self.max_difficulty - self.min_difficulty
        raw = self.min_difficulty + frac * span
        quant = sc["difficulty_step"]
        return min(self.max_difficulty,
                   int(raw / quant) * int(quant)
                   if raw >= self.min_difficulty + quant
                   else self.min_difficulty)

    def get_difficulty(self, global_step: int) -> int:
        if self.schedule_type == FIXED_LINEAR:
            return self._root_difficulty(global_step, 1.0)
        if self.schedule_type == FIXED_ROOT:
            return self._root_difficulty(
                global_step, float(self.schedule_config["root_degree"]))
        if self.schedule_type == FIXED_DISCRETE:
            sc = self.schedule_config
            for diff, max_step in zip(sc["difficulty"], sc["max_step"]):
                if global_step <= max_step:
                    return int(diff)
            return int(sc["difficulty"][-1])
        if self.custom_get_difficulty is None:
            raise RuntimeError("custom curriculum schedule requires "
                               "set_custom_get_difficulty()")
        return int(self.custom_get_difficulty(global_step))

    def update_difficulty(self, global_step: int) -> int:
        self.current_difficulty = self.get_difficulty(global_step)
        return self.current_difficulty
