"""Random-LTD (layer token drop) as JAX transforms.

Counterpart of reference ``data_routing/basic_layer.py``
(``RandomLayerTokenDrop``), ``data_routing/scheduler.py``
(``RandomLTDScheduler``) and the CUDA sampling kernels
``csrc/random_ltd/token_sort.cu`` / ``gather_scatter.cu``: each wrapped
layer processes only a random subset of ``reserved`` tokens; the rest skip
the layer (identity). Indices are sorted ascending so causal order is
preserved for decoders (the reference's token_sort kernel exists for
exactly this — on TPU it is one ``argsort`` the XLA compiler fuses).

Everything here is functional and jit-safe: sampling is `jax.random`,
gather/scatter are `take_along_axis` / indexed `.at[]` updates (autodiff
flows through both, so no custom VJP is needed — the reference's
GatherTokens/ScatterTokens autograd Functions exist only because torch
needed explicit backward routing).
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp


def sample_token_indices(rng: jax.Array, num_layers: int, batch: int,
                         seq: int, reserved: int) -> jax.Array:
    """[num_layers, batch, reserved] random token indices, sorted ascending
    per row (reference gpt_sample_tokens: independent draw per layer)."""
    noise = jax.random.uniform(rng, (num_layers, batch, seq))
    perm = jnp.argsort(noise, axis=-1)[..., :reserved]
    return jnp.sort(perm, axis=-1)


def gather_tokens(hidden: jax.Array, indices: jax.Array) -> jax.Array:
    """hidden [B, T, H], indices [B, r] → [B, r, H]."""
    return jnp.take_along_axis(hidden, indices[..., None], axis=1)


def scatter_tokens(full: jax.Array, part: jax.Array,
                   indices: jax.Array) -> jax.Array:
    """Write the processed subset back into the full sequence."""
    batch_idx = jnp.arange(full.shape[0])[:, None]
    return full.at[batch_idx, indices].set(part)


def apply_random_ltd(layer_fn: Callable[[jax.Array], jax.Array],
                     hidden: jax.Array, indices: jax.Array) -> jax.Array:
    """Run ``layer_fn`` on the sampled tokens only; others pass through
    (reference basic_layer.py forward: gather → layer → scatter)."""
    part = gather_tokens(hidden, indices)
    out = layer_fn(part)
    return scatter_tokens(hidden, out, indices)


class RandomLTDScheduler:
    """Reserved-sequence-length schedule (reference data_routing/scheduler.py):
    grow from ``min_value`` to ``max_value`` by ``seq_per_step`` every
    ``require_steps`` optimizer steps (fixed_linear)."""

    def __init__(self, config: Dict[str, Any]):
        sched = config.get("random_ltd_schedule", config)
        self.min_value = int(sched.get("min_value", 128))
        self.max_value = int(sched.get("max_value", 2048))
        sc = sched.get("schedule_config", {})
        self.seq_per_step = int(sc.get("seq_per_step", 16))
        self.require_steps = int(sc.get("require_steps", 100))
        schedule_type = sched.get("schedule_type", "fixed_linear")
        if schedule_type != "fixed_linear":
            raise ValueError(
                f"random-LTD supports fixed_linear schedules, got "
                f"{schedule_type!r} (reference scheduler.py has the same)")
        self.current_seq = self.min_value
        self.global_step = 0

    def get_current_seq(self) -> int:
        return self.current_seq

    def update_seq(self, global_step: int) -> int:
        self.global_step = global_step
        grown = (global_step // self.require_steps) * self.seq_per_step
        self.current_seq = min(self.max_value, self.min_value + grown)
        return self.current_seq

    def state_dict(self) -> Dict[str, int]:
        return {"current_seq": self.current_seq,
                "global_step": self.global_step}

    def load_state_dict(self, state: Dict[str, int]) -> None:
        self.current_seq = int(state["current_seq"])
        self.global_step = int(state["global_step"])
