"""Offline data analyzer.

Counterpart of reference ``data_sampling/data_analyzer.py`` (``DataAnalyzer``):
map-reduce metric computation over a dataset — each worker computes metric
values for its index range (``run_map``), then a reduce pass merges the
parts into ``<metric>_values.npy`` (sample → value) and
``<metric>_index_by_value.npy`` (samples sorted easiest-first), the files
the curriculum sampler consumes.

The reference builds Megatron mmap ``.bin/.idx`` pairs because its samplers
read them; the TPU-native pipeline keeps plain ``.npy`` (host-side numpy is
the single-controller data plane)."""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np


def metric_seqlen(sample: Any) -> int:
    """Built-in metric: token count (reference's seqlen metric)."""
    if isinstance(sample, dict):
        sample = sample.get("input_ids", next(iter(sample.values())))
    return int(np.asarray(sample).shape[-1] if np.asarray(sample).ndim
               else 1)


def metric_vocab_rarity(vocab_freq: np.ndarray) -> Callable[[Any], float]:
    """Built-in metric factory: mean negative-log-frequency of the sample's
    tokens (reference's vocabularyrarity)."""
    logp = -np.log(np.clip(vocab_freq / max(1, vocab_freq.sum()), 1e-12, 1))

    def fn(sample: Any) -> float:
        if isinstance(sample, dict):
            sample = sample.get("input_ids", next(iter(sample.values())))
        ids = np.asarray(sample).reshape(-1)
        return float(logp[ids].mean())

    return fn


class DataAnalyzer:
    def __init__(self, dataset: Sequence[Any],
                 metric_functions: Dict[str, Callable[[Any], float]],
                 save_path: str,
                 num_workers: int = 1, worker_id: int = 0,
                 batch_size: int = 1024):
        self.dataset = dataset
        self.metric_functions = dict(metric_functions)
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.batch_size = batch_size
        os.makedirs(save_path, exist_ok=True)

    def _worker_range(self):
        n = len(self.dataset)
        per = (n + self.num_workers - 1) // self.num_workers
        lo = self.worker_id * per
        return lo, min(n, lo + per)

    def run_map(self) -> Dict[str, str]:
        """Compute this worker's metric slices; write one part file each."""
        lo, hi = self._worker_range()
        out = {}
        for name, fn in self.metric_functions.items():
            values = np.asarray([fn(self.dataset[i]) for i in range(lo, hi)])
            path = os.path.join(self.save_path,
                                f"{name}_part{self.worker_id:05d}.npy")
            np.save(path, values)
            out[name] = path
        return out

    def run_reduce(self) -> Dict[str, Dict[str, str]]:
        """Merge all part files: values array + easiest-first sample index
        (the reference's index_to_sample_percentile_merged role)."""
        out = {}
        for name in self.metric_functions:
            parts = sorted(
                f for f in os.listdir(self.save_path)
                if f.startswith(f"{name}_part") and f.endswith(".npy"))
            if not parts:
                raise FileNotFoundError(
                    f"no map output for metric {name!r} in {self.save_path}")
            values = np.concatenate(
                [np.load(os.path.join(self.save_path, f)) for f in parts])
            v_path = os.path.join(self.save_path, f"{name}_values.npy")
            i_path = os.path.join(self.save_path,
                                  f"{name}_index_by_value.npy")
            np.save(v_path, values)
            np.save(i_path, np.argsort(values, kind="stable"))
            out[name] = {"values": v_path, "index_by_value": i_path}
        return out

    def run(self) -> Dict[str, Dict[str, str]]:
        """Single-worker convenience: map then reduce."""
        self.run_map()
        return self.run_reduce()
