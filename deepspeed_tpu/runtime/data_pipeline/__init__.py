"""Data efficiency pipeline (reference ``runtime/data_pipeline/``):
curriculum learning scheduler + sampler, offline data analyzer, and
random-LTD token dropping re-designed as JAX transforms."""

from .curriculum_scheduler import CurriculumScheduler
from .data_sampler import DeepSpeedDataSampler
from .data_analyzer import DataAnalyzer
from .random_ltd import (RandomLTDScheduler, apply_random_ltd,
                         sample_token_indices)

__all__ = [
    "CurriculumScheduler", "DeepSpeedDataSampler", "DataAnalyzer",
    "RandomLTDScheduler", "apply_random_ltd", "sample_token_indices",
]
