"""Curriculum-aware data sampler.

Counterpart of reference ``data_sampling/data_sampler.py``
(``DeepSpeedDataSampler``): yields per-step sample indices whose difficulty
(per-sample metric values, e.g. sequence length) is within the curriculum
schedulers' current thresholds, shuffled within the admitted pool, sharded
over data-parallel ranks.

TPU-native notes: the reference is a per-rank torch sampler coordinating
through a process group and mmap'd Megatron index files. Under the JAX
single-controller model one sampler instance produces the *global* batch
index array (the loader device_puts the batch sharded over the data axis),
so no cross-rank coordination is needed; metric values are plain numpy
arrays (the analyzer writes ``.npy`` — data_analyzer.py)."""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from .curriculum_scheduler import CurriculumScheduler

VALUE_BASED = "value"          # threshold compares metric values
PERCENTILE_BASED = "percentile"  # threshold is a percentile of the pool


class DeepSpeedDataSampler:
    def __init__(self,
                 data_efficiency_config: Dict[str, Any],
                 one_epoch_total_samples: int,
                 micro_batch_size: int,
                 data_parallel_size: int,
                 gradient_accumulation_steps: int = 1,
                 metric_values: Optional[Dict[str, np.ndarray]] = None,
                 drop_last: bool = True):
        cfg = data_efficiency_config
        self.seed = int(cfg.get("seed", 1234))
        sampling = cfg.get("data_sampling", {})
        self.num_epochs = int(sampling.get("num_epochs", 1000))
        self.total_samples = one_epoch_total_samples * self.num_epochs
        self.one_epoch_total_samples = one_epoch_total_samples
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.gradient_accumulation_steps = gradient_accumulation_steps
        self.global_batch_size = (micro_batch_size * data_parallel_size
                                  * gradient_accumulation_steps)
        self.drop_last = drop_last
        self.np_rng = np.random.default_rng(self.seed)
        self.consumed_samples = 0
        self.curriculum_step = 0

        cl = sampling.get("curriculum_learning", {})
        self.curriculum_enabled = bool(cl.get("enabled", False))
        self.curriculum_schedulers: Dict[str, CurriculumScheduler] = {}
        self.difficulty_type: Dict[str, str] = {}
        self.metric_values: Dict[str, np.ndarray] = {}
        if self.curriculum_enabled:
            metrics = cl.get("metrics", {})
            if not metrics:
                raise ValueError("curriculum_learning.enabled requires "
                                 "curriculum_learning.metrics")
            for name, mcfg in metrics.items():
                self.curriculum_schedulers[name] = CurriculumScheduler(mcfg)
                self.difficulty_type[name] = mcfg.get("difficulty_type",
                                                      VALUE_BASED)
                values = (metric_values or {}).get(name)
                if values is None:
                    path = mcfg.get("metric_path")
                    if path is None:
                        raise ValueError(
                            f"metric {name!r}: pass metric_values or set "
                            "metric_path (a .npy written by DataAnalyzer)")
                    values = np.load(path)
                values = np.asarray(values)
                if values.shape[0] != one_epoch_total_samples:
                    raise ValueError(
                        f"metric {name!r} has {values.shape[0]} values for "
                        f"{one_epoch_total_samples} samples")
                self.metric_values[name] = values

    def __len__(self) -> int:
        return self.total_samples

    # -- curriculum pool --------------------------------------------------
    def _admitted_pool(self) -> np.ndarray:
        """Indices whose every metric is within its current difficulty."""
        mask = np.ones(self.one_epoch_total_samples, dtype=bool)
        for name, sched in self.curriculum_schedulers.items():
            difficulty = sched.update_difficulty(self.curriculum_step)
            values = self.metric_values[name]
            if self.difficulty_type[name] == PERCENTILE_BASED:
                cutoff = np.percentile(values, min(100, difficulty))
                mask &= values <= cutoff
            else:
                mask &= values <= difficulty
        pool = np.nonzero(mask)[0]
        if pool.size == 0:    # degenerate config: admit the easiest sample
            pool = np.array([int(np.argmin(
                next(iter(self.metric_values.values()))))])
        return pool

    def state_dict(self) -> Dict[str, Any]:
        return {"consumed_samples": self.consumed_samples,
                "curriculum_step": self.curriculum_step,
                "seed": self.seed}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.consumed_samples = int(state["consumed_samples"])
        self.curriculum_step = int(state["curriculum_step"])
        # re-derive the rng stream position deterministically
        self.np_rng = np.random.default_rng(self.seed)
        for _ in range(self.curriculum_step):
            self.np_rng.integers(0, 2**31)

    # -- iteration --------------------------------------------------------
    def __iter__(self) -> Iterator[np.ndarray]:
        """Yields global-batch index arrays (len = global_batch_size)."""
        while self.consumed_samples < self.total_samples:
            self.curriculum_step += 1
            draw_seed = int(self.np_rng.integers(0, 2**31))
            if self.curriculum_enabled:
                pool = self._admitted_pool()
            else:
                pool = np.arange(self.one_epoch_total_samples)
            rng = np.random.default_rng(draw_seed)
            replace = pool.size < self.global_batch_size
            batch = rng.choice(pool, size=self.global_batch_size,
                               replace=replace)
            self.consumed_samples += self.global_batch_size
            yield batch
