from .async_swapper import AsyncTensorSwapper, OptimizerStateSwapper  # noqa: F401
