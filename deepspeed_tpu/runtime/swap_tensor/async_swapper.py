"""Async tensor swapper over the native aio engine.

Counterpart of reference ``runtime/swap_tensor/async_swapper.py``
(``AsyncTensorSwapper``) + ``partitioned_optimizer_swapper.py`` over
``csrc/aio``: moves flat numpy arrays between host DRAM and NVMe files with
overlapped background I/O (swap-out of step N overlaps compute of N+1).
Falls back to synchronous numpy file I/O when the native module is absent.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, Optional

import numpy as np

from ...ops.op_builder import AsyncIOBuilder
from ...utils.logging import logger


class AsyncTensorSwapper:
    def __init__(self, swap_dir: str, block_size: int = 1 << 20,
                 n_threads: int = 2):
        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        self._lib = AsyncIOBuilder().load()
        self._handle = None
        if self._lib is not None:
            self._handle = self._lib.ds_aio_new(block_size, n_threads)

    @property
    def has_native(self) -> bool:
        return self._handle is not None

    def _path(self, key: str) -> str:
        return os.path.join(self.swap_dir, f"{key}.swp")

    def swap_out(self, key: str, array: np.ndarray) -> None:
        """Write to NVMe (async when native). ``array`` must stay alive
        until ``wait`` returns."""
        if self._handle is not None:
            buf = array.ctypes.data_as(ctypes.POINTER(ctypes.c_char))
            self._lib.ds_aio_pwrite(self._handle, self._path(key).encode(),
                                    buf, array.nbytes, 0)
        else:
            # crash-safe sync fallback: temp file + flush/fsync + atomic
            # rename (the runtime/checkpointing.py _atomic_write_text
            # discipline) — a crash mid-write leaves either the old
            # complete .swp or none, never a torn one a later swap_in
            # would read back as garbage
            path = self._path(key)
            tmp = path + ".tmp"
            try:
                with open(tmp, "wb") as fh:
                    array.tofile(fh)
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise

    def swap_in(self, key: str, array: np.ndarray) -> None:
        """Read from NVMe into ``array`` (async when native)."""
        if self._handle is not None:
            buf = array.ctypes.data_as(ctypes.POINTER(ctypes.c_char))
            self._lib.ds_aio_pread(self._handle, self._path(key).encode(),
                                   buf, array.nbytes, 0)
        else:
            array[...] = np.fromfile(self._path(key),
                                     dtype=array.dtype).reshape(array.shape)

    def wait(self) -> None:
        if self._handle is not None:
            errors = self._lib.ds_aio_wait(self._handle)
            if errors:
                raise IOError(f"{errors} async I/O operations failed "
                              f"in {self.swap_dir}")

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def close(self):
        if self._handle is not None:
            self._lib.ds_aio_free(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class OptimizerStateSwapper:
    """NVMe-resident optimizer moments (ZeRO-Infinity tier, reference
    partitioned_optimizer_swapper.py): keeps m/v on disk, pages them into
    reusable host buffers around each optimizer step."""

    def __init__(self, swap_dir: str, n_threads: int = 2):
        self.swapper = AsyncTensorSwapper(swap_dir, n_threads=n_threads)
        self._shapes: Dict[str, tuple] = {}
        self._buffers: Dict[str, np.ndarray] = {}

    def register(self, key: str, shape: tuple, dtype=np.float32) -> None:
        self._shapes[key] = (tuple(shape), np.dtype(dtype))
        init = np.zeros(shape, dtype)
        self.swapper.swap_out(key, init)
        self.swapper.wait()

    def _buffer(self, key: str) -> np.ndarray:
        shape, dtype = self._shapes[key]
        if key not in self._buffers or self._buffers[key].shape != shape:
            self._buffers[key] = np.empty(shape, dtype)
        return self._buffers[key]

    def load(self, key: str) -> np.ndarray:
        buf = self._buffer(key)
        self.swapper.swap_in(key, buf)
        self.swapper.wait()
        return buf

    def store(self, key: str, array: np.ndarray, wait: bool = True) -> None:
        self.swapper.swap_out(key, array)
        if wait:
            self.swapper.wait()

    def close(self):
        self.swapper.close()
