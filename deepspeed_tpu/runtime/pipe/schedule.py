"""Pipeline instruction schedules (1F1B and inference).

Counterpart of reference ``runtime/pipe/schedule.py`` (``TrainSchedule``
:189 — 1F1B; ``InferenceSchedule`` :135; instruction classes :327-489).
On TPU the hot path does not interpret these instructions — the SPMD
pipeline (parallel/pipeline.py) compiles the whole schedule into one XLA
program. These generators drive the host-level executor
(runtime/pipe/engine.py PipelineEngine), which interprets the streams with
real dataflow for the classic PipelineModule/LayerSpec API and is the
skeleton of the multi-slice DCN pipeline.
"""

from __future__ import annotations


class PipeInstruction:
    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        if self.kwargs:
            inner = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
            return f"{self.name}({inner})"
        return self.name

    def __eq__(self, other):
        return repr(self) == repr(other)


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


def _is_even(x):
    return x % 2 == 0


class PipeSchedule:
    """Base generator (reference schedule.py:13): yields per-step lists of
    instructions for one stage."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    @property
    def num_pipe_buffers(self):
        return self.micro_batches

    def steps(self):
        raise NotImplementedError

    def __iter__(self):
        return iter(self.steps())

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    def _buffer_idx(self, micro_batch_id):
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain (reference schedule.py:135)."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        out = []
        for step_id in range(total_steps):
            cmds = []
            micro_batch_id = step_id - self.stage_id
            if self._valid_micro_batch(micro_batch_id):
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(self._buffer_idx(micro_batch_id)))
                if self._valid_stage(self.prev_stage) and not self.is_first_stage:
                    cmds.append(RecvActivation(self._buffer_idx(micro_batch_id)))
                cmds.append(ForwardPass(self._buffer_idx(micro_batch_id)))
                if self._valid_stage(self.next_stage) and not self.is_last_stage:
                    cmds.append(SendActivation(self._buffer_idx(micro_batch_id)))
            out.append(cmds)
        return out


class TrainSchedule(PipeSchedule):
    """1F1B (reference schedule.py:189): alternate forward/backward per step
    with warm-up and cool-down; grad reduction + optimizer step at the end."""

    @property
    def num_pipe_buffers(self):
        buffers = min(self.stages - self.stage_id, self.micro_batches)
        return max(2, buffers)

    def _step_to_micro_batch(self, step_id):
        """Map step to (micro_batch, is_forward) — reference :252."""
        if _is_even(step_id) and _is_even(self.stage_id):
            micro_batch_id = self._even_step_forward_id(step_id)
            is_forward = True
        elif not _is_even(step_id) and not _is_even(self.stage_id):
            micro_batch_id = self._odd_step_forward_id(step_id)
            is_forward = True
        elif _is_even(step_id) and not _is_even(self.stage_id):
            micro_batch_id = self._even_step_backward_id(step_id)
            is_forward = False
        else:
            micro_batch_id = self._odd_step_backward_id(step_id)
            is_forward = False
        return micro_batch_id, is_forward

    def _even_step_forward_id(self, step_id):
        return step_id // 2 - self.stage_id // 2

    def _odd_step_forward_id(self, step_id):
        return (step_id - 1) // 2 - self.stage_id // 2

    def _even_step_backward_id(self, step_id):
        # only reached for odd stages (even step + odd stage → backward)
        return step_id // 2 - self.stages + (self.stage_id + 1) // 2

    def _odd_step_backward_id(self, step_id):
        # only reached for even stages
        return (step_id - 1) // 2 - self.stages + 1 + self.stage_id // 2

    def steps(self):
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        out = []
        prev_micro_batch_id = -1
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._step_to_micro_batch(step_id)
            cmds = []
            if self._valid_micro_batch(prev_micro_batch_id):
                prev_buffer = self._buffer_idx(prev_micro_batch_id)
                if is_forward:
                    # previous step was a backward → its grad goes upstream
                    if self._valid_stage(self.prev_stage):
                        cmds.append(SendGrad(prev_buffer))
                else:
                    # previous step was a forward → activations go downstream
                    if self._valid_stage(self.next_stage):
                        cmds.append(SendActivation(prev_buffer))
            if self._valid_micro_batch(micro_batch_id):
                curr_buffer = self._buffer_idx(micro_batch_id)
                if is_forward:
                    if self.is_first_stage:
                        cmds.append(LoadMicroBatch(curr_buffer))
                    elif self._valid_stage(self.prev_stage):
                        cmds.append(RecvActivation(curr_buffer))
                    cmds.append(ForwardPass(curr_buffer))
                else:
                    if self._valid_stage(self.next_stage):
                        cmds.append(RecvGrad(curr_buffer))
                    cmds.append(BackwardPass(curr_buffer))
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            prev_micro_batch_id = micro_batch_id
            out.append(cmds)
        return out
