from .topology import ProcessTopology, PipeModelDataParallelTopology  # noqa: F401
from .schedule import (  # noqa: F401
    TrainSchedule, InferenceSchedule, PipeSchedule,
    ForwardPass, BackwardPass, SendActivation, RecvActivation,
    SendGrad, RecvGrad, LoadMicroBatch, ReduceGrads, ReduceTiedGrads,
    OptimizerStep)
from .module import LayerSpec, TiedLayerSpec, PipelineModule  # noqa: F401
from .engine import PipelineEngine  # noqa: F401
