"""Pipeline instruction-schedule executor.

Counterpart of reference ``runtime/pipe/engine.py`` (``PipelineEngine`` :55
— ``train_batch`` :312 executes the 1F1B instruction stream through
``_exec_schedule`` :1331 with P2P activation/grad exchange). The TPU-native
*fast path* is the SPMD pipeline (``parallel/pipeline.py``: layers sharded
over the pipe mesh axis, ppermute rotation inside one jitted scan). This
module is the **host-driven executor** for the classic
``PipelineModule``/``LayerSpec`` API: it interprets the exact
``TrainSchedule``/``InferenceSchedule`` instruction streams
(schedule.py) clock-step by clock-step with real dataflow —

- ``ForwardPass`` runs the stage function under ``jax.vjp`` and keeps the
  pullback in the pipe buffer (the functional equivalent of retaining the
  autograd graph per micro-batch);
- ``Send/RecvActivation`` / ``Send/RecvGrad`` move arrays through FIFO
  edge mailboxes (single-controller stand-in for the p2p wire protocol,
  ``pipe/p2p.py`` in the reference — on a multi-slice DCN deployment the
  mailboxes become host transfers);
- ``BackwardPass`` applies the saved pullback to the received cotangent
  (1F1B order ⇒ bounded live activations, exactly the schedule's point);
- ``ReduceTiedGrads`` sums gradients of tie-group params contributed by
  every stage that uses them (TiedLayerSpec);
- ``OptimizerStep`` applies the per-stage optimizer.

Layer protocol (functional stand-in for the reference's nn.Module layers):
a built LayerSpec object exposes ``init(rng, x) -> params`` and
``apply(params, x) -> y``.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ...ops.optimizers import build_optimizer
from .module import PipelineModule, TiedLayerSpec
from . import schedule as sched


class PipelineEngine:
    """Host-driven schedule interpreter over a PipelineModule."""

    def __init__(self, module: PipelineModule, loss_fn: Callable,
                 num_micro_batches: int, optimizer: str = "sgd",
                 optimizer_params: Optional[dict] = None, seed: int = 0):
        self.module = module
        self.loss_fn = loss_fn
        self.num_micro = num_micro_batches
        self.num_stages = module.num_stages
        self._rng = jax.random.PRNGKey(seed)
        self._opt = build_optimizer(optimizer,
                                    optimizer_params or {"lr": 1e-2})
        self._initialized = False
        self.global_steps = 0

        # built layers per stage + tie bookkeeping
        self._stage_layers: List[List[Any]] = []
        self._tie_key_of: List[List[Optional[str]]] = []
        for sid in range(self.num_stages):
            specs = module.stage_layers(sid)
            built, ties = [], []
            for spec in specs:
                built.append(spec.build() if hasattr(spec, "build") else spec)
                ties.append(spec.key if isinstance(spec, TiedLayerSpec)
                            else None)
            self._stage_layers.append(built)
            self._tie_key_of.append(ties)

    # ------------------------------------------------------------- params
    def _lazy_init(self, example_x):
        """Shape-propagating init: tied groups initialize once and share."""
        tied_params: Dict[str, Any] = {}
        self.params: List[List[Any]] = []
        x = example_x
        for sid in range(self.num_stages):
            stage_p = []
            for layer, tie in zip(self._stage_layers[sid],
                                  self._tie_key_of[sid]):
                self._rng, sub = jax.random.split(self._rng)
                if tie is not None and tie in tied_params:
                    p = tied_params[tie]
                else:
                    p = layer.init(sub, x)
                    if tie is not None:
                        tied_params[tie] = p
                stage_p.append(p)
                x = layer.apply(p, x)
            self.params.append(stage_p)
        self.opt_state = [self._opt.init(sp) for sp in self.params]
        self._initialized = True

    def _stage_apply(self, sid: int, stage_params, x):
        for layer, p in zip(self._stage_layers[sid], stage_params):
            x = layer.apply(p, x)
        return x

    # ---------------------------------------------------------- execution
    def train_batch(self, data_iter) -> float:
        """Pull ``num_micro`` (x, y) micro-batches and execute the 1F1B
        TrainSchedule across all stages (reference train_batch :312)."""
        micros = [next(data_iter) for _ in range(self.num_micro)]
        xs = [m[0] if isinstance(m, (tuple, list)) else m["x"]
              for m in micros]
        ys = [m[1] if isinstance(m, (tuple, list)) else m["y"]
              for m in micros]
        if not self._initialized:
            self._lazy_init(jnp.asarray(xs[0]))

        S, M = self.num_stages, self.num_micro
        schedules = [sched.TrainSchedule(M, S, sid).steps()
                     for sid in range(S)]
        total = len(schedules[0])
        assert all(len(s) == total for s in schedules)

        # per-stage machine state
        inputs = [dict() for _ in range(S)]     # buffer -> stage input
        outputs = [dict() for _ in range(S)]    # buffer -> stage output
        pullbacks = [dict() for _ in range(S)]  # buffer -> vjp fn
        cotangents = [dict() for _ in range(S)]  # buffer -> received grad
        grad_out = [dict() for _ in range(S)]   # buffer -> grad to send up
        grads = [jax.tree.map(jnp.zeros_like, sp) for sp in self.params]
        act_edges = [deque() for _ in range(S)]   # edge (s-1) -> s
        grad_edges = [deque() for _ in range(S)]  # edge (s+1) -> s
        load_ptr = [0]          # next micro to load at stage 0
        label_q = deque()       # labels consumed by last-stage forwards
        losses: List[jnp.ndarray] = []

        def exec_cmd(sid, cmd):
            b = getattr(cmd, "buffer_id", None)
            if isinstance(cmd, sched.LoadMicroBatch):
                i = load_ptr[0]
                load_ptr[0] += 1
                inputs[sid][b] = jnp.asarray(xs[i])
                label_q.append(jnp.asarray(ys[i]))
            elif isinstance(cmd, sched.RecvActivation):
                inputs[sid][b] = act_edges[sid].popleft()
            elif isinstance(cmd, sched.RecvGrad):
                cotangents[sid][b] = grad_edges[sid].popleft()
            elif isinstance(cmd, sched.ForwardPass):
                x = inputs[sid].pop(b)
                if sid == S - 1:
                    y = label_q.popleft()

                    def fwd(sp, xx):
                        out = self._stage_apply(sid, sp, xx)
                        return self.loss_fn(out, y)

                    loss, vjp = jax.vjp(fwd, self.params[sid], x)
                    losses.append(loss)
                    pullbacks[sid][b] = ("loss", vjp)
                else:
                    def fwd(sp, xx):
                        return self._stage_apply(sid, sp, xx)

                    out, vjp = jax.vjp(fwd, self.params[sid], x)
                    outputs[sid][b] = out
                    pullbacks[sid][b] = ("act", vjp)
            elif isinstance(cmd, sched.BackwardPass):
                kind, vjp = pullbacks[sid].pop(b)
                if kind == "loss":
                    cot = jnp.ones((), losses[-1].dtype) / M
                else:
                    cot = cotangents[sid].pop(b)
                gp, gx = vjp(cot)
                if sid > 0:
                    grad_out[sid][b] = gx
                grads[sid] = jax.tree.map(jnp.add, grads[sid], gp)
            elif isinstance(cmd, sched.SendActivation):
                act_edges[sid + 1].append(outputs[sid].pop(b))
            elif isinstance(cmd, sched.SendGrad):
                grad_edges[sid - 1].append(grad_out[sid].pop(b))
            elif isinstance(cmd, sched.ReduceTiedGrads):
                if sid == 0:
                    self._reduce_tied_grads(grads)
            elif isinstance(cmd, sched.ReduceGrads):
                pass    # DP reduction: single-controller — GSPMD handles DP
            elif isinstance(cmd, sched.OptimizerStep):
                if sid == 0:
                    self._optimizer_step(grads)
            else:   # pragma: no cover - unknown instruction
                raise TypeError(f"unknown pipe instruction {cmd!r}")

        # Blocking-p2p semantics (reference pipe/p2p.py): each stage walks
        # its instruction stream in order; a recv with an empty mailbox
        # blocks that stage until the producer's send lands. Round-robin
        # until every stream drains — a correct schedule cannot deadlock.
        streams = [[c for step in schedules[sid] for c in step]
                   for sid in range(S)]
        cursor = [0] * S
        while any(cursor[s] < len(streams[s]) for s in range(S)):
            progressed = False
            for sid in range(S):
                while cursor[sid] < len(streams[sid]):
                    cmd = streams[sid][cursor[sid]]
                    if isinstance(cmd, sched.RecvActivation) \
                            and not act_edges[sid]:
                        break
                    if isinstance(cmd, sched.RecvGrad) \
                            and not grad_edges[sid]:
                        break
                    exec_cmd(sid, cmd)
                    cursor[sid] += 1
                    progressed = True
            if not progressed:
                raise RuntimeError(
                    "pipeline schedule deadlock: every stage blocked on a "
                    "recv — instruction streams are inconsistent")

        self.global_steps += 1
        return float(jnp.mean(jnp.stack(losses)))

    def _reduce_tied_grads(self, grads):
        """Sum tie-group gradients across stages, broadcast back
        (reference _exec_reduce_tied_grads)."""
        groups: Dict[str, List] = {}
        for sid in range(self.num_stages):
            for li, tie in enumerate(self._tie_key_of[sid]):
                if tie is not None:
                    groups.setdefault(tie, []).append((sid, li))
        for tie, sites in groups.items():
            if len(sites) < 2:
                continue
            total = None
            for sid, li in sites:
                g = grads[sid][li]
                total = g if total is None else jax.tree.map(jnp.add,
                                                             total, g)
            for sid, li in sites:
                grads[sid][li] = total

    def _optimizer_step(self, grads):
        tied_updated: Dict[str, Any] = {}
        for sid in range(self.num_stages):
            new_p, new_o = self._opt.step(self.params[sid], grads[sid],
                                          self.opt_state[sid],
                                          getattr(self._opt, "lr", 1e-2))
            self.params[sid] = list(new_p)
            self.opt_state[sid] = new_o
        # re-share tied params (each stage stepped its own copy with the
        # same summed grad + same state ⇒ identical values; aliasing keeps
        # future updates in lockstep)
        for sid in range(self.num_stages):
            for li, tie in enumerate(self._tie_key_of[sid]):
                if tie is None:
                    continue
                if tie in tied_updated:
                    self.params[sid][li] = tied_updated[tie]
                else:
                    tied_updated[tie] = self.params[sid][li]

    # ---------------------------------------------------------- inference
    def eval_batch(self, x) -> jnp.ndarray:
        """Forward-only fill-drain (InferenceSchedule :135): one micro."""
        if not self._initialized:
            self._lazy_init(jnp.asarray(x))
        out = jnp.asarray(x)
        S = self.num_stages
        streams = [[c for step in sched.InferenceSchedule(1, S, sid).steps()
                    for c in step] for sid in range(S)]
        act_edges = [deque() for _ in range(S)]
        vals = [None] * S
        cursor = [0] * S
        while any(cursor[s] < len(streams[s]) for s in range(S)):
            progressed = False
            for sid in range(S):
                while cursor[sid] < len(streams[sid]):
                    cmd = streams[sid][cursor[sid]]
                    if isinstance(cmd, sched.RecvActivation) \
                            and not act_edges[sid]:
                        break
                    if isinstance(cmd, sched.LoadMicroBatch):
                        vals[sid] = out
                    elif isinstance(cmd, sched.RecvActivation):
                        vals[sid] = act_edges[sid].popleft()
                    elif isinstance(cmd, sched.ForwardPass):
                        vals[sid] = self._stage_apply(
                            sid, self.params[sid], vals[sid])
                    elif isinstance(cmd, sched.SendActivation):
                        act_edges[sid + 1].append(vals[sid])
                    cursor[sid] += 1
                    progressed = True
            if not progressed:
                raise RuntimeError("inference schedule deadlock")
        return vals[S - 1]
