"""Pipeline module: layer specs and stage partitioning.

Counterpart of reference ``runtime/pipe/module.py`` (``LayerSpec`` :30,
``TiedLayerSpec`` :77, ``PipelineModule`` :86 with uniform / parameter-count
/ regex partitioning). On TPU the stage assignment produced here feeds the
SPMD pipeline (parallel/pipeline.py) — with scan-over-layers models the
partition is implicit (contiguous L/P slices), but arbitrary layer lists
with heterogeneous costs still need the balanced-partition solver.
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional, Sequence


class LayerSpec:
    """Deferred layer construction (reference pipe/module.py:30)."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """Layer whose params are shared across stages (reference :77 — e.g.
    tied embedding/unembedding). ``key`` names the tie group."""

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="weight", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Balanced contiguous partition minimizing the max part weight
    (reference deepspeed/runtime/utils.py partition_balanced — solved here
    by binary search over the bottleneck + greedy fill)."""
    n = len(weights)
    if num_parts >= n:
        return list(range(n + 1)) + [n] * (num_parts - n)

    def parts_needed(limit: float) -> Optional[List[int]]:
        bounds = [0]
        acc = 0.0
        for i, w in enumerate(weights):
            if w > limit:
                return None
            if acc + w > limit:
                bounds.append(i)
                acc = w
            else:
                acc += w
        bounds.append(n)
        return bounds if len(bounds) - 1 <= num_parts else None

    lo, hi = max(weights), sum(weights)
    for _ in range(60):
        mid = (lo + hi) / 2
        if parts_needed(mid) is not None:
            hi = mid
        else:
            lo = mid
    bounds = parts_needed(hi)
    # pad to exactly num_parts by splitting trailing empty parts
    while len(bounds) - 1 < num_parts:
        bounds.append(n)
    return bounds


class PipelineModule:
    """Partitions a layer list across pipeline stages.

    ``layers``: list of LayerSpec / callables. ``partition_method``:
    "uniform" | "parameters" | "type:regex" (reference pipe/module.py:382
    ``_partition_layers``).
    """

    def __init__(self, layers, num_stages: int,
                 partition_method: str = "parameters",
                 loss_fn: Optional[Callable] = None,
                 activation_checkpoint_interval: int = 0,
                 param_count_fn: Optional[Callable] = None):
        self.layer_specs = list(layers)
        self.num_stages = num_stages
        self.partition_method = partition_method
        self.loss_fn = loss_fn
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self._param_count_fn = param_count_fn or self._default_param_count
        self.parts = self._partition_layers()

    @staticmethod
    def _default_param_count(spec) -> int:
        if isinstance(spec, LayerSpec):
            cnt = spec.module_kwargs.get("num_params")
            if cnt is not None:
                return int(cnt)
            built = None
            try:
                built = spec.build()
            except Exception:
                return 1
            spec = built
        if hasattr(spec, "num_params"):
            try:
                return int(spec.num_params())
            except Exception:
                return 1
        return 1

    def _partition_layers(self) -> List[int]:
        n = len(self.layer_specs)
        method = self.partition_method.lower()
        if method == "uniform":
            weights = [1.0] * n
        elif method == "parameters":
            weights = [float(self._param_count_fn(s)) for s in self.layer_specs]
        elif method.startswith("type:"):
            pattern = method.split(":", 1)[1]
            weights = [1.0 if re.search(
                pattern, getattr(getattr(s, "typename", s), "__name__",
                                 str(s)), re.IGNORECASE) else 0.0
                for s in self.layer_specs]
            if sum(weights) == 0:
                raise ValueError(f"no layers matched type regex {pattern!r}")
        else:
            raise ValueError(f"unknown partition_method {self.partition_method!r}")
        return partition_balanced(weights, self.num_stages)

    def stage_layers(self, stage_id: int) -> List:
        lo, hi = self.parts[stage_id], self.parts[stage_id + 1]
        return self.layer_specs[lo:hi]

    def stage_owner(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise ValueError(f"layer {layer_idx} out of range")

    @property
    def tied_keys(self):
        return sorted({s.key for s in self.layer_specs
                       if isinstance(s, TiedLayerSpec)})
