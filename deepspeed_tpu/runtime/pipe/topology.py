"""Cartesian process topology for hybrid parallelism.

Counterpart of reference ``runtime/pipe/topology.py`` (``ProcessTopology``
:12 — axes/dims grid with rank↔coordinate mapping and filtered queries;
``PipeModelDataParallelTopology`` :244). On TPU the live grid is the
``jax.sharding.Mesh`` (parallel/topology.py); this class remains the
rank-arithmetic view used by the pipe module partitioner, checkpoint
layouts, and parity tests.
"""

from __future__ import annotations

from collections import namedtuple
from itertools import product
from typing import Dict, List


class ProcessTopology:
    """Maps n-dimensional axis coordinates ↔ linear ranks. Axes are ordered
    outer-to-inner (first axis varies slowest), matching the reference."""

    def __init__(self, axes: List[str], dims: List[int]):
        if len(axes) != len(dims):
            raise ValueError("axes and dims must have equal length")
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        self.mapping = {}
        for coord in product(*(range(d) for d in dims)):
            key = self.ProcessCoord(*coord)
            self.mapping[key] = len(self.mapping)

    def get_rank(self, **coord_kwargs) -> int:
        if sorted(coord_kwargs) != sorted(self.axes):
            raise ValueError(f"expected axes {self.axes}, got {sorted(coord_kwargs)}")
        return self.mapping[self.ProcessCoord(**coord_kwargs)]

    def get_axis_names(self) -> List[str]:
        return list(self.axes)

    def get_rank_repr(self, rank: int, omit_axes=("data",), inner_sep="_",
                      outer_sep="-") -> str:
        omit = set(omit_axes)
        coord = self.get_coord(rank)
        parts = [f"{a}{inner_sep}{getattr(coord, a):02d}"
                 for a in self.axes if a not in omit]
        return outer_sep.join(parts)

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)] if axis in self.axes else 0

    def get_coord(self, rank: int):
        for coord, r in self.mapping.items():
            if r == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks that vary only along ``axis`` (the reference's
        process-group construction input)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for other_coord in product(*(range(self.get_dim(a)) for a in other_axes)):
            fixed = dict(zip(other_axes, other_coord))
            ranks = [self.get_rank(**{axis: i, **fixed})
                     for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        return sorted(r for coord, r in self.mapping.items()
                      if all(getattr(coord, k) == v for k, v in filter_kwargs.items()))

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        return self.filter_match(**{axis: idx})

    def world_size(self) -> int:
        return len(self.mapping)

    def __str__(self):
        return str(self.mapping)


class PipeModelDataParallelTopology(ProcessTopology):
    """3D pipe×model(tensor)×data grid (reference pipe/topology.py:244)."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


class PipeDataParallelTopology(ProcessTopology):
    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])
