"""Activation checkpointing API.

Counterpart of reference ``runtime/activation_checkpointing/checkpointing.py``
(``checkpoint`` :984, ``CheckpointFunction`` :485, ``configure`` :1065,
``CudaRNGStatesTracker`` :122). The mechanism is ``jax.checkpoint``
(rematerialization): the forward is traced once and recomputed in the
backward per the chosen policy — so most of the reference's machinery is
the compiler's job here:

- *partitioned activations across TP* → under GSPMD, saved residuals keep
  their shardings; there is nothing to partition by hand.
- *CPU checkpointing* → ``jax.checkpoint`` + offload policies
  (``save_and_offload_only_these_names``) when host offload is wanted;
  the engine's remat config covers the common cases.
- *contiguous memory buffers* → XLA's allocator owns layout.
- *RNG state tracking for dropout determinism* → JAX PRNG keys are values,
  not global state: the same key in forward and recompute is deterministic
  by construction, which is the entire job of the reference's
  ``CudaRNGStatesTracker``.

The reference's call surface is kept so Megatron-style model code ports
unchanged: ``checkpoint(fn, *args)`` runs ``fn`` under remat,
``configure(...)`` records the config, the boolean probes answer from it.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax

from ..utils.logging import logger

_config = {
    "partition_activations": False,
    "contiguous_memory_optimization": False,
    "cpu_checkpointing": False,
    "num_checkpoints": None,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
}

_POLICIES = {
    None: None,
    "dots_saveable": "dots_saveable",
    "nothing_saveable": "nothing_saveable",
}


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Reference ``configure`` (:1065): record the checkpointing options.
    On TPU these inform policy choice; partitioning/contiguity are XLA's
    concern (module docstring)."""
    if deepspeed_config is not None:
        ac = getattr(deepspeed_config, "activation_checkpointing", None)
        if ac is not None:
            _config["partition_activations"] = ac.partition_activations
            _config["contiguous_memory_optimization"] = \
                ac.contiguous_memory_optimization
            _config["cpu_checkpointing"] = ac.cpu_checkpointing
            _config["num_checkpoints"] = ac.number_checkpoints
            _config["profile"] = ac.profile
    for key, val in (("partition_activations", partition_activations),
                     ("contiguous_memory_optimization",
                      contiguous_checkpointing),
                     ("num_checkpoints", num_checkpoints),
                     ("cpu_checkpointing", checkpoint_in_cpu),
                     ("synchronize_checkpoint_boundary", synchronize),
                     ("profile", profile)):
        if val is not None:
            _config[key] = val
    if _config["cpu_checkpointing"]:
        logger.warning(
            "cpu_checkpointing: host offload of residuals is policy-driven "
            "on TPU (jax.checkpoint offload policies); the engine's "
            "remat_policy handles the standard cases")


def is_configured() -> bool:
    return True     # jax.checkpoint needs no global setup


def partition_activations_in_checkpoint(partition: bool) -> None:
    _config["partition_activations"] = bool(partition)


def checkpoint(function: Callable, *args, policy: Optional[str] = None,
               static_argnums=()) -> Any:
    """Reference ``checkpoint`` (:984): run ``function(*args)`` storing
    only the inputs (plus what ``policy`` saves); the backward recomputes
    the rest. Differentiable through ``jax.grad`` like any JAX function."""
    pol = None
    if policy == "dots_saveable":
        pol = jax.checkpoint_policies.dots_saveable
    elif policy == "nothing_saveable":
        pol = jax.checkpoint_policies.nothing_saveable
    elif policy is not None:
        raise ValueError(f"unknown remat policy {policy!r}")
    wrapped = jax.checkpoint(function, policy=pol,
                             static_argnums=tuple(static_argnums))
    return wrapped(*args)


class CheckpointFunction:
    """API-parity alias (reference ``CheckpointFunction`` :485 is a torch
    autograd.Function; functional JAX needs only the wrapper above)."""

    @staticmethod
    def apply(function, *args):
        return checkpoint(function, *args)


def get_rng_tracker():
    """Reference ``get_cuda_rng_tracker``: JAX PRNG keys are explicit
    values — recompute under ``jax.checkpoint`` replays the same keys, so
    dropout is deterministic with no tracker. Returns None."""
    return None


def model_parallel_cuda_manual_seed(seed: int) -> None:
    """Reference RNG seeding hook: a no-op — seeds flow through PRNG keys
    (`jax.random.PRNGKey(seed)` at engine init)."""
