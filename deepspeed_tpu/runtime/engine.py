"""DeepSpeedTpuEngine — the core training runtime.

Counterpart of reference ``runtime/engine.py:175`` (``DeepSpeedEngine``):
same lifecycle (``forward`` :1757 / ``backward`` :1898 / ``step`` :2096,
gradient accumulation, clipping, dynamic fp16 loss scaling
``runtime/fp16/loss_scaler.py:91``, checkpoint save/load :3006/:2657,
throughput + wall-clock timers) — re-designed around XLA:

- The train state (master fp32 params, optimizer moments, gradient
  accumulator, loss-scale state, counters) is one pytree whose shardings are
  produced by the ZeRO plan (``parallel/sharding.py``). ZeRO stages 1/2/3 are
  *out_shardings*, not subsystems.
- ``forward`` runs a single jitted fwd+bwd+accumulate program (a functional
  runtime cannot split autograd across host calls without recomputing;
  ``backward(loss)`` is the API-parity no-op that advances the micro-step,
  matching the contract ``loss = engine(batch); engine.backward(loss);
  engine.step()``).
- ``step`` runs the jitted update program at accumulation boundaries:
  unscale, global-norm clip, overflow-gated optimizer step (``lax.cond`` —
  the reference's ``_take_model_step`` overflow skip), loss-scale update,
  schedule-computed LR (traced — no host round trip).
- Buffer donation keeps params/moments in-place in HBM.
"""

from __future__ import annotations

import dataclasses
import os
import time
from functools import partial
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import comm as dist
from ..models.transformer import CausalLM
from ..ops.optimizers import OptimizerState, build_optimizer, FusedAdam
from ..parallel import topology as topo
from ..parallel.sharding import ZeroShardingPlan
from ..utils.logging import logger, log_dist
from ..utils.timer import (FORWARD_MICRO_TIMER, STEP_GLOBAL_TIMER,
                           SynchronizedWallClockTimer, ThroughputTimer)
from .config import DeepSpeedTpuConfig, DtypeEnum, load_config
from .lr_schedules import LRSchedulerShim, build_schedule
from .dataloader import DeepSpeedTpuDataLoader


class ScaleState(NamedTuple):
    """Dynamic loss scale state (reference fp16/loss_scaler.py:91)."""
    scale: jnp.ndarray        # f32 scalar
    good_steps: jnp.ndarray   # i32 scalar
    hysteresis: jnp.ndarray   # i32 scalar


class TrainState(NamedTuple):
    params: Any               # fp32 master weights
    opt_state: OptimizerState
    grad_acc: Any             # fp32 accumulator (scaled grads summed)
    scale_state: ScaleState
    global_step: jnp.ndarray  # i32
    skipped_steps: jnp.ndarray  # i32


class DeepSpeedTpuEngine:
    """See module docstring. Construct via ``deepspeed_tpu.initialize``."""

    def __init__(self, args=None, model=None, optimizer=None,
                 model_parameters=None, training_data=None, lr_scheduler=None,
                 mesh=None, collate_fn=None, config=None, rng=None):
        self.config: DeepSpeedTpuConfig = load_config(
            getattr(args, "deepspeed_config", None) if config is None else config)
        dist.init_distributed(config=self.config)

        # -- topology ------------------------------------------------------
        if mesh is not None:
            self.topology = mesh if isinstance(mesh, topo.MeshTopology) else topo.MeshTopology(mesh)
        elif topo.has_topology():
            self.topology = topo.get_topology()
        else:
            self.topology = topo.MeshTopology.build(self.config.mesh)
        # -- MiCS (reference runtime/zero/mics.py:55 MiCS_Init) -------------
        # mics_shard_size=k shards params over a k-sized sub-group and
        # replicates across the rest of the DP world. TPU-natively the
        # sub-group IS the fsdp mesh axis (ICI-contiguous), replication is
        # the data axis — so honoring the flag means shaping the mesh, after
        # which the ZeRO-3 plan + XLA collectives do the rest (the
        # hierarchical gather of mics.py:227 is XLA's collective scheduling
        # over ICI/DCN; mics_hierarchical_params_gather needs no manual
        # two-phase gather here).
        zc0 = self.config.zero_optimization
        if zc0.mics_shard_size and zc0.mics_shard_size > 0:
            k = int(zc0.mics_shard_size)
            if zc0.stage != 3:
                raise ValueError(
                    f"mics_shard_size={k} requires zero_optimization.stage=3 "
                    "(MiCS is a ZeRO-3 variant, reference mics.py:55)")
            fsdp_size = self.topology.mesh.shape.get("fsdp", 1)
            if fsdp_size != k:
                if mesh is None and fsdp_size == 1 \
                        and self.topology.world_size % k == 0:
                    # engine-built default mesh: reshape fsdp to the shard
                    # group, data soaks up the replication factor
                    self.topology = topo.MeshTopology.build(
                        self.config.mesh, fsdp=k, data=-1)
                else:
                    raise ValueError(
                        f"mics_shard_size={k} conflicts with the mesh fsdp "
                        f"axis ({fsdp_size}); size the fsdp axis to the MiCS "
                        "shard group (params shard over fsdp, replicate over "
                        "data)")
            log_dist(
                f"MiCS: shard group={k} (fsdp axis), replication="
                f"{self.topology.axis_size('data')} (data axis)", ranks=[0])
        topo.set_topology(self.topology)
        self.mesh = self.topology.mesh

        self._apply_elasticity()
        self.config.resolve_batch_sizes(self.topology.get_data_parallel_world_size())

        # -- model ---------------------------------------------------------
        self.module = self._resolve_model(model)
        self.zero_stage = self.config.zero_optimization.stage
        spec_tree = (self.module.param_specs()
                     if hasattr(self.module, "param_specs") else None)
        hpz_size = self.config.zero_optimization.zero_hpz_partition_size
        if hpz_size > 1:
            # hpZ maps the secondary (weight-shard) group onto the fsdp mesh
            # axis and the primary partition onto fsdp×data; the configured
            # group size must therefore equal the fsdp axis size — honoring
            # an arbitrary size would need a different mesh, so reject
            # rather than silently reinterpret (reference zero/config.py:256).
            fsdp_size = self.topology.mesh.shape.get("fsdp", 1)
            if hpz_size != fsdp_size:
                raise ValueError(
                    f"zero_hpz_partition_size={hpz_size} must equal the mesh "
                    f"fsdp axis size ({fsdp_size}); size the mesh's fsdp axis "
                    "to the intended secondary-partition group")
        self.plan = ZeroShardingPlan(
            self.topology, self.zero_stage, spec_tree, hpz=hpz_size > 1)

        # -- precision -----------------------------------------------------
        self.precision = self.config.precision
        self.compute_dtype = self.precision.to_jnp()
        self.fp16_enabled = self.precision == DtypeEnum.fp16
        self.bf16_enabled = self.precision == DtypeEnum.bf16
        self.dynamic_loss_scale = self.fp16_enabled and self.config.fp16.loss_scale == 0
        self._static_scale = (self.config.fp16.loss_scale
                              if self.fp16_enabled and not self.dynamic_loss_scale else 1.0)

        # -- optimizer + schedule -----------------------------------------
        oc = self.config.optimizer
        self.client_optimizer = optimizer
        if optimizer is not None and not isinstance(optimizer, str):
            self.opt = optimizer  # duck-typed: init/step
        else:
            self.opt = build_optimizer(oc.type if oc else "Adam",
                                       oc.params if oc else {"lr": 1e-3})
        # 1-bit optimizers take over gradient communication (ops/onebit.py):
        # the engine computes unreduced per-worker grads under shard_map and
        # the optimizer owns the (compressed) cross-worker reduction —
        # reference runtime/engine.py:1194 likewise skips the engine
        # allreduce for these optimizer types.
        from ..ops.onebit import OneBitOptimizer

        self._onebit = isinstance(self.opt, OneBitOptimizer)
        if self._onebit:
            bad_axes = {a: s for a, s in dict(self.mesh.shape).items()
                        if a != "data" and s > 1}
            if bad_axes:
                raise ValueError(
                    "1-bit optimizers require pure data parallelism (they "
                    f"own the gradient reduction); mesh has {bad_axes}")
            if self.zero_stage > 1:
                raise ValueError(
                    "1-bit optimizers require zero_optimization.stage <= 1 "
                    "(reference onebit/adam.py compatibility constraint)")
            if self._offload_config() is not None:
                raise ValueError("1-bit optimizers are incompatible with "
                                 "optimizer offload")
            self.opt.dp_size = self.topology.get_data_parallel_world_size()

        base_lr = getattr(self.opt, "lr", 1e-3)
        sc = self.config.scheduler
        if lr_scheduler is not None:
            self.schedule = lr_scheduler  # callable step -> lr
        else:
            self.schedule = build_schedule(sc.type if sc else None,
                                           sc.params if sc else None,
                                           fallback_lr=base_lr)
        self.lr_scheduler = LRSchedulerShim(
            self.schedule,
            step_source=lambda: int(self.state.global_step)
            if getattr(self, "state", None) is not None else 0)

        # -- state init (sharded from birth — zero.Init role) --------------
        self._rng = rng if rng is not None else jax.random.PRNGKey(self.config.seed)
        self.state = self._init_state()

        # -- ZeRO++ (qwZ/qgZ explicit quantized collectives) ---------------
        self._setup_zeropp()

        # -- data ----------------------------------------------------------
        self.training_dataloader = None
        self._data_iter = None  # persistent train_batch iterator (ADVICE r1)
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data, collate_fn=collate_fn)

        # -- curriculum learning (seqlen curriculum; reference engine.py
        # curriculum legacy path + data_pipeline/curriculum_scheduler.py) --
        self.curriculum_scheduler = None
        cl = self.config.curriculum_learning or {}
        if cl.get("enabled"):
            from .data_pipeline import CurriculumScheduler

            self.curriculum_scheduler = CurriculumScheduler(cl)

        # -- compression (QAT/pruning baked into the step programs) --------
        self._compression = None
        if self.config.compression_training:
            from ..compression import CompressionTransform

            ct = CompressionTransform(
                {"compression_training": self.config.compression_training})
            if ct:
                self._compression = ct

        # -- step programs -------------------------------------------------
        self._build_step_fns()

        # -- counters / telemetry -----------------------------------------
        self.micro_steps = 0          # micro steps since engine start
        self.global_steps = 0         # host mirror of state.global_step
        # NOTE: skipped_steps is a property over state.skipped_steps — the
        # device counter is authoritative and reading it lazily avoids a
        # host-device sync on every optimizer boundary (ADVICE r1 / review r2).
        self._pending_loss = None
        self._last_lr = float(self.schedule(0))
        self.timers = SynchronizedWallClockTimer(sync_fn=self._sync)
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size(),
            steps_per_output=self.config.steps_per_print,
            monitor_memory=self.config.memory_breakdown)
        self.monitor = self._build_monitor()
        # step profiling (docs/OBSERVABILITY.md "Step profiling"):
        # wall_clock_breakdown (reference engine.py flag) or an enabled
        # telemetry block brackets fwd+bwd and the optimizer step with
        # synchronized timers — a block_until_ready per bracket, so real
        # device time is measured, at a small throughput cost — and
        # records matching spans on the tracer ("train" trace).
        self.tracer = self.config.telemetry.build_tracer()
        self._profile_steps = bool(self.config.wall_clock_breakdown
                                   or self.config.telemetry.enabled)

        log_dist(
            f"DeepSpeedTpuEngine ready: mesh={dict(self.mesh.shape)} "
            f"zero_stage={self.zero_stage} precision={self.precision.value} "
            f"micro_batch={self.train_micro_batch_size_per_gpu()} "
            f"gas={self.gradient_accumulation_steps()}", ranks=[0])

    # ------------------------------------------------------------------ setup
    def _apply_elasticity(self):
        """Elastic batch config (reference elasticity/elasticity.py:233 via
        runtime/config.py elasticity hookup): validate the current chip
        count against the elastic config's valid set and, with
        ``ignore_non_elastic_batch_info``, adopt the elastic
        (batch, micro, gas) for this world size. Scale-up/down is
        restart-based: universal checkpoints resume on any valid mesh."""
        ec = self.config.elasticity
        if not ec.enabled:
            return
        from ..elasticity import (ElasticityConfigError,
                                  ElasticityIncompatibleWorldSize,
                                  compute_elastic_config)

        batch_keys_set = any(
            isinstance(v, int) for v in (self.config.train_batch_size,
                                         self.config.train_micro_batch_size_per_gpu,
                                         self.config.gradient_accumulation_steps))
        if batch_keys_set and not ec.ignore_non_elastic_batch_info:
            raise ElasticityConfigError(
                "elasticity is enabled but batch sizes are also set; remove "
                "them or set elasticity.ignore_non_elastic_batch_info "
                "(reference elasticity adopts the same all-or-nothing rule)")
        world = self.topology.world_size
        batch, valid, micro = compute_elastic_config(
            {"elasticity": {
                "enabled": True,
                "max_train_batch_size": ec.max_train_batch_size,
                "micro_batch_sizes": list(ec.micro_batch_sizes),
                "min_gpus": ec.min_gpus, "max_gpus": ec.max_gpus,
                "version": ec.version,
                "prefer_larger_batch": ec.prefer_larger_batch,
                "model_parallel_size": ec.model_parallel_size,
                "num_gpus_per_node": ec.num_gpus_per_node}},
            world_size=world, return_microbatch=True)
        dp = self.topology.get_data_parallel_world_size()
        if micro is None or batch % (micro * dp):
            raise ElasticityIncompatibleWorldSize(
                f"elastic batch {batch} unreachable with dp={dp} and micro "
                f"candidates {list(ec.micro_batch_sizes)}")
        self.config.train_batch_size = batch
        self.config.train_micro_batch_size_per_gpu = micro
        self.config.gradient_accumulation_steps = batch // (micro * dp)
        log_dist(
            f"elasticity: batch={batch} micro={micro} "
            f"gas={self.config.gradient_accumulation_steps} "
            f"valid_chips={valid}", ranks=[0])

    def _resolve_model(self, model):
        if model is None:
            raise ValueError("model is required")
        if isinstance(model, str):
            from ..models import build_model

            return build_model(model)
        return model

    def _sync(self):
        jax.block_until_ready(self.state.params) if self.state else None

    def _build_monitor(self):
        try:
            from ..monitor.monitor import MonitorMaster

            return MonitorMaster(self.config)
        except Exception:
            return None

    def _model_dtype_override(self):
        """Push engine precision + pipeline/remat settings into the model
        config when the model is a framework CausalLM."""
        if not isinstance(self.module, CausalLM):
            return
        over = {}
        if self.module.cfg.dtype != self.compute_dtype:
            over["dtype"] = self.compute_dtype
        pmb = self.config.pipeline.micro_batches
        if pmb and self.module.cfg.pipeline_microbatches != pmb:
            over["pipeline_microbatches"] = pmb
        if over:
            self.module = CausalLM(dataclasses.replace(self.module.cfg, **over))

    def _offload_config(self):
        oc = self.config.zero_optimization.offload_optimizer
        if oc is None or str(oc.device.value) == "none":
            return None
        return oc

    def _setup_zeropp(self):
        """ZeRO++ qwZ/qgZ: install explicit quantized-collective transforms
        on the model (reference partition_parameters.py:679 CUDAQuantizer +
        coalesced_collectives.py:31 all_to_all_quant_reduce; see
        parallel/zeropp.py for the TPU formulation)."""
        zc = self.config.zero_optimization
        if not (zc.zero_quantized_weights or zc.zero_quantized_gradients):
            return
        if self.zero_stage < 3:
            raise ValueError(
                "zero_quantized_weights/gradients (ZeRO++) require "
                f"zero_optimization.stage=3, got stage={self.zero_stage}")
        if not isinstance(self.module, CausalLM):
            raise ValueError("ZeRO++ transforms require a framework CausalLM "
                             "(custom modules: wire parallel/zeropp.py "
                             "make_quantized_gather_transform directly)")
        from jax.sharding import PartitionSpec

        from ..parallel.zeropp import make_quantized_gather_transform

        qw = 8 if zc.zero_quantized_weights else None
        qg = 8 if zc.zero_quantized_gradients else None
        # per-layer view: strip the stacked-layers leading dim from each spec
        layer_specs = {k: PartitionSpec(*ns.spec[1:])
                       for k, ns in self._param_shardings["layers"].items()}
        self.module.layer_transform = make_quantized_gather_transform(
            self.mesh, layer_specs, qw_bits=qw, qg_bits=qg)
        g_specs = {}
        for grp in ("embed", "final_norm", "lm_head"):
            for k, ns in self._param_shardings.get(grp, {}).items():
                g_specs[f"{grp}.{k}"] = ns.spec
        self.module.global_transform = make_quantized_gather_transform(
            self.mesh, g_specs, qw_bits=qw, qg_bits=qg)
        if self.module.layer_transform or self.module.global_transform:
            log_dist(f"ZeRO++ enabled: qwZ={bool(qw)} qgZ={bool(qg)}",
                     ranks=[0])

    def _init_state(self) -> TrainState:
        self._model_dtype_override()
        init_rng, self._rng = jax.random.split(self._rng)

        # Init params already sharded (the reference's zero.Init
        # partition_parameters.py:734 — params never exist unsharded).
        shapes = jax.eval_shape(self.module.init, init_rng)
        p_shard = self.plan.params(shapes)
        params = jax.jit(self.module.init, out_shardings=p_shard)(init_rng)

        # ZeRO-Offload: split leaves between host optimizer and device
        oc = self._offload_config()
        self._offload_plan = None
        if oc is not None:
            from .zero_offload import OffloadOptimizerPlan

            opt_cfg = self.config.optimizer
            self._offload_plan = OffloadOptimizerPlan(
                params, opt_cfg.type if opt_cfg else "Adam",
                opt_cfg.params if opt_cfg else {},
                device=str(oc.device.value), ratio=oc.ratio,
                nvme_path=oc.nvme_path,
                aio_threads=self.config.aio.thread_count)

        if self._offload_plan is not None:
            # device optimizer covers only the non-offloaded subtree
            p_leaves = jax.tree_util.tree_flatten(params)[0]
            s_leaves = jax.tree_util.tree_flatten(p_shard)[0]
            kept = {str(i): p_leaves[i] for i in self._offload_plan.kept}
            kept_shard = {str(i): s_leaves[i] for i in self._offload_plan.kept}
            opt_shapes = jax.eval_shape(self.opt.init, kept)
            o_shard = OptimizerState(
                step=self.plan.replicated(),
                moments={mk: kept_shard for mk in opt_shapes.moments})
            opt_state = jax.jit(self.opt.init, out_shardings=o_shard)(kept)
        elif self._onebit:
            # Error-feedback moments are per-worker state: leading dp axis,
            # sharded over the data mesh axis (ops/onebit.py contract).
            from jax.sharding import NamedSharding, PartitionSpec

            dspec = NamedSharding(self.mesh, PartitionSpec("data"))
            rep = self.plan.replicated()
            opt_shapes = jax.eval_shape(self.opt.init, params)
            o_moments = {
                k: jax.tree.map(
                    lambda _: dspec if k in self.opt.dp_moment_keys else rep,
                    sub)
                for k, sub in opt_shapes.moments.items()}
            o_shard = OptimizerState(step=rep, moments=o_moments)
            opt_state = jax.jit(self.opt.init, out_shardings=o_shard)(params)
        else:
            opt_shapes = jax.eval_shape(self.opt.init, params)
            o_shard = OptimizerState(
                step=self.plan.replicated(),
                moments=self.plan.opt_state(opt_shapes.moments))
            opt_state = jax.jit(self.opt.init, out_shardings=o_shard)(params)

        if self._onebit:
            # Per-worker (unreduced) gradient accumulators: leading dp axis
            # sharded over 'data' — each worker accumulates its own grads;
            # the optimizer's compressed collective does the averaging.
            from jax.sharding import NamedSharding, PartitionSpec

            dp = self.topology.get_data_parallel_world_size()
            acc_shapes = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct((dp,) + s.shape, s.dtype),
                shapes)
            g_shard = jax.tree.map(
                lambda _: NamedSharding(self.mesh, PartitionSpec("data")),
                acc_shapes)
            grad_acc = jax.jit(
                lambda: jax.tree.map(jnp.zeros_like, acc_shapes),
                out_shardings=g_shard)()
        else:
            g_shard = self.plan.grads(shapes)
            grad_acc = jax.jit(lambda: jax.tree.map(jnp.zeros_like, shapes),
                               out_shardings=g_shard)()

        scale0 = (2.0 ** self.config.fp16.initial_scale_power
                  if self.dynamic_loss_scale else self._static_scale)
        scale_state = ScaleState(
            scale=jnp.asarray(scale0, jnp.float32),
            good_steps=jnp.zeros((), jnp.int32),
            hysteresis=jnp.asarray(self.config.fp16.hysteresis, jnp.int32))
        self._param_shardings = p_shard
        self._opt_shardings = o_shard
        self._grad_shardings = g_shard
        return TrainState(params=params, opt_state=opt_state, grad_acc=grad_acc,
                          scale_state=scale_state,
                          global_step=jnp.zeros((), jnp.int32),
                          skipped_steps=jnp.zeros((), jnp.int32))

    # ----------------------------------------------------------- jitted steps
    def _build_step_fns(self):
        plan = self.plan
        module = self.module
        opt = self.opt
        schedule = self.schedule
        gas = self.gradient_accumulation_steps()
        clip = self.config.gradient_clipping
        fp16 = self.fp16_enabled
        dynamic = self.dynamic_loss_scale
        fpc = self.config.fp16
        predivide = self.config.prescale_gradients
        dp_size = self.topology.get_data_parallel_world_size()

        state_shardings = TrainState(
            params=self._param_shardings,
            opt_state=self._opt_shardings,
            grad_acc=self._grad_shardings,
            scale_state=ScaleState(*(plan.replicated(),) * 3),
            global_step=plan.replicated(),
            skipped_steps=plan.replicated())
        self._state_shardings = state_shardings
        batch_sharding = plan.batch()

        compression = self._compression

        def micro(state: TrainState, batch, rng):
            """fwd + bwd + accumulate (one micro batch)."""
            scale = state.scale_state.scale

            def loss_fn(params):
                if compression is not None:   # QAT/pruning: STE to masters
                    params = compression(params, state.global_step)
                loss = module.loss(params, batch, rng)
                return (loss * scale / (dp_size if predivide else 1.0)).astype(jnp.float32), loss

            grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
            grad_acc = jax.tree.map(jnp.add, state.grad_acc, grads)
            return state._replace(grad_acc=grad_acc), loss

        def unscale_and_clip(state: TrainState):
            scale = state.scale_state.scale
            denom = scale * gas / (dp_size if predivide else 1.0)
            grads = jax.tree.map(lambda g: g / denom, state.grad_acc)
            flat = jax.tree.leaves(grads)
            sumsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in flat)
            gnorm = jnp.sqrt(sumsq)
            overflow = ~jnp.isfinite(gnorm)
            if clip > 0:
                coeff = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * coeff, grads)
            return grads, gnorm, overflow

        def next_scale_state(ss: ScaleState, overflow):
            """Dynamic loss scale automaton (reference loss_scaler.py:136)."""
            if not (fp16 and dynamic):
                return ss
            window = fpc.loss_scale_window
            min_scale = fpc.min_loss_scale

            def on_overflow(s):
                new_h = jnp.maximum(s.hysteresis - 1, 0)
                shrink = new_h <= 0
                new_scale = jnp.where(
                    shrink, jnp.maximum(s.scale / 2.0, min_scale), s.scale)
                return ScaleState(
                    scale=new_scale, good_steps=jnp.zeros((), jnp.int32),
                    hysteresis=jnp.where(
                        shrink, jnp.asarray(fpc.hysteresis, jnp.int32), new_h))

            def on_good(s):
                grown = s.good_steps + 1 >= window
                return ScaleState(
                    scale=jnp.where(grown, s.scale * 2.0, s.scale),
                    good_steps=jnp.where(grown, 0, s.good_steps + 1).astype(jnp.int32),
                    hysteresis=s.hysteresis)

            return lax.cond(overflow, on_overflow, on_good, ss)

        def book_keeping(state, new_params, new_opt, overflow):
            zero_acc = jax.tree.map(jnp.zeros_like, state.grad_acc)
            return TrainState(
                params=new_params, opt_state=new_opt, grad_acc=zero_acc,
                scale_state=next_scale_state(state.scale_state, overflow),
                global_step=state.global_step + jnp.where(overflow, 0, 1),
                skipped_steps=state.skipped_steps + jnp.where(overflow, 1, 0))

        def update(state: TrainState):
            """unscale → clip → (overflow-gated) optimizer step → new scale."""
            grads, gnorm, overflow = unscale_and_clip(state)
            lr = schedule(state.global_step)

            def do_step(_):
                return opt.step(state.params, grads, state.opt_state, lr)

            def skip(_):
                return state.params, state.opt_state

            new_params, new_opt = lax.cond(overflow, skip, do_step, None)
            new_state = book_keeping(state, new_params, new_opt, overflow)
            metrics = {"grad_norm": gnorm, "lr": lr, "overflow": overflow,
                       "loss_scale": state.scale_state.scale}
            return new_state, metrics

        offload_plan = getattr(self, "_offload_plan", None)

        def finalize_offload(state: TrainState):
            """Offload variant: device update for the kept subtree, grads of
            offloaded leaves returned for the host optimizer."""
            grads, gnorm, overflow = unscale_and_clip(state)
            lr = schedule(state.global_step)
            p_leaves = jax.tree_util.tree_flatten(state.params)[0]
            g_leaves = jax.tree_util.tree_flatten(grads)[0]
            kept = {str(i): p_leaves[i] for i in offload_plan.kept}
            kept_grads = {str(i): g_leaves[i] for i in offload_plan.kept}

            def do_step(_):
                return opt.step(kept, kept_grads, state.opt_state, lr)

            def skip(_):
                return kept, state.opt_state

            new_kept, new_opt = lax.cond(overflow, skip, do_step, None)
            new_leaves = list(p_leaves)
            for i in offload_plan.kept:
                new_leaves[i] = new_kept[str(i)]
            new_params = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(state.params), new_leaves)
            off_grads = {str(i): g_leaves[i] for i in offload_plan.offloaded}
            new_state = book_keeping(state, new_params, new_opt, overflow)
            metrics = {"grad_norm": gnorm, "lr": lr, "overflow": overflow,
                       "loss_scale": state.scale_state.scale}
            return new_state, off_grads, metrics

        if getattr(self, "_onebit", False):
            # 1-bit optimizer path: the whole micro/update runs inside
            # shard_map over the data axis so gradients stay per-worker
            # (unreduced) and the optimizer owns the compressed collective
            # (ops/onebit.py; reference onebit optimizers likewise take over
            # the engine's allreduce). Two compiled update programs — full-
            # precision warmup vs int8-compressed — dispatched host-side on
            # freeze_step, so no traced branch wraps the collectives.
            from ..compat import shard_map
            from jax.sharding import PartitionSpec as P

            mesh = self.mesh
            is_shard = lambda x: isinstance(x, jax.sharding.Sharding)  # noqa: E731
            state_specs = jax.tree.map(lambda s: s.spec, state_shardings,
                                       is_leaf=is_shard)

            def micro_onebit(state: TrainState, batch, rng):
                def shard_fn(state, batch, rng):
                    scale = state.scale_state.scale

                    def loss_fn(params):
                        if compression is not None:
                            params = compression(params, state.global_step)
                        loss = module.loss(params, batch, rng)
                        return (loss * scale).astype(jnp.float32), loss

                    grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
                    grad_acc = jax.tree.map(
                        lambda a, g: a + g[None].astype(a.dtype),
                        state.grad_acc, grads)
                    loss = lax.pmean(loss, "data")
                    return state._replace(grad_acc=grad_acc), loss

                return shard_map(
                    shard_fn, mesh=mesh,
                    in_specs=(state_specs, P("data"), P()),
                    out_specs=(state_specs, P()),
                    check_vma=False)(state, batch, rng)

            opt_dp = self.topology.get_data_parallel_world_size()

            def make_update_onebit(compressed: bool):
                step_fn = (opt.compressed_step_local if compressed
                           else opt.warmup_step_local)

                def update_onebit(state: TrainState):
                    def shard_fn(state):
                        scale = state.scale_state.scale
                        denom = scale * gas
                        local = jax.tree.map(lambda a: a[0] / denom,
                                             state.grad_acc)
                        # Root-mean of per-worker squared norms: an upper
                        # bound on the averaged-grad norm costing one scalar
                        # psum (the exact norm would need the full-precision
                        # gradient psum this path exists to avoid) — see
                        # ops/onebit.py "Documented divergences".
                        sumsq = sum(
                            jnp.sum(jnp.square(g.astype(jnp.float32)))
                            for g in jax.tree.leaves(local))
                        gnorm = jnp.sqrt(lax.psum(sumsq, "data") / opt_dp)
                        overflow = ~jnp.isfinite(gnorm)
                        if clip > 0:
                            coeff = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                            local = jax.tree.map(lambda g: g * coeff, local)
                        lr = schedule(state.global_step)
                        # No lax.cond around the optimizer here: its branches
                        # would trap collectives inside a conditional. Run
                        # the step unconditionally, select on overflow.
                        new_p, new_opt = step_fn(state.params, local,
                                                 state.opt_state, lr)
                        pick = lambda n, o: jnp.where(overflow, o, n)  # noqa: E731
                        new_p = jax.tree.map(pick, new_p, state.params)
                        new_opt = jax.tree.map(pick, new_opt, state.opt_state)
                        new_state = book_keeping(state, new_p,
                                                 new_opt, overflow)
                        metrics = {"grad_norm": gnorm, "lr": lr,
                                   "overflow": overflow,
                                   "loss_scale": state.scale_state.scale}
                        return new_state, metrics

                    return shard_map(
                        shard_fn, mesh=mesh,
                        in_specs=(state_specs,),
                        out_specs=(state_specs,
                                   {"grad_norm": P(), "lr": P(),
                                    "overflow": P(), "loss_scale": P()}),
                        check_vma=False)(state)

                return update_onebit

            micro = micro_onebit
            update = make_update_onebit(compressed=True)
            self._update_warm_raw = make_update_onebit(compressed=False)
            self._update_warm_fn = jax.jit(
                self._update_warm_raw,
                out_shardings=(state_shardings, None),
                donate_argnums=(0,))

        # NOTE: no in_shardings on any of these jits. The state/batch arrays
        # are committed with the plan's shardings already (init runs under
        # out_shardings; batches via device_put), so jit infers identical
        # input shardings from the arrays — and pinning in_shardings to
        # default layouts was measured to cost ~3x step time on TPU (it
        # defeats XLA's input-layout selection, forcing full-state relayouts
        # per call). The TPU path instead pins *XLA-preferred* layouts, found
        # by a one-time AUTO-format compile at the first forward
        # (_autotune_layouts below).
        self._micro_raw = micro
        self._update_raw = update
        self._finalize_raw = finalize_offload if offload_plan is not None else None
        self._layouts_tuned = False
        self._micro_fn = jax.jit(
            micro,
            out_shardings=(state_shardings, plan.replicated()),
            donate_argnums=(0,))
        if offload_plan is not None:
            self._update_fn = None
            self._finalize_fn = jax.jit(
                finalize_offload,
                out_shardings=(state_shardings, None, None),
                donate_argnums=(0,))
        else:
            self._finalize_fn = None
            self._update_fn = jax.jit(
                update,
                out_shardings=(state_shardings, None),
                donate_argnums=(0,))

        def eval_step(state: TrainState, batch, rng):
            params = state.params
            if compression is not None:
                params = compression(params, state.global_step)
            return module.loss(params, batch, None)

        self._eval_fn = jax.jit(eval_step)

    def _autotune_layouts(self, batch, rng):
        """One-time XLA layout autotuning for the hot step (TPU only).

        XLA picks faster-than-default in-memory layouts for the train state
        when allowed to (measured ~3x step time on a 536M LM on v5e when the
        state is pinned to default layouts). Compile the micro program once
        with AUTO input/output formats, read back the layouts XLA chose, move
        the live state into them, and rebuild the step jits pinned to those
        concrete formats so state cycles micro→update→micro with zero
        relayouts. Counterpart of the reference's kernel/layout autotuning
        role (it has no direct equivalent — CUDA torch controls layouts
        explicitly)."""
        self._layouts_tuned = True
        try:
            from jax.experimental.layout import Format, Layout
        except Exception:
            return
        if jax.devices()[0].platform != "tpu":
            return
        try:
            ss = self._state_shardings
            is_shard = lambda x: isinstance(x, jax.sharding.Sharding)
            auto_state = jax.tree.map(lambda s: Format(Layout.AUTO, s), ss,
                                      is_leaf=is_shard)
            rep = self.plan.replicated()
            micro_auto = jax.jit(
                self._micro_raw,
                in_shardings=(auto_state, None, None),
                out_shardings=(auto_state, rep),
                donate_argnums=(0,))
            # AUTO layouts require abstract (ShapeDtypeStruct) args to lower.
            avals = jax.eval_shape(lambda s, b, r: (s, b, r),
                                   self.state, batch, rng)
            compiled = micro_auto.lower(*avals).compile()
            out_state_fmt = compiled.output_formats[0]
            # Move the live state into the preferred layouts (one-time cost)
            # and pin every step program to them.
            self.state = jax.device_put(self.state, out_state_fmt)
            self._micro_fn = jax.jit(
                self._micro_raw,
                in_shardings=(out_state_fmt, None, None),
                out_shardings=(out_state_fmt, rep),
                donate_argnums=(0,))
            if self._finalize_raw is not None:
                self._finalize_fn = jax.jit(
                    self._finalize_raw,
                    in_shardings=(out_state_fmt,),
                    out_shardings=(out_state_fmt, None, None),
                    donate_argnums=(0,))
            else:
                self._update_fn = jax.jit(
                    self._update_raw,
                    in_shardings=(out_state_fmt,),
                    out_shardings=(out_state_fmt, None),
                    donate_argnums=(0,))
                if getattr(self, "_onebit", False):
                    self._update_warm_fn = jax.jit(
                        self._update_warm_raw,
                        in_shardings=(out_state_fmt,),
                        out_shardings=(out_state_fmt, None),
                        donate_argnums=(0,))
            log_dist("layout autotune: state pinned to XLA-preferred formats",
                     ranks=[0])
        except Exception as exc:  # pragma: no cover - depends on backend
            logger.warning(f"layout autotune skipped: {exc}")

    # ------------------------------------------------------------- data plumbing
    def deepspeed_io(self, dataset, batch_size=None, collate_fn=None,
                     num_local_io_workers=None, data_sampler=None,
                     route=None):
        """Reference engine.py:1665 ``deepspeed_io``. In the single-controller
        view one batch is the *global* micro batch (per-device micro ×
        DP world), sharded over the data axes at device_put."""
        global_micro = (self.train_micro_batch_size_per_gpu()
                        * self.topology.get_data_parallel_world_size())
        return DeepSpeedTpuDataLoader(
            dataset,
            batch_size=batch_size or global_micro,
            topology=self.topology,
            collate_fn=collate_fn,
            seed=self.config.seed,
            data_sampler=data_sampler)

    def _device_batch(self, batch):
        """Shard a host batch over the data axes."""
        sharding = self.plan.batch()

        def put(x):
            x = np.asarray(x)
            return jax.device_put(x, sharding)

        if isinstance(batch, dict):
            return {k: put(v) for k, v in batch.items()}
        if isinstance(batch, (tuple, list)):
            return {"input_ids": put(batch[0]), "labels": put(batch[1])} \
                if len(batch) == 2 else {"input_ids": put(batch[0])}
        return {"input_ids": put(batch)}

    # ----------------------------------------------------------------- API
    def __call__(self, batch, *args, **kwargs):
        return self.forward(batch, *args, **kwargs)

    def forward(self, batch, *args, **kwargs):
        """Run fwd+bwd+accumulate for one micro batch; returns the loss.

        Gradient work happens here (functional autograd); ``backward`` is
        the parity call that advances the micro counter.
        """
        self.tput_timer.start()
        batch = self._device_batch(batch) if not self._is_device_batch(batch) else batch
        if self.tput_timer.flops_per_sample is None:
            self._autofill_flops_per_sample(batch)
        step_rng = jax.random.fold_in(self._rng, self.micro_steps)
        if not self._layouts_tuned:
            self._autotune_layouts(batch, step_rng)
        if self._profile_steps:
            # synchronized bracket: start() waits out pending device work,
            # stop() blocks until this micro step's fwd+bwd really ran (the
            # two are one fused program — they cannot be timed separately
            # from the host; docs/OBSERVABILITY.md)
            fwd_timer = self.timers(FORWARD_MICRO_TIMER)
            fwd_timer.start()
            span = self.tracer.begin("fwd_bwd", trace_id="train",
                                     attrs={"micro_step": self.micro_steps})
            self.state, loss = self._micro_fn(self.state, batch, step_rng)
            fwd_timer.stop(record=True)
            span.end()
        else:
            self.state, loss = self._micro_fn(self.state, batch, step_rng)
        self._pending_loss = loss
        if self.config.check_numerics and not self.fp16_enabled \
                and not np.isfinite(float(loss)):
            # numeric sanitizer (reference runtime/utils.py CheckOverflow /
            # loss_scaler._has_inf_or_nan): name the poisoned leaves rather
            # than letting NaNs propagate silently. Debug mode — the float()
            # forces a device sync per micro step.
            raise FloatingPointError(
                f"check_numerics: non-finite loss {float(loss)} at micro "
                f"step {self.micro_steps}; offending state leaves: "
                f"{self._numerics_scan()}")
        return loss

    def _autofill_flops_per_sample(self, batch):
        """Feed :class:`ThroughputTimer` its per-sample FLOPs from the
        flops profiler's analytic counting (profiling/flops_profiler.py)
        so samples/sec reports come with a TFLOPS estimate without the
        user wiring anything. Non-CausalLM modules (no analytic model)
        set 0.0 — tflops() then stays silent — and never retry."""
        if not isinstance(self.module, CausalLM) \
                or not isinstance(batch, dict) or "input_ids" not in batch:
            self.tput_timer.flops_per_sample = 0.0
            return
        from ..profiling.flops_profiler import train_step_flops

        seq = max(1, int(batch["input_ids"].shape[-1]) - 1)
        self.tput_timer.flops_per_sample = float(
            train_step_flops(self.module.cfg, 1, seq))

    def _numerics_scan(self):
        """Per-leaf finiteness scan of params + accumulated grads; returns
        the pytree paths of non-finite leaves (reference fp16
        loss_scaler.py _has_inf_or_nan per-tensor scan, as one jitted
        tree-map instead of a host loop). The jitted scanner is cached —
        a fresh jit per call would retrace every step."""
        if not hasattr(self, "_numerics_scan_fn"):
            self._numerics_scan_fn = jax.jit(lambda t: jax.tree.map(
                lambda x: jnp.all(jnp.isfinite(x.astype(jnp.float32))), t))
        tree = {"params": self.state.params, "grad_acc": self.state.grad_acc}
        flags = jax.device_get(self._numerics_scan_fn(tree))
        return sorted(
            jax.tree_util.keystr(kp)
            for kp, ok in jax.tree_util.tree_flatten_with_path(flags)[0]
            if not bool(ok))

    @staticmethod
    def _is_device_batch(batch):
        return isinstance(batch, dict) and all(
            isinstance(v, jax.Array) for v in batch.values())

    def backward(self, loss=None, retain_graph=False):
        """API-parity (reference engine.py:1898): gradients were produced in
        ``forward``; this advances the micro-step counter."""
        self.micro_steps += 1
        return loss

    def is_gradient_accumulation_boundary(self) -> bool:
        return self.micro_steps % self.gradient_accumulation_steps() == 0

    def step(self):
        """Reference engine.py:2096: optimizer step at accumulation boundary."""
        if not self.is_gradient_accumulation_boundary():
            return
        # sanitizer scan must run BEFORE the update: the jitted update
        # zeroes grad_acc and overflow-gates the param write, so post-hoc
        # state would name nothing
        pre_scan = (self._numerics_scan()
                    if self.config.check_numerics and not self.fp16_enabled
                    else None)
        if self._profile_steps:
            step_timer = self.timers(STEP_GLOBAL_TIMER)
            step_timer.start()
            opt_span = self.tracer.begin(
                "optimizer_step", trace_id="train",
                attrs={"global_step": self.global_steps})
        if self._offload_plan is not None:
            metrics = self._offload_step()
        elif self._onebit and self.global_steps < self.opt.freeze_step:
            # Warmup phase: full-precision momentum/variance build-up
            # (host-dispatched — see _build_step_fns onebit path).
            self.state, metrics = self._update_warm_fn(self.state)
        else:
            self.state, metrics = self._update_fn(self.state)
        if self._profile_steps:
            step_timer.stop(record=True)   # synced: real update duration
            opt_span.set("skipped",
                         bool(np.asarray(metrics.get("overflow", False)))) \
                    .end()
        if pre_scan is not None \
                and not np.isfinite(float(metrics.get("grad_norm", 0.0))):
            # under fp16 the dynamic-loss-scale automaton owns overflow
            # (skip + rescale); everywhere else a non-finite grad norm is a
            # real numeric fault — fail loudly with the leaf names
            raise FloatingPointError(
                f"check_numerics: non-finite grad norm at step "
                f"{self.global_steps}; offending state leaves: {pre_scan}")
        self.global_steps += 1
        self.lr_scheduler.step()
        self._last_metrics = metrics
        self.tput_timer.stop(report_speed=(
            self.global_steps % self.config.steps_per_print == 0))
        if self.global_steps % self.config.steps_per_print == 0:
            m = {k: float(v) for k, v in metrics.items()}
            log_dist(
                f"step={self.global_steps} loss={float(self._pending_loss):.4f} "
                f"lr={m['lr']:.3e} grad_norm={m['grad_norm']:.3f} "
                f"loss_scale={m['loss_scale']:.0f}", ranks=[0])
            events = [
                ("Train/loss", float(self._pending_loss), self.global_steps),
                ("Train/lr", m["lr"], self.global_steps)]
            if self._profile_steps:
                # per-global-step wall-clock breakdown over the window
                # since the last report (fwd_microstep accumulates gas
                # micro steps per global step): the "what fraction of a
                # step is fwd+bwd vs optimizer" numbers, through the same
                # monitor fan-out as the loss curves
                names = [n for n in (FORWARD_MICRO_TIMER, STEP_GLOBAL_TIMER)
                         if self.timers.has(n)]
                means = self.timers.log(
                    names, normalizer=self.config.steps_per_print)
                events += [(f"Train/timer/{k}_ms", v, self.global_steps)
                           for k, v in means.items()]
            events.append(("Train/samples_per_sec",
                           self.tput_timer.avg_samples_per_sec(),
                           self.global_steps))
            if self.tput_timer.flops_per_sample:
                events.append(("Train/tflops", self.tput_timer.tflops(),
                               self.global_steps))
            if self.tput_timer.memory_bytes is not None:
                events.append(("Train/device_mem_gib",
                               self.tput_timer.memory_bytes / 2**30,
                               self.global_steps))
            if self.monitor is not None:
                self.monitor.write_events(events)
        return metrics

    def _offload_step(self):
        """Host-side optimizer step for offloaded leaves (ZeRO-Offload):
        device finalize → grads to host → C++ SIMD update of fp32 masters →
        masters stream back into the sharded device params."""
        # Drive the host LR from the authoritative device counter: the jitted
        # path uses state.global_step, which does NOT advance on fp16-overflow
        # skipped steps, while self.global_steps advances on every boundary.
        # Using the host mirror would permanently desync offloaded-leaf LR
        # from device-resident leaves after any overflow (ADVICE r1).
        lr_host = float(self.schedule(int(self.state.global_step)))
        self.state, off_grads, metrics = self._finalize_fn(self.state)
        if not bool(metrics["overflow"]):
            plan = self._offload_plan
            # Pipelined host step (round-2 weak #4): leaf i's C++ optimizer
            # update runs on a worker thread while leaf i+1's gradient is
            # still transferring device→host — the reference's stream
            # overlap (stage_1_and_2.py:1096) as a transfer/compute
            # pipeline. One worker keeps leaf updates ordered; the C++ op
            # is OpenMP-parallel internally.
            if not hasattr(self, "_offload_pool"):
                from concurrent.futures import ThreadPoolExecutor

                self._offload_pool = ThreadPoolExecutor(max_workers=1)
            futures = []
            for i in plan.offloaded:
                g = np.asarray(jax.device_get(off_grads[str(i)]))
                futures.append(self._offload_pool.submit(
                    plan.host_update_leaf, i, g, lr_host))
            for f in futures:
                f.result()
            p_leaves = jax.tree_util.tree_flatten(self.state.params)[0]
            kept = {str(i): p_leaves[i] for i in plan.kept}
            new_params = plan.merge(kept, plan.masters, self._param_shardings)
            self.state = self.state._replace(params=new_params)
        return metrics

    def train_batch(self, data_iter=None):
        """Full effective batch: GAS micro steps + update (pipeline-engine
        parity, reference pipe/engine.py:312).

        The no-arg form keeps ONE persistent iterator across calls (reference
        PipelineEngine keeps self.data_iterator, pipe/engine.py:114) so that
        successive train_batch() calls walk the dataset instead of restarting
        it; the loader repeats across epochs via RepeatingLoader.
        """
        if data_iter is not None:
            it = data_iter
        else:
            if self._data_iter is None:
                from .dataloader import RepeatingLoader
                loader = self.training_dataloader
                if not isinstance(loader, RepeatingLoader):
                    loader = RepeatingLoader(loader)
                self._data_iter = iter(loader)
            it = self._data_iter
        fp = self.config.flops_profiler
        profiling = (fp.enabled and isinstance(self.module, CausalLM)
                     and self.global_steps + 1 == fp.profile_step)
        if profiling:
            if self.global_steps == 0:
                logger.warning("flops_profiler.profile_step=1 times the "
                               "first step, which includes XLA compilation")
            self._sync()
            t0 = time.perf_counter()
        losses = []
        seq_len = None
        for _ in range(self.gradient_accumulation_steps()):
            batch = next(it)
            if self.curriculum_scheduler is not None:
                batch = self._apply_curriculum(batch)
            if profiling and seq_len is None and isinstance(batch, dict):
                seq_len = int(np.asarray(batch["input_ids"]).shape[-1]) - 1
            losses.append(self.forward(batch))
            self.backward()
        self.step()
        if profiling:
            self._sync()
            dt = time.perf_counter() - t0
            from ..profiling import FlopsProfiler

            prof = FlopsProfiler(engine=self)
            report = prof.profile_report(
                batch_size=self.train_batch_size(),
                seq_len=seq_len or self.module.cfg.max_seq_len,
                step_time=dt)
            if fp.output_file:
                with open(fp.output_file, "w") as fh:
                    fh.write(report)
            else:
                print(report)
        return jnp.mean(jnp.stack(losses))

    def reset_data_iterator(self):
        """Drop the persistent no-arg ``train_batch`` iterator so the next
        call rebuilds it from ``training_dataloader``'s current position —
        the hook the resilience supervisor uses after restoring dataloader
        state from a checkpoint (runtime/resilience.py)."""
        self._data_iter = None

    def _apply_curriculum(self, batch):
        """Seqlen curriculum: truncate the token batch to the scheduled
        difficulty (reference engine curriculum path; difficulty_step
        quantization bounds the number of distinct compiled shapes)."""
        difficulty = self.curriculum_scheduler.update_difficulty(
            self.global_steps + 1)
        if not isinstance(batch, dict) or "input_ids" not in batch:
            return batch
        ids = batch["input_ids"]
        seq = int(np.asarray(ids).shape[-1]) - 1
        if difficulty >= seq:
            return batch
        out = dict(batch)
        for key in ("input_ids", "labels", "attention_mask"):
            if key in out:
                out[key] = np.asarray(out[key])[..., :difficulty + 1]
        return out

    def comms_report(self, batch=None, print_log: bool = True):
        """Static collective analysis of the compiled step programs
        (utils/comms_logging.analyze_compiled): per-op counts + per-shard
        bytes on the wire each step. Covers what the eager comms logger
        cannot see — collectives fused inside jit (ZeRO gathers, qwZ/qgZ
        quantized collectives, 1-bit int8 allreduce, TP/EP/SP traffic)."""
        from ..utils.comms_logging import (analyze_compiled,
                                           format_compiled_comms)

        if batch is None:
            micro = self.train_micro_batch_size_per_gpu()
            dp = self.topology.get_data_parallel_world_size()
            seq = getattr(getattr(self.module, "cfg", None), "max_seq_len",
                          128)
            batch = {"input_ids": np.zeros((micro * dp, min(seq, 128) + 1),
                                           np.int64)}
        batch = self._device_batch(batch)
        rng = jax.random.fold_in(self._rng, 0)

        rep = self.plan.replicated()

        def aval(x):
            # eval_shape drops shardings; keep them or GSPMD partitioning
            # (and thus every collective) vanishes from the lowered
            # program. Eagerly-created scalars carry SingleDeviceSharding —
            # normalize those to mesh-replicated so all args share devices.
            if isinstance(x, jax.Array):
                sh = x.sharding
                if isinstance(sh, jax.sharding.SingleDeviceSharding):
                    sh = rep
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
            return x

        avals = jax.tree.map(aval, (self.state, batch, rng))
        report = analyze_compiled(
            self._micro_fn.lower(*avals).compile())
        # the micro program runs gas times per optimizer step
        gas = self.gradient_accumulation_steps()
        for rec in report.values():
            rec["count"] *= gas
            rec["bytes"] *= gas
        update_fn = self._finalize_fn if self._finalize_fn is not None \
            else self._update_fn
        upd = analyze_compiled(update_fn.lower(avals[0]).compile())
        for op, rec in upd.items():
            dst = report.setdefault(op, {"count": 0, "bytes": 0,
                                         "group_sizes": set(),
                                         "dtypes": set()})
            dst["count"] += rec["count"]
            dst["bytes"] += rec["bytes"]
            dst["group_sizes"] |= rec["group_sizes"]
            dst["dtypes"] |= rec["dtypes"]
        if print_log:
            log_dist(format_compiled_comms(report), ranks=[0])
        return report

    def set_compression(self, transform):
        """Attach a CompressionTransform after construction (the
        ``init_compression(engine, config)`` path — reference
        compression/compress.py:100) and rebuild the step programs."""
        self._compression = transform if transform else None
        self._build_step_fns()
        self._layouts_tuned = False

    def set_custom_curriculum_learning_schedule(self, schedule_fn):
        """Reference engine.py set_custom_curriculum_learning_schedule."""
        if self.curriculum_scheduler is None:
            raise RuntimeError("curriculum_learning is not enabled")
        self.curriculum_scheduler.set_custom_get_difficulty(schedule_fn)

    def eval_batch(self, batch):
        batch = self._device_batch(batch) if not self._is_device_batch(batch) else batch
        return self._eval_fn(self.state, batch, None)

    # ------------------------------------------------------------- accessors
    def train_batch_size(self) -> int:
        return self.config.train_batch_size

    def train_micro_batch_size_per_gpu(self) -> int:
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self) -> int:
        return self.config.gradient_accumulation_steps

    @property
    def optimizer(self):
        return self.opt

    def get_lr(self):
        # state.global_step is authoritative (does not count overflow-skipped
        # steps); the host mirror would report a drifted LR after overflows.
        return [float(self.schedule(int(self.state.global_step)))]

    def get_global_grad_norm(self) -> Optional[float]:
        m = getattr(self, "_last_metrics", None)
        return float(m["grad_norm"]) if m else None

    @property
    def loss_scale(self) -> float:
        return float(self.state.scale_state.scale)

    @property
    def skipped_steps(self) -> int:
        """Overflow-skipped steps; reads the authoritative device counter
        lazily (no per-step host sync)."""
        return int(self.state.skipped_steps)

    def zero_optimization(self) -> bool:
        return self.zero_stage > 0

    def zero_optimization_stage(self) -> int:
        return self.zero_stage

    # ---------------------------------------------------------- checkpointing
    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True, exclude_frozen_parameters=False,
                        async_save=False, urgent=False):
        from .checkpointing import save_checkpoint as _save

        return _save(self, save_dir, tag=tag, client_state=client_state or {},
                     save_latest=save_latest, async_save=async_save,
                     urgent=urgent)

    def wait_pending_checkpoint(self):
        """Join an async_save's background writes (+ cross-host barrier)."""
        from .checkpointing import wait_pending_save

        wait_pending_save(self)

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True, load_lr_scheduler_states=True,
                        load_module_only=False):
        from .checkpointing import load_checkpoint as _load

        return _load(self, load_dir, tag=tag,
                     load_optimizer_states=load_optimizer_states,
                     load_module_only=load_module_only)

    def save_16bit_model(self, save_dir, save_filename="pytorch_model.bin",
                         exclude_frozen_parameters=False):
        """Reference engine.py:3488: export params in compute dtype,
        consolidated (fully replicated)."""
        from .checkpointing import save_16bit_model as _save16

        return _save16(self, save_dir, save_filename)
