"""LR schedules: WarmupLR / WarmupDecayLR / WarmupCosineLR / OneCycle /
LRRangeTest.

Counterpart of reference ``runtime/lr_schedules.py`` (:267 LRRangeTest,
:370 OneCycle, :634 WarmupLR, WarmupDecayLR, WarmupCosineLR). The reference's
schedulers mutate optimizer param groups per step from Python; here each
schedule is a pure function ``step -> lr`` built from jnp ops so it traces
into the jitted train step (no host round-trip per step). ``get_lr()`` /
``step()`` host-side API is provided by the engine wrapper for parity.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def warmup_lr(warmup_min_lr=0.0, warmup_max_lr=0.001, warmup_num_steps=1000,
              warmup_type="log", **_) -> Schedule:
    """Reference WarmupLR (lr_schedules.py:634): warm up then hold."""
    warmup_num_steps = max(2, warmup_num_steps)

    def sched(step):
        s = jnp.asarray(step, jnp.float32)
        frac = jnp.clip(s / warmup_num_steps, 0.0, 1.0)
        if warmup_type == "log":
            # log(1+t)/log(1+T) ramp, matching the reference's log warmup
            gamma = jnp.log1p(s) / math.log(1 + warmup_num_steps)
            gamma = jnp.clip(gamma, 0.0, 1.0)
        else:
            gamma = frac
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma

    return sched


def warmup_decay_lr(total_num_steps, warmup_min_lr=0.0, warmup_max_lr=0.001,
                    warmup_num_steps=1000, warmup_type="log", **_) -> Schedule:
    """WarmupLR then linear decay to 0 over total_num_steps."""
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def sched(step):
        s = jnp.asarray(step, jnp.float32)
        decay = jnp.clip((total_num_steps - s) / max(1.0, total_num_steps - warmup_num_steps),
                         0.0, 1.0)
        # Decay to warmup_min_lr, not zero (reference WarmupDecayLR,
        # lr_schedules.py:684: min_lr + (max_lr - min_lr) * gamma).
        decayed = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * decay
        return jnp.where(s < warmup_num_steps, base(step), decayed)

    return sched


def warmup_cosine_lr(total_num_steps, warmup_min_ratio=0.0, warmup_num_steps=1000,
                     cos_min_ratio=0.0001, lr=0.001, **_) -> Schedule:
    """Reference WarmupCosineLR: linear warmup from min_ratio*lr, cosine to
    cos_min_ratio*lr."""

    def sched(step):
        s = jnp.asarray(step, jnp.float32)
        warm = warmup_min_ratio + (1 - warmup_min_ratio) * jnp.clip(
            s / max(1, warmup_num_steps), 0.0, 1.0)
        progress = jnp.clip((s - warmup_num_steps) /
                            max(1.0, total_num_steps - warmup_num_steps), 0.0, 1.0)
        cos = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (1 + jnp.cos(jnp.pi * progress))
        ratio = jnp.where(s < warmup_num_steps, warm, cos)
        return lr * ratio

    return sched


def one_cycle(cycle_min_lr, cycle_max_lr, cycle_first_step_size=2000,
              cycle_second_step_size=None, decay_step_size=0,
              decay_lr_rate=0.0, **_) -> Schedule:
    """Reference OneCycle (lr_schedules.py:370), LR part: ramp min→max over
    first phase, max→min over second, then decay."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total = cycle_first_step_size + second

    def sched(step):
        s = jnp.asarray(step, jnp.float32)
        up = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * jnp.clip(
            s / cycle_first_step_size, 0.0, 1.0)
        down = cycle_max_lr - (cycle_max_lr - cycle_min_lr) * jnp.clip(
            (s - cycle_first_step_size) / max(1, second), 0.0, 1.0)
        in_cycle = jnp.where(s < cycle_first_step_size, up, down)
        if decay_step_size > 0:
            decay_steps = jnp.maximum(s - total, 0.0) / decay_step_size
            post = cycle_min_lr / (1.0 + decay_lr_rate * decay_steps)
        else:
            post = jnp.asarray(cycle_min_lr, jnp.float32)
        return jnp.where(s <= total, in_cycle, post)

    return sched


def lr_range_test(lr_range_test_min_lr=1e-3, lr_range_test_step_size=2000,
                  lr_range_test_step_rate=1.0, lr_range_test_staircase=False,
                  **_) -> Schedule:
    """Reference LRRangeTest (lr_schedules.py:267): LR sweep for tuning."""

    def sched(step):
        s = jnp.asarray(step, jnp.float32)
        interval = jnp.floor(s / lr_range_test_step_size) if lr_range_test_staircase \
            else s / lr_range_test_step_size
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return sched


def constant_lr(lr=0.001, **_) -> Schedule:
    def sched(step):
        return jnp.full((), lr, jnp.float32)

    return sched


SCHEDULES = {
    "warmuplr": warmup_lr,
    "warmupdecaylr": warmup_decay_lr,
    "warmupcosinelr": warmup_cosine_lr,
    "onecycle": one_cycle,
    "lrrangetest": lr_range_test,
    "constant": constant_lr,
}


def build_schedule(type_name: Optional[str], params: Optional[dict] = None,
                   fallback_lr: float = 1e-3) -> Schedule:
    if type_name is None:
        return constant_lr(lr=fallback_lr)
    key = type_name.lower().replace("_", "")
    if key not in SCHEDULES:
        raise ValueError(f"Unknown scheduler {type_name!r}; known: {sorted(SCHEDULES)}")
    return SCHEDULES[key](**(params or {}))


class LRSchedulerShim:
    """Host-side wrapper giving the reference's scheduler API
    (``get_lr``/``get_last_lr``/``step``/``state_dict``) over a pure schedule.

    When given a ``step_source`` callable (the engine wires
    ``lambda: int(state.global_step)``), the authoritative step count comes
    from the device train state — which does NOT advance on overflow-skipped
    steps — so ``get_lr``/``state_dict`` can never drift from the LR the
    jitted update actually applied. The host ``last_step`` mirror is kept
    only as a fallback for standalone use."""

    def __init__(self, schedule: Schedule, start_step: int = 0,
                 step_source=None):
        self.schedule = schedule
        self.last_step = start_step
        self.step_source = step_source

    def _current_step(self) -> int:
        if self.step_source is not None:
            return int(self.step_source())
        return self.last_step

    def step(self, increment: int = 1):
        self.last_step += increment

    def get_lr(self):
        return [float(self.schedule(self._current_step()))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_step": self._current_step()}

    def load_state_dict(self, sd):
        self.last_step = sd["last_step"]
