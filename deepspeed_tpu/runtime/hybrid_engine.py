"""Hybrid (RLHF) engine: one engine that trains AND generates.

Counterpart of reference ``runtime/hybrid_engine.py``
(``DeepSpeedHybridEngine`` :31): RLHF alternates generate-heavy rollout
phases with ZeRO-3 training steps on the same weights. The torch version
maintains a second set of injected inference modules, manually gathers
ZeRO-3 partitions around ``generate`` (``GatheredParameters``), fuses/
unfuses LoRA, and swaps module forwards in and out.

TPU-native design: in a functional runtime the flip is a *sharding*
operation, not a module surgery. Training owns fp32 masters sharded by the
ZeRO plan; ``generate()`` feeds a bf16 view of those same masters to the
compiled inference program whose in_shardings are the serving layout
(TP-sharded / replicated) — XLA inserts exactly the all-gather the
reference performs manually, and "releasing" the inference copy is
dropping a reference (``release_inference_cache``). The serving view is
cached and invalidated per optimizer step, mirroring the reference's
``retake_inference_cache`` lifecycle. Latency accounting keeps the
reference's generate/train split (hybrid_engine.py ``generate`` :174 /
``step`` :430 stats).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist
from .engine import DeepSpeedTpuEngine


class DeepSpeedTpuHybridEngine(DeepSpeedTpuEngine):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        hc = self.config.hybrid_engine
        self._he_cfg = hc
        self._infer_engine = None
        self._infer_params_step = -1
        self._in_train_mode = True
        # reference perf stats (hybrid_engine.py:56)
        self._generate_latency = 0.0
        self._training_latency = 0.0
        self._iters = 0
        self._training_start_time = None
        log_dist(
            f"HybridEngine: max_out_tokens={hc.max_out_tokens} "
            f"inference_tp_size={hc.inference_tp_size} "
            f"release_inference_cache={hc.release_inference_cache}",
            ranks=[0])

    # ------------------------------------------------------------ inference
    def _serving_module(self):
        from ..models.transformer import CausalLM

        if not isinstance(self.module, CausalLM):
            raise ValueError("hybrid engine generate() needs a framework "
                             "CausalLM (reference requires an injectable "
                             "HF model the same way)")
        dtype = (self.compute_dtype if self.compute_dtype != jnp.float32
                 else jnp.bfloat16)
        cfg = dataclasses.replace(self.module.cfg, dtype=dtype, remat=False)
        return CausalLM(cfg), dtype

    def _inference_engine(self):
        if self._infer_engine is None:
            from ..inference.engine import InferenceEngine

            module, dtype = self._serving_module()
            self._infer_engine = InferenceEngine(
                model=module, params=self._cast_params(dtype),
                mesh=self.topology,
                config={"dtype": "bf16" if dtype == jnp.bfloat16
                        else str(self.precision.value)})
            self._infer_params_step = self.global_steps
        return self._infer_engine

    def _cast_params(self, dtype):
        return jax.tree.map(
            lambda p: p.astype(dtype) if jnp.issubdtype(p.dtype,
                                                        jnp.floating) else p,
            self.state.params)

    def _sync_inference_params(self):
        """Refresh the serving view iff a training step happened since the
        last generate (reference gathers partitions at each generate; here
        the gather is XLA resharding of the cast masters)."""
        eng = self._inference_engine()
        if self._infer_params_step != self.global_steps:
            _, dtype = self._serving_module()
            cast = self._cast_params(dtype)
            eng.params = jax.tree.map(jax.device_put, cast,
                                      eng.plan.params(cast))
            self._infer_params_step = self.global_steps
        return eng

    # ------------------------------------------------------------------ API
    def generate(self, input_ids, max_new_tokens: Optional[int] = None,
                 **kwargs) -> Any:
        """Rollout generate on the current training weights (reference
        hybrid_engine.py:174)."""
        t0 = time.perf_counter()
        eng = self._sync_inference_params()
        max_new = max_new_tokens or self._he_cfg.max_out_tokens
        out = eng.generate(input_ids, max_new_tokens=max_new, **kwargs)
        jax.block_until_ready(out)
        self._generate_latency += time.perf_counter() - t0
        self._iters += 1
        if self._he_cfg.release_inference_cache:
            self._infer_engine = None       # drop the serving copy + cache
            self._infer_params_step = -1
        return out

    def eval(self):
        """Flip to rollout mode (reference :382): start the generate phase
        clock; training latency accumulates between train() and eval()."""
        if self._in_train_mode and self._training_start_time is not None:
            self._training_latency += time.perf_counter() - self._training_start_time
            self._training_start_time = None
        self._in_train_mode = False
        return self

    def train(self, mode: bool = True):
        """Flip back to training (reference :418)."""
        self._in_train_mode = mode
        if mode and self._training_start_time is None:
            self._training_start_time = time.perf_counter()
        return self

    def step(self):
        metrics = super().step()
        # a new optimizer step invalidates the cached serving view lazily
        # (next generate re-syncs); nothing to un-fuse in a functional world
        return metrics

    def latency_stats(self):
        """Reference's per-phase wall-clock split."""
        return {"generate_latency_s": self._generate_latency,
                "training_latency_s": self._training_latency,
                "generate_iters": self._iters}
