"""Preemption-safe self-healing training: supervisor, watchdog, rollback.

PR 5 made the *serving* stack survive replica death; this module gives the
*training* loop the same property (docs/TRAINING.md "Fault tolerance").
Counterpart of the reference's elasticity/checkpoint-engine capabilities
(``deepspeed/elasticity/``, Nebula checkpoint engine) recast for preemptible
TPU fleets, reusing the supervisor/backoff/chaos idioms proven out in
``serving/supervisor.py`` and ``serving/faults.py``:

- :class:`TrainingSupervisor` wraps the train loop. SIGTERM (the cloud
  preemption notice) triggers an *urgent* bounded-time checkpoint save
  inside the grace window; a crash, a wedged step, or an anomaly storm
  triggers restart-from-``latest`` with exponential backoff + seeded
  jitter and a circuit breaker (mirroring the serving supervisor). Resume
  is *deterministic*: params/moments (exact fp32), LR schedule,
  :class:`~.engine.ScaleState`, the RNG stream (``micro_steps`` replays
  the ``fold_in`` fold points), and the data-iterator position
  (``DeepSpeedTpuDataLoader.state_dict``) are all restored, so an
  interrupted+resumed run reproduces the uninterrupted loss curve
  byte-for-byte (asserted in tests/test_train_resilience.py and the
  bench ``train_chaos`` phase).
- :class:`StepWatchdog`: a host-side thread with a rolling-median
  step-time baseline. A wedged step (stuck device call) is detected, the
  flight recorder is dumped, and the supervisor restarts from ``latest``
  on a fresh engine instead of hanging forever.
- Anomaly guards extend the engine's overflow/skip-step machinery (the
  jitted update already skips any non-finite-gradient step in *every*
  precision, not just fp16): the supervisor counts consecutive
  NaN/inf-gradient or loss-spike steps and, after K in a row, rolls back
  to the last good checkpoint instead of burning the run.
- :class:`TrainFaultInjector`: seeded, scripted training faults
  (``crash``/``sigterm``/``nan_grads``/``slow_step`` at exact step
  indices) in the style of ``serving/faults.py``, driving the chaos
  suite and bench phase. Disabled = zero hooks anywhere.

Everything defaults off: with no ``resilience:`` block (and no supervisor
constructed) training behavior is byte-for-byte historical.
"""

from __future__ import annotations

import dataclasses
import math
import os
import random
import signal
import statistics
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from pydantic import Field

from ..utils.locks import RankedLock
from ..utils.logging import logger
from ..utils.restart import RestartPolicy
from .config_utils import DSConfigModel

# --------------------------------------------------------------------- config

TRAIN_FAULT_KINDS = ("crash", "sigterm", "nan_grads", "slow_step")


class TrainFaultsConfig(DSConfigModel):
    """``resilience.faults: {...}`` TEST-ONLY deterministic training fault
    injection (docs/CONFIG.md): a seeded schedule of crashes, preemption
    signals, NaN gradient storms, and wedged-step latency, driving the
    chaos suite (tests/test_train_resilience.py) and ``bench.py``'s
    ``train_chaos`` phase. Disabled = no hooks — byte-for-byte the
    uninstrumented training loop."""

    enabled: bool = False
    seed: int = 0
    # entries: {"kind": "crash"|"sigterm"|"nan_grads"|"slow_step",
    #           "at_step": k | "at_step_range": [lo, hi] (seeded draw),
    #           "duration_s": t (slow_step wedge length),
    #           "count": c (firings allowed; 0 = every time)}
    schedule: List[Dict[str, Any]] = Field(default_factory=list)

    def build_injector(self) -> Optional["TrainFaultInjector"]:
        if not self.enabled:
            return None
        return TrainFaultInjector(self.schedule, seed=self.seed)


class ResilienceConfig(DSConfigModel):
    """``resilience: {...}`` block on ``DeepSpeedTpuConfig``
    (docs/CONFIG.md, docs/TRAINING.md "Fault tolerance"). Consumed by
    :class:`TrainingSupervisor`; the block existing changes nothing by
    itself — constructing the supervisor is the opt-in, and with
    ``enabled: false`` the supervisor refuses to run."""

    enabled: bool = False
    # checkpoint root; 'latest' inside it is the auto-resume anchor
    save_dir: Optional[str] = None
    # periodic checkpoint cadence in optimizer steps (0 = only urgent /
    # caller-driven saves); saves are skipped while an anomaly streak is
    # open so 'latest' always names a last-GOOD state
    save_interval_steps: int = 0
    # preemption: install a SIGTERM handler (main thread only) and
    # complete an urgent synchronous save within this grace window
    handle_sigterm: bool = True
    preempt_grace_s: float = 30.0
    # restart backoff + circuit breaker (serving supervisor idiom):
    # base * 2^(failures_in_window - 1), capped, with seeded jitter;
    # max_restarts_in_window failures inside restart_window_s parks the
    # run (status "parked") instead of looping forever
    restart_backoff_s: float = 0.5
    restart_backoff_max_s: float = 30.0
    restart_backoff_jitter: float = 0.2
    seed: int = 0
    max_restarts_in_window: int = 3
    restart_window_s: float = 3600.0
    # step watchdog: a step outrunning max(step_timeout_s,
    # watchdog_factor x rolling-median) is declared wedged. With
    # step_timeout_s == 0 the auto baseline arms only after
    # watchdog_min_steps completed steps (XLA compiles make the first
    # steps wild). Wedge recovery needs an engine_factory — the stuck
    # thread owns the old engine.
    watchdog_enabled: bool = True
    step_timeout_s: float = 0.0
    watchdog_factor: float = 10.0
    watchdog_min_steps: int = 5
    watchdog_poll_s: float = 0.5
    # anomaly guards: a step is anomalous when the update skipped on a
    # non-finite gradient norm (the engine's overflow gate — all
    # precisions), the loss is non-finite, or the loss exceeds
    # loss_spike_factor x the rolling median of the last loss_window
    # good losses (0 disables the spike check). K consecutive anomalies
    # roll the run back to the last good checkpoint.
    anomaly_detection: bool = True
    loss_spike_factor: float = 10.0
    loss_window: int = 20
    max_consecutive_anomalies: int = 3
    # test-only deterministic fault injection
    faults: TrainFaultsConfig = Field(default_factory=TrainFaultsConfig)


# ------------------------------------------------------------ fault injection


class InjectedTrainFault(RuntimeError):
    """The scripted training failure. A plain RuntimeError subclass on
    purpose: the supervisor must treat it exactly like a real crash."""


@dataclasses.dataclass
class TrainFaultEvent:
    kind: str                       # one of TRAIN_FAULT_KINDS
    at_step: Optional[int] = None   # optimizer-step index
    duration_s: float = 0.0         # slow_step wedge length
    count: int = 1                  # firings allowed; 0 = every time
    error: str = "injected train fault"
    fired: int = 0

    def _matches(self, step: int) -> bool:
        if self.at_step is None:
            return False
        if self.count != 0 and self.fired >= self.count:
            return False
        return step >= self.at_step


class TrainFaultInjector:
    """Seeded, scripted schedule of :class:`TrainFaultEvent`.

    ``on_step(step)`` is consulted once per optimizer step *before* the
    step runs: ``crash`` raises :class:`InjectedTrainFault` into the
    loop's normal crash path, ``slow_step`` sleeps (the stuck-device-call
    shape the watchdog detects), and ``sigterm``/``nan_grads`` events are
    returned to the caller (the supervisor delivers the signal / poisons
    the gradient accumulator). ``at_step_range: [lo, hi]`` draws the step
    from the seeded RNG at construction — same seed, same failure story."""

    # ``events`` is immutable after construction; the firing ledger is
    # multi-writer (docs/CONCURRENCY.md)
    _GUARDED_BY = {"fired_log": "_lock"}

    def __init__(self, schedule: List[Dict[str, Any]], seed: int = 0):
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.events: List[TrainFaultEvent] = []
        for raw in schedule:
            e = dict(raw)
            rng_range = e.pop("at_step_range", None)
            ev = TrainFaultEvent(**e)
            if rng_range is not None:
                ev.at_step = self.rng.randint(int(rng_range[0]),
                                              int(rng_range[1]))
            if ev.kind not in TRAIN_FAULT_KINDS:
                raise ValueError(f"unknown train fault kind {ev.kind!r} "
                                 f"(expected one of {TRAIN_FAULT_KINDS})")
            if ev.at_step is None:
                raise ValueError(f"{ev.kind} fault needs at_step "
                                 "(or at_step_range)")
            self.events.append(ev)
        self._lock = RankedLock("train.faults")
        self.fired_log: List[tuple] = []   # (kind, step, monotonic t)

    def _take(self, step: int) -> List[TrainFaultEvent]:
        with self._lock:
            hits = [ev for ev in self.events if ev._matches(step)]
            for ev in hits:
                ev.fired += 1
                self.fired_log.append((ev.kind, step, time.monotonic()))
        return hits

    def fired_events(self) -> List[tuple]:
        with self._lock:
            return list(self.fired_log)

    def on_step(self, step: int,
                handler: Optional[Callable[[TrainFaultEvent], None]] = None
                ) -> List[TrainFaultEvent]:
        """Pre-step hook. Sleeps wedges itself; ``sigterm``/``nan_grads``
        events go through ``handler`` (or the return list when none is
        given); a ``crash`` raises LAST, after every co-scheduled event
        was delivered — all taken events count as fired, so none may be
        silently swallowed by the raise."""
        out = []
        crash: Optional[TrainFaultEvent] = None
        for ev in self._take(step):
            if ev.kind == "slow_step":
                time.sleep(ev.duration_s)
            elif ev.kind == "crash":
                crash = ev
            elif handler is not None:
                handler(ev)
            else:
                out.append(ev)
        if crash is not None:
            raise InjectedTrainFault(
                f"{crash.error} (crash at step {step})")
        return out


# ----------------------------------------------------------------- watchdog


class StepWatchdog:
    """Host-side wedged-step detector.

    The stepping thread brackets each optimizer step with
    :meth:`step_begin`/:meth:`step_end`; this thread polls and declares a
    wedge when the in-flight step outruns ``max(step_timeout_s, factor x
    rolling-median step time)``. With ``step_timeout_s == 0`` the
    auto-baseline arms only after ``min_samples`` completed steps — the
    first steps include XLA compiles and would poison the median. The
    watchdog only *detects* (sets :attr:`wedged`, fires ``on_wedge``
    once); recovery is the supervisor's job — the wedged thread is stuck
    inside a device call nobody can interrupt."""

    # the duration ring is the only cross-thread structure; the step
    # bracket (``_step_started``) is a single-writer watermark
    _GUARDED_BY = {"_durations": "_dur_lock"}

    def __init__(self, poll_s: float = 0.5, step_timeout_s: float = 0.0,
                 factor: float = 10.0, min_samples: int = 5,
                 on_wedge: Optional[Callable[[float], None]] = None,
                 history: int = 64):
        self.poll_s = float(poll_s)
        self.step_timeout_s = float(step_timeout_s)
        self.factor = float(factor)
        self.min_samples = int(min_samples)
        self.on_wedge = on_wedge
        self._durations: "deque[float]" = deque(maxlen=history)
        # guards _durations: the stepping thread appends while this
        # thread medians — an unguarded sort over a mutating deque
        # raises and would silently kill the watchdog (the one thread
        # that must not die quietly)
        self._dur_lock = RankedLock("train.watchdog.durations")
        self._step_started: Optional[float] = None
        self.wedged = threading.Event()
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._loop, daemon=True,
                                       name="train-step-watchdog")

    # hooks for the stepping thread ------------------------------------
    def step_begin(self) -> None:
        self._step_started = time.monotonic()

    def step_end(self, duration_s: float) -> None:
        self._step_started = None
        with self._dur_lock:
            self._durations.append(float(duration_s))

    def step_abort(self) -> None:
        """Close the bracket without recording (a step cut short by a
        preemption notice is not a latency sample)."""
        self._step_started = None

    # ------------------------------------------------------------------
    def timeout_s(self) -> Optional[float]:
        """Current wedge threshold: ``max(step_timeout_s, factor x
        rolling median)`` — the documented contract. The fixed floor
        alone applies before the median arms (so a configured timeout
        starts protecting from step one); with no floor the watchdog is
        unarmed (None) until ``min_samples`` steps completed."""
        with self._dur_lock:
            samples = list(self._durations)
        auto = (self.factor * statistics.median(samples)
                if len(samples) >= max(1, self.min_samples) else None)
        if self.step_timeout_s > 0:
            return self.step_timeout_s if auto is None \
                else max(self.step_timeout_s, auto)
        return auto

    def start(self) -> None:
        self.thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self.thread.is_alive():
            self.thread.join(timeout)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                started = self._step_started
                limit = self.timeout_s()
            except Exception:  # pragma: no cover — the watchdog must
                self._stop.wait(self.poll_s)  # never die of its own bug
                continue
            if started is not None and limit is not None:
                stuck_for = time.monotonic() - started
                if stuck_for > limit:
                    self.wedged.set()
                    logger.error(
                        f"train watchdog: step wedged for "
                        f"{stuck_for:.2f}s (limit {limit:.2f}s)")
                    if self.on_wedge is not None:
                        try:
                            self.on_wedge(stuck_for)
                        except Exception:  # pragma: no cover - defensive
                            pass
                    return          # one detection per watchdog instance
            self._stop.wait(self.poll_s)


# --------------------------------------------------------------- supervisor


class TrainingSupervisor:
    """Self-healing wrapper around ``engine.train_batch()``.

    ``run(num_steps)`` drives the engine to ``num_steps`` optimizer
    steps, auto-resuming from the ``latest`` checkpoint in ``save_dir``
    first (so calling ``run`` again after a preemption or in a restarted
    process IS the resume path). The step loop runs on a worker thread so
    the supervisor can abandon a wedged step; crashes, wedges, and
    anomaly storms restart from ``latest`` with backoff + a circuit
    breaker. Returns a status dict (``status`` in ``completed`` /
    ``preempted`` / ``parked`` plus the stats counters).

    Engine contract: the engine has ``training_dataloader`` attached
    (``deepspeed_tpu.initialize(..., training_data=...)``) so
    ``train_batch()`` owns the batch stream, and ``engine_factory`` (when
    given) rebuilds an equivalent engine — required for wedge recovery
    (the stuck thread owns the old engine) and for restarts before any
    checkpoint exists."""

    def __init__(self, engine=None, engine_factory: Optional[Callable] = None,
                 config: Optional[ResilienceConfig] = None,
                 save_dir: Optional[str] = None, journal=None):
        if engine is None and engine_factory is None:
            raise ValueError("TrainingSupervisor needs an engine or an "
                             "engine_factory")
        self.engine_factory = engine_factory
        self._engine = engine if engine is not None else engine_factory()
        if config is None:
            config = self._engine.config.resilience
        elif isinstance(config, dict):
            config = ResilienceConfig(**config)
        self.config = config
        self.save_dir = save_dir or self.config.save_dir
        if not self.save_dir:
            raise ValueError("resilience needs a save_dir (config "
                             "resilience.save_dir or the save_dir argument)")
        self.injector = self.config.faults.build_injector()
        # ops journal (docs/OBSERVABILITY.md "The ops event journal"):
        # restarts, parks, preemption saves, anomaly rollbacks,
        # checkpoint publications and wedges land in the SAME
        # schema-validated stream the serving stack uses, so a
        # train+serve host has one merged incident timeline
        if journal is None:
            from ..telemetry.journal import OpsJournal

            journal = OpsJournal(capacity=512, source="training")
        self.journal = journal
        self.rng = random.Random(self.config.seed)
        self.stats: Dict[str, Any] = {
            "train_restarts": 0, "steps_lost": 0, "anomaly_rollbacks": 0,
            "preemptions": 0, "wedges": 0, "urgent_save_s": None,
            "parked": False}
        # (global_step, loss) per completed step, restarts appending the
        # replayed steps again — losses_by_step() keeps the last write
        self.loss_log: List[tuple] = []
        self.restart_log: List[dict] = []
        self.dump_paths: List[dict] = []
        self._gen = 0                       # attempt generation token
        self._preempt = threading.Event()
        # the serving supervisor's backoff/breaker discipline, shared
        # implementation (utils/restart.py)
        self._restart_policy = RestartPolicy(
            self.config.restart_backoff_s, self.config.restart_backoff_max_s,
            self.config.restart_backoff_jitter,
            self.config.max_restarts_in_window, self.config.restart_window_s,
            self.rng)
        # consecutive-anomaly count of the live attempt, mirrored out of
        # the worker so the preemption path can refuse to publish an
        # anomalous state as 'latest'
        self._anomaly_streak = 0
        self._signal_installed = False
        self._prev_handler = None
        self._recorder = None

    # ------------------------------------------------------------ properties
    @property
    def engine(self):
        return self._engine

    def losses_by_step(self) -> Dict[int, float]:
        """Per-step losses with replayed steps collapsed (last write
        wins) — the resume-parity comparison surface."""
        return {step: loss for step, loss in self.loss_log}

    # ------------------------------------------------------------------ run
    def run(self, num_steps: int) -> Dict[str, Any]:
        cfg = self.config
        if not cfg.enabled:
            raise ValueError("resilience.enabled is false; enable it (or "
                             "drive engine.train_batch yourself) — a "
                             "disabled supervisor supervising would be a lie")
        self._install_sigterm()
        # a preemption honored by a PREVIOUS run() (urgent save done) must
        # not poison this one — calling run() again IS the resume path
        self._preempt.clear()
        try:
            self._restore_latest()
            while True:
                if self.stats["parked"]:
                    return self._status("parked")
                if self._preempt.is_set():
                    # preempted outside a clean boundary exit (e.g. during
                    # restart backoff): 'latest' already holds the last
                    # checkpoint — do not save mid-flight state
                    return self._status("preempted")
                box = self._attempt(num_steps)
                outcome = box["outcome"]
                if outcome == "completed":
                    return self._status("completed")
                if outcome == "preempted":
                    if self._anomaly_streak > 0:
                        # 'latest' must keep naming the last GOOD state:
                        # an urgent save here would publish the anomalous
                        # params and make a later rollback restore them
                        self.stats["preemptions"] += 1
                        logger.warning(
                            f"preempted with {self._anomaly_streak} "
                            "consecutive anomalies open: skipping the "
                            "urgent save — resume falls back to the last "
                            "good checkpoint")
                    else:
                        self._urgent_save()
                    return self._status("preempted")
                # crash / wedge / anomaly → supervised restart (the
                # anomaly_rollbacks counter is bumped inside
                # _handle_failure only when a rollback actually happened)
                if not self._handle_failure(outcome, box):
                    return self._status("parked")
        finally:
            self._restore_sigterm()

    # -------------------------------------------------------------- attempt
    def _attempt(self, num_steps: int) -> Dict[str, Any]:
        cfg = self.config
        gen = self._gen
        engine = self._engine
        box: Dict[str, Any] = {"outcome": None, "error": None,
                               "step_at_exit": None}
        watchdog = None
        if cfg.watchdog_enabled:
            watchdog = StepWatchdog(
                poll_s=cfg.watchdog_poll_s,
                step_timeout_s=cfg.step_timeout_s,
                factor=cfg.watchdog_factor,
                min_samples=cfg.watchdog_min_steps)
            watchdog.start()

        def loop():
            consecutive = 0
            self._anomaly_streak = 0        # fresh attempt, fresh streak
            good_losses: "deque[float]" = deque(maxlen=max(1, cfg.loss_window))
            try:
                while engine.global_steps < num_steps:
                    if self._gen != gen:
                        box["outcome"] = "superseded"
                        return
                    if self._preempt.is_set():
                        box["outcome"] = "preempted"
                        box["step_at_exit"] = engine.global_steps
                        return
                    step = engine.global_steps
                    # the injector hook runs INSIDE the watchdog bracket:
                    # slow_step models a wedged device call, and a wedge
                    # outside the bracket would be invisible. A step that
                    # changes the curriculum difficulty recompiles —
                    # minutes vs a sub-second median — so it is exempt
                    # from the bracket entirely (neither wedge-checked
                    # nor median-recorded): missing a real wedge on a
                    # compile step beats parking a healthy run mid-compile
                    bracket = watchdog is not None \
                        and not self._expect_recompile(engine, step)
                    if bracket:
                        watchdog.step_begin()
                    t0 = time.monotonic()
                    if self.injector is not None:
                        # may raise (crash, delivered last) or sleep
                        # (slow_step) here; sigterm/nan_grads arrive via
                        # the handler even when a crash is co-scheduled
                        def handle(ev):
                            if ev.kind == "sigterm":
                                self._deliver_sigterm()
                            elif ev.kind == "nan_grads":
                                self._poison_grads(engine)

                        self.injector.on_step(step, handler=handle)
                        if self._preempt.is_set():
                            if bracket:
                                watchdog.step_abort()
                            continue        # exit at loop top, pre-step
                    loss = float(engine.train_batch())
                    dt = time.monotonic() - t0
                    if bracket:
                        watchdog.step_end(dt)
                    if self._gen != gen:
                        box["outcome"] = "superseded"
                        return
                    self.loss_log.append((engine.global_steps, loss))
                    anomaly = self._is_anomaly(engine, loss, good_losses)
                    if anomaly:
                        consecutive += 1
                        self._anomaly_streak = consecutive
                        if consecutive >= max(1, cfg.max_consecutive_anomalies):
                            box["outcome"] = "anomaly"
                            box["step_at_exit"] = engine.global_steps
                            return
                    else:
                        consecutive = 0
                        self._anomaly_streak = 0
                        good_losses.append(loss)
                        if cfg.save_interval_steps > 0 and \
                                engine.global_steps % cfg.save_interval_steps == 0:
                            self._save(engine)
                box["outcome"] = "completed"
                box["step_at_exit"] = engine.global_steps
            except BaseException as e:  # noqa: BLE001 — becomes the crash path
                box["outcome"] = "crash"
                box["error"] = e
                box["step_at_exit"] = engine.global_steps

        worker = threading.Thread(target=loop, daemon=True,
                                  name="train-supervised-loop")
        worker.start()
        try:
            while worker.is_alive():
                worker.join(0.05)
                if watchdog is not None and watchdog.wedged.is_set() \
                        and worker.is_alive():
                    # abandon the stuck worker: it owns the engine until
                    # its device call returns, so bump the generation (it
                    # exits at the next loop-top check) and recover on a
                    # FRESH engine. Return a fresh dict — the abandoned
                    # worker still holds `box` and may scribble on it.
                    self._gen += 1
                    self.stats["wedges"] += 1
                    self.journal.emit("train_wedge",
                                      step=int(engine.global_steps))
                    self._dump_flight_recorder(engine, "train_wedge")
                    return {"outcome": "wedge", "error": None,
                            "step_at_exit": engine.global_steps}
        finally:
            if watchdog is not None:
                watchdog.stop()
        return box

    # ------------------------------------------------------------- anomalies
    @staticmethod
    def _expect_recompile(engine, step: int) -> bool:
        """True when the upcoming step changes the curriculum difficulty:
        the batch shape changes, so train_batch pays an XLA compile that
        can outrun the rolling-median wedge threshold by orders of
        magnitude. Pure probe — get_difficulty does not mutate the
        scheduler (``_apply_curriculum`` inside the step does the actual
        update, with the same ``step + 1`` the engine uses)."""
        sched = getattr(engine, "curriculum_scheduler", None)
        if sched is None:
            return False
        try:
            return sched.get_difficulty(step + 1) != \
                sched.get_difficulty(step)
        except Exception:       # a broken schedule fails in train_batch,
            return False        # with its real error — not in this probe

    def _is_anomaly(self, engine, loss: float,
                    good_losses: "deque[float]") -> bool:
        cfg = self.config
        if not cfg.anomaly_detection:
            return False
        if not math.isfinite(loss):
            return True
        m = getattr(engine, "_last_metrics", None)
        if m is not None and bool(np.asarray(m.get("overflow", False))):
            # the jitted update skipped this step on a non-finite grad
            # norm (every precision — the fp16 scale automaton additionally
            # rescales); one skip is the bounded step-skip, K in a row is
            # the rollback trigger
            return True
        if cfg.loss_spike_factor > 0 and len(good_losses) >= 3:
            med = statistics.median(good_losses)
            if med > 0 and loss > cfg.loss_spike_factor * med:
                return True
        return False

    @staticmethod
    def _poison_grads(engine) -> None:
        """nan_grads injection: poison the gradient accumulator so this
        step's update sees a non-finite norm (eager elementwise op —
        preserves each leaf's sharding, no resharding on the next jit)."""
        import jax

        nan = float("nan")
        engine.state = engine.state._replace(
            grad_acc=jax.tree.map(lambda g: g * nan, engine.state.grad_acc))

    # ----------------------------------------------------------- checkpoints
    def _client_state(self, engine) -> Dict[str, Any]:
        cs: Dict[str, Any] = {"resilience": {"format": 1}}
        loader = getattr(engine, "training_dataloader", None)
        if loader is not None and hasattr(loader, "state_dict"):
            try:
                cs["dataloader"] = loader.state_dict()
            except NotImplementedError:
                pass        # sampler/iterable sources own their position
        return cs

    def _save(self, engine, urgent: bool = False) -> None:
        engine.save_checkpoint(self.save_dir,
                               client_state=self._client_state(engine),
                               urgent=urgent)
        # journaled AFTER the save returns: the event records a
        # checkpoint that actually published (atomic 'latest' swap)
        self.journal.emit("checkpoint_saved",
                          step=int(engine.global_steps), urgent=urgent)

    def _restore_latest(self) -> bool:
        """Load ``latest`` (if any) into the current engine and restore
        the data-iterator position; returns True when a checkpoint was
        loaded. The gradient accumulator is explicitly zeroed — a crash
        mid-accumulation leaves stale partial sums the checkpoint knows
        nothing about."""
        import jax
        import jax.numpy as jnp

        engine = self._engine
        path, cs = engine.load_checkpoint(self.save_dir)
        if path is None:
            return False
        engine.state = engine.state._replace(
            grad_acc=jax.tree.map(jnp.zeros_like, engine.state.grad_acc))
        loader = getattr(engine, "training_dataloader", None)
        dl_state = (cs or {}).get("dataloader")
        if loader is not None and dl_state is not None \
                and hasattr(loader, "load_state_dict"):
            loader.load_state_dict(dl_state)
            engine.reset_data_iterator()
        elif loader is not None:
            # the checkpoint carries no data position (sampler/iterable
            # source — state_dict raised at save time): params rolled
            # back but the batch stream cannot, so replayed steps may see
            # different batches. Never silent — this voids the
            # byte-for-byte resume contract (docs/TRAINING.md).
            logger.warning(
                "resume: checkpoint has no dataloader position (source "
                "is not resumable) — replayed steps may train on "
                "different batches; resume is NOT byte-for-byte for "
                "this data source")
        return True

    def _urgent_save(self) -> None:
        """The SIGTERM grace-window save: joins any in-flight async
        write, completes synchronously, and records the measured wall
        time against the grace budget."""
        cfg = self.config
        engine = self._engine
        span = engine.tracer.begin("train_preempt_save", trace_id="train",
                                   attrs={"global_step": engine.global_steps})
        t0 = time.monotonic()
        try:
            self._save(engine, urgent=True)
        finally:
            span.end()
        dt = getattr(engine, "last_urgent_save_s", None)
        dt = float(dt) if dt is not None else time.monotonic() - t0
        self.stats["urgent_save_s"] = dt
        self.stats["preemptions"] += 1
        self.journal.emit("train_preempt_save",
                          step=int(engine.global_steps),
                          save_s=round(dt, 4),
                          within_grace=dt <= cfg.preempt_grace_s)
        if dt > cfg.preempt_grace_s:
            logger.error(f"urgent checkpoint took {dt:.2f}s — exceeds the "
                         f"{cfg.preempt_grace_s:.0f}s preemption grace "
                         "window; shrink the model state per host or raise "
                         "the grace budget")
        else:
            logger.info(f"urgent checkpoint saved in {dt:.2f}s "
                        f"(grace {cfg.preempt_grace_s:.0f}s)")

    # --------------------------------------------------------------- failure
    def _handle_failure(self, reason: str, box: Dict[str, Any]) -> bool:
        """Backoff (seeded jitter), circuit-breaker check, engine
        replacement, restore-from-latest. Returns False when the run
        parks (breaker tripped or recovery is impossible)."""
        cfg = self.config
        now = time.monotonic()
        err = box.get("error")
        logger.warning(f"train supervisor: {reason} at step "
                       f"{box.get('step_at_exit')}"
                       + (f" ({type(err).__name__}: {err})" if err else ""))
        n, backoff = self._restart_policy.record_failure(now)
        if backoff is None:             # circuit breaker tripped
            self.stats["parked"] = True
            self.journal.emit("train_parked", failures=n,
                              reason="circuit_breaker")
            logger.error(f"train supervisor PARKED after {n} failures in "
                         f"{cfg.restart_window_s:.0f}s window — not "
                         "restarting a run that keeps dying")
            return False
        needs_fresh_engine = reason == "wedge"
        has_checkpoint = os.path.exists(os.path.join(self.save_dir, "latest"))
        if (needs_fresh_engine or not has_checkpoint) \
                and self.engine_factory is None:
            # a wedged thread owns the old engine; and with no checkpoint
            # a restart must rebuild virgin state — both need the factory
            self.stats["parked"] = True
            self.journal.emit("train_parked", failures=n,
                              reason="no_engine_factory")
            logger.error(
                "train supervisor PARKED: recovery needs an engine_factory "
                f"({'wedged step' if needs_fresh_engine else 'no checkpoint yet'})")
            return False
        logger.warning(f"train supervisor: restart {n} in {backoff:.2f}s")
        if self._preempt.wait(backoff):
            return True                 # run() surfaces the preemption
        t0 = time.monotonic()
        if needs_fresh_engine or (not has_checkpoint
                                  and self.engine_factory is not None):
            self._engine = self.engine_factory()
        restored = self._restore_latest()
        step_at_exit = int(box.get("step_at_exit") or 0)
        steps_lost = max(0, step_at_exit - self._engine.global_steps)
        self.stats["train_restarts"] += 1
        self.stats["steps_lost"] += steps_lost
        if reason == "anomaly":
            # counted HERE, after the restore: a parked anomaly storm
            # never rolled anything back and must not report one
            self.stats["anomaly_rollbacks"] += 1
            self.journal.emit("train_anomaly_rollback",
                              step=step_at_exit,
                              resumed_step=int(self._engine.global_steps))
        recovery_s = time.monotonic() - t0
        self.journal.emit("train_restart", reason=reason, attempt=n,
                          steps_lost=steps_lost,
                          resumed_step=int(self._engine.global_steps),
                          recovery_s=round(recovery_s, 4))
        self.restart_log.append({
            "reason": reason, "attempt": n,
            "from_step": step_at_exit,
            "resumed_step": int(self._engine.global_steps),
            "steps_lost": steps_lost, "restored": restored,
            "backoff_s": backoff, "recovery_s": recovery_s})
        self._engine.tracer.begin(
            "train_restart", trace_id="train",
            attrs={"reason": reason, "attempt": n,
                   "steps_lost": steps_lost,
                   "resumed_step": int(self._engine.global_steps)}).end()
        if reason != "wedge" and self._engine.tracer.enabled:
            # wedges already dumped pre-restart; crash/anomaly restarts
            # dump only under telemetry, like serving restarts
            self._dump_flight_recorder(self._engine, f"train_{reason}")
        self._publish_gauges()
        logger.warning(
            f"train supervisor: restarted from step "
            f"{self._engine.global_steps} ({reason}; {steps_lost} steps "
            f"lost; {recovery_s:.2f}s)")
        return True

    # ------------------------------------------------------------- telemetry
    def _dump_flight_recorder(self, engine, reason: str) -> None:
        """Post-incident record (serving restart-dump idiom): spans in
        flight at the wedge/crash + whatever metrics providers were
        registered. Never raises — best effort by construction."""
        try:
            from ..telemetry import FlightRecorder

            if self._recorder is None or self._recorder.tracer is not engine.tracer:
                self._recorder = FlightRecorder(engine.tracer)
            self._recorder.snapshot_metrics()
            self.dump_paths.append(self._recorder.dump(reason=reason))
        except Exception as e:  # pragma: no cover - defensive
            logger.warning(f"train flight-recorder dump failed: {e!r}")

    def _publish_gauges(self) -> None:
        """docs/OBSERVABILITY.md gauge names: Train/train_restarts,
        Train/steps_lost, Train/anomaly_rollbacks through the monitor
        fan-out (same path as the loss curves)."""
        mon = getattr(self._engine, "monitor", None)
        if mon is None:
            return
        step = int(self._engine.global_steps)
        try:
            mon.write_events([
                ("Train/train_restarts", self.stats["train_restarts"], step),
                ("Train/steps_lost", self.stats["steps_lost"], step),
                ("Train/anomaly_rollbacks",
                 self.stats["anomaly_rollbacks"], step)])
        except Exception:  # pragma: no cover - defensive
            pass

    # --------------------------------------------------------------- signals
    def _install_sigterm(self) -> None:
        if not self.config.handle_sigterm:
            return
        if threading.current_thread() is not threading.main_thread():
            return
        try:
            self._prev_handler = signal.signal(signal.SIGTERM,
                                               self._on_sigterm)
            self._signal_installed = True
        except (ValueError, OSError):   # non-main interpreter contexts
            self._signal_installed = False

    def _restore_sigterm(self) -> None:
        if self._signal_installed:
            try:
                signal.signal(signal.SIGTERM, self._prev_handler
                              if self._prev_handler is not None
                              else signal.SIG_DFL)
            except (ValueError, OSError):  # pragma: no cover - defensive
                pass
            self._signal_installed = False

    def _on_sigterm(self, signum, frame) -> None:
        logger.warning("SIGTERM received: finishing the in-flight step, "
                       "then urgent-checkpointing inside the grace window")
        self._preempt.set()

    def _deliver_sigterm(self) -> None:
        """Injected preemption: go through the real signal machinery when
        our handler is installed (exercises the production path), else
        set the preempt flag directly. Waits for the flag so the worker
        deterministically exits before running another step."""
        if self._signal_installed:
            signal.raise_signal(signal.SIGTERM)
        else:
            self._preempt.set()
        self._preempt.wait(5.0)

    # --------------------------------------------------------- health report
    def health_report(self, recent_events: int = 20) -> Dict[str, Any]:
        """One queryable training-health answer (docs/OBSERVABILITY.md
        "The health report"), the training counterpart of
        ``ServingFrontend.health_report()``: progress, the resilience
        counters, the restart log tail, the open anomaly streak, and the
        recent ops-journal events — merged into a single dict."""
        report = {
            "wall_time": time.time(),
            "global_step": int(self._engine.global_steps),
            "parked": bool(self.stats["parked"]),
            "preempt_pending": self._preempt.is_set(),
            "anomaly_streak": int(self._anomaly_streak),
            "counters": {k: self.stats[k] for k in
                         ("train_restarts", "steps_lost",
                          "anomaly_rollbacks", "preemptions", "wedges")},
            "urgent_save_s": self.stats["urgent_save_s"],
            "restart_log": list(self.restart_log[-5:]),
            "events": self.journal.events(limit=recent_events),
        }
        return report

    def health_report_text(self, recent_events: int = 10) -> str:
        """The training health report rendered for a terminal."""
        r = self.health_report(recent_events=recent_events)
        c = r["counters"]
        lines = [
            "== training health ==",
            f"step={r['global_step']}"
            + ("  PARKED" if r["parked"] else "")
            + ("  PREEMPT-PENDING" if r["preempt_pending"] else "")
            + (f"  anomaly_streak={r['anomaly_streak']}"
               if r["anomaly_streak"] else ""),
            f"restarts={c['train_restarts']} steps_lost={c['steps_lost']} "
            f"rollbacks={c['anomaly_rollbacks']} "
            f"preemptions={c['preemptions']} wedges={c['wedges']}",
        ]
        if r["events"]:
            lines.append("recent events:")
            lines.append(self.journal.render_text(limit=recent_events))
        return "\n".join(lines)

    # ---------------------------------------------------------------- status
    def _status(self, status: str) -> Dict[str, Any]:
        out = {"status": status,
               "completed_steps": int(self._engine.global_steps),
               "restarts": len(self.restart_log),
               "restart_log": list(self.restart_log),
               "dump_paths": list(self.dump_paths)}
        out.update(self.stats)
        return out
