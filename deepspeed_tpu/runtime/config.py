"""The framework's JSON config system.

Counterpart of reference ``runtime/config.py:688`` (``DeepSpeedConfig``) and
its ~40 typed sub-configs (``_initialize_params`` :781, zero config
``runtime/zero/config.py:86``, offload config ``runtime/zero/offload_config.py``).
The JSON surface keeps the reference's key names (``train_batch_size``,
``zero_optimization``, ``fp16``/``bf16``, ``optimizer``/``scheduler`` blocks,
``activation_checkpointing``, monitors, ``flops_profiler``, ``comms_logger``,
``aio``...) so configs written for the reference work here, plus a TPU-native
``mesh`` block describing the device-mesh axes
(data/fsdp/tensor/pipe/sequence/expert) that all parallelism rides on.
"""

from __future__ import annotations

import json
from enum import Enum
from typing import Any, Dict, List, Optional, Union

from pydantic import Field, model_validator

from .config_utils import AUTO, DSConfigModel, dict_raise_error_on_duplicate_keys
from .resilience import ResilienceConfig
from ..serving.config import (AdmissionConfig, KVQuantConfig, KVTierConfig,
                              PrefixCacheConfig, ServingConfig,
                              SpeculativeConfig, WeightQuantConfig)
from ..telemetry.config import TelemetryConfig
from ..utils.logging import logger

# ----------------------------------------------------------------- defaults
TRAIN_BATCH_SIZE_DEFAULT = None
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None
STEPS_PER_PRINT_DEFAULT = 10


class DtypeEnum(str, Enum):
    fp32 = "fp32"
    fp16 = "fp16"
    bf16 = "bf16"

    def to_jnp(self):
        import jax.numpy as jnp

        return {"fp32": jnp.float32, "fp16": jnp.float16, "bf16": jnp.bfloat16}[self.value]


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class FP16Config(DSConfigModel):
    """Mirrors reference fp16 block (runtime/config.py get_fp16_enabled)."""
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0


class BF16Config(DSConfigModel):
    enabled: bool = False
    immediate_grad_update: bool = False


class OptimizerConfig(DSConfigModel):
    """{"type": "Adam"|"AdamW"|"Lamb"|"Lion"|"SGD"|..., "params": {...}}"""
    type: str = "Adam"
    params: Dict[str, Any] = Field(default_factory=dict)


class SchedulerConfig(DSConfigModel):
    """{"type": "WarmupLR"|"WarmupDecayLR"|"WarmupCosineLR"|"OneCycle"|"LRRangeTest", "params": {...}}"""
    type: str = "WarmupLR"
    params: Dict[str, Any] = Field(default_factory=dict)


class OffloadParamConfig(DSConfigModel):
    """Mirrors reference runtime/zero/offload_config.py DeepSpeedZeroOffloadParamConfig."""
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = int(1e8)
    max_in_cpu: int = int(1e9)
    pin_memory: bool = False


class OffloadOptimizerConfig(DSConfigModel):
    """Mirrors reference DeepSpeedZeroOffloadOptimizerConfig; ``ratio`` is the
    ZeRO-Offload++ Twin-Flow partial-offload fraction (reference engine.py:703)."""
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = 1.0


class ZeroConfig(DSConfigModel):
    """Mirrors reference runtime/zero/config.py:86-291. On TPU the stages map
    to sharding rules over the mesh's fsdp/data axes (see runtime/zero.py):
    stage 1 shards optimizer state, stage 2 additionally reduce-scatters
    gradients, stage 3 shards parameters; bucket/overlap knobs are accepted
    for config compatibility (XLA's latency-hiding scheduler plays that role)."""
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = int(5e8)
    allgather_partitions: bool = True
    allgather_bucket_size: int = int(5e8)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[OffloadParamConfig] = None
    offload_optimizer: Optional[OffloadOptimizerConfig] = None
    sub_group_size: int = int(1e9)
    cpu_offload_param: Optional[bool] = None
    cpu_offload_use_pin_memory: Optional[bool] = None
    cpu_offload: Optional[bool] = None
    prefetch_bucket_size: int = int(5e7)
    param_persistence_threshold: int = int(1e5)
    model_persistence_threshold: int = int(1e9)
    max_live_parameters: int = int(1e9)
    max_reuse_distance: int = int(1e9)
    gather_16bit_weights_on_model_save: bool = False
    stage3_gather_fp16_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    # ZeRO++ (reference zero/config.py:256-272)
    zero_hpz_partition_size: int = 1
    zero_quantized_weights: bool = False
    zero_quantized_nontrainable_weights: bool = False
    zero_quantized_gradients: bool = False
    # MiCS
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False
    memory_efficient_linear: bool = True
    pipeline_loading_checkpoint: bool = False
    override_module_apply: bool = True

    @model_validator(mode="before")
    @classmethod
    def _legacy_cpu_offload(cls, values):
        if isinstance(values, dict):
            if values.get("cpu_offload") and not values.get("offload_optimizer"):
                values["offload_optimizer"] = {"device": "cpu"}
            if values.get("cpu_offload_param") and not values.get("offload_param"):
                values["offload_param"] = {"device": "cpu"}
        return values


class ActivationCheckpointingConfig(DSConfigModel):
    """Mirrors reference activation_checkpointing block
    (activation_checkpointing/checkpointing.py:1065). On TPU this selects a
    ``jax.checkpoint`` (remat) policy; partition_activations maps to
    sequence/TP-sharded remat saves."""
    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


class CommsLoggerConfig(DSConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = Field(default_factory=list)


class MonitorBackendConfig(DSConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DSConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


class CSVConfig(DSConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class FlopsProfilerConfig(DSConfigModel):
    enabled: bool = False
    recompute_fwd_factor: float = 0.0
    # reference default is 1; here step 1 pays the XLA compile, which would
    # make the timed achieved-TFLOPS meaningless, so default past warmup
    profile_step: int = 3
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class AioConfig(DSConfigModel):
    """Mirrors reference runtime/swap_tensor/aio_config.py; consumed by the
    native async-IO module (csrc equivalent: deepspeed_tpu/csrc/aio.cpp)."""
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


class PipelineConfig(DSConfigModel):
    stages: int = 1
    partition_method: str = "parameters"
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    pipe_partitioned: bool = True
    grad_partitioned: bool = True
    micro_batches: Optional[int] = None


class MeshConfig(DSConfigModel):
    """TPU-native parallel topology: sizes of the named mesh axes. -1 on the
    data axis means "all remaining devices". The ordering matters for ICI
    locality: innermost axes (tensor/sequence) get the fastest links."""
    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    pipe: int = 1
    sequence: int = 1
    expert: int = 1
    axis_order: List[str] = Field(
        default_factory=lambda: ["pipe", "data", "fsdp", "sequence", "expert", "tensor"])


class CheckpointConfig(DSConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = Field(default_factory=dict)
    async_save: bool = False


class DataTypesConfig(DSConfigModel):
    grad_accum_dtype: Optional[str] = None


class ElasticityConfig(DSConfigModel):
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True
    model_parallel_size: int = 1     # v0.2 (reference elasticity/config.py)
    num_gpus_per_node: int = 1       # chips per host, v0.2 host granularity


class HybridEngineConfig(DSConfigModel):
    """RLHF train↔generate engine (reference runtime/hybrid_engine.py +
    config get_hybrid_engine_config)."""
    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8


class AutotuningConfig(DSConfigModel):
    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = False
    metric: str = "throughput"
    start_profile_step: int = 3
    end_profile_step: int = 5
    num_tuning_micro_batch_sizes: int = 3
    tuner_type: str = "gridsearch"
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    arg_mappings: Dict[str, str] = Field(default_factory=dict)


class DeepSpeedConfigError(Exception):
    pass


class DeepSpeedTpuConfig(DSConfigModel):
    """Top-level config. Mirrors reference ``DeepSpeedConfig``
    (runtime/config.py:688) including the batch-size triple resolution:
    train_batch_size = micro_batch_per_device × gradient_accumulation_steps ×
    data-parallel world size."""

    train_batch_size: Optional[Union[int, str]] = None
    train_micro_batch_size_per_gpu: Optional[Union[int, str]] = None
    gradient_accumulation_steps: Optional[Union[int, str]] = None
    steps_per_print: int = STEPS_PER_PRINT_DEFAULT
    dump_state: bool = False
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    sparse_gradients: bool = False
    gradient_clipping: float = 0.0
    # numeric sanitizer (reference runtime/utils.py CheckOverflow): raise
    # with offending leaf paths on non-finite loss/grad-norm (debug mode —
    # forces a host sync per micro step)
    check_numerics: bool = False
    communication_data_type: Optional[str] = None
    seq_parallel_communication_data_type: str = "fp32"
    disable_allgather: bool = False

    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None
    fp16: FP16Config = Field(default_factory=FP16Config)
    bf16: BF16Config = Field(default_factory=BF16Config)
    zero_optimization: ZeroConfig = Field(default_factory=ZeroConfig)
    activation_checkpointing: ActivationCheckpointingConfig = Field(
        default_factory=ActivationCheckpointingConfig)
    comms_logger: CommsLoggerConfig = Field(default_factory=CommsLoggerConfig)
    tensorboard: MonitorBackendConfig = Field(default_factory=MonitorBackendConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)
    flops_profiler: FlopsProfilerConfig = Field(default_factory=FlopsProfilerConfig)
    aio: AioConfig = Field(default_factory=AioConfig)
    pipeline: PipelineConfig = Field(default_factory=PipelineConfig)
    mesh: MeshConfig = Field(default_factory=MeshConfig)
    checkpoint: CheckpointConfig = Field(default_factory=CheckpointConfig)
    data_types: DataTypesConfig = Field(default_factory=DataTypesConfig)
    elasticity: ElasticityConfig = Field(default_factory=ElasticityConfig)
    autotuning: AutotuningConfig = Field(default_factory=AutotuningConfig)
    hybrid_engine: HybridEngineConfig = Field(default_factory=HybridEngineConfig)
    # request-serving layer (deepspeed_tpu/serving/, docs/SERVING.md)
    serving: ServingConfig = Field(default_factory=ServingConfig)
    # prefix-cache KV block reuse for the v2 ragged engine (docs/SERVING.md
    # "Prefix caching"); also reachable as ``serving.prefix_cache``
    prefix_cache: PrefixCacheConfig = Field(default_factory=PrefixCacheConfig)
    # speculative decoding for the v2 ragged engine (docs/SERVING.md
    # "Speculative decoding"); also reachable as ``serving.speculative``
    speculative: SpeculativeConfig = Field(default_factory=SpeculativeConfig)
    # int8/fp8 KV-cache quantization for the v2 ragged engine
    # (docs/SERVING.md "KV quantization"); also reachable as
    # ``serving.kv_quant``
    kv_quant: KVQuantConfig = Field(default_factory=KVQuantConfig)
    # int8/fp8 weight serving for the v2 ragged engine (docs/SERVING.md
    # "Weight quantization"); also reachable as ``serving.weight_quant``
    weight_quant: WeightQuantConfig = Field(default_factory=WeightQuantConfig)
    # tiered KV memory for the v2 ragged engine (docs/SERVING.md
    # "KV tiering"); also reachable as ``serving.kv_tier``
    kv_tier: KVTierConfig = Field(default_factory=KVTierConfig)
    # reservation-aware admission + preemptive KV spill for the v2
    # scheduler (docs/SERVING.md "Admission and preemption"); also
    # reachable as ``serving.admission``
    admission: AdmissionConfig = Field(default_factory=AdmissionConfig)
    # unified telemetry (docs/OBSERVABILITY.md): training step spans here;
    # serving request tracing via ``serving.telemetry``
    telemetry: TelemetryConfig = Field(default_factory=TelemetryConfig)
    # training fault tolerance (docs/TRAINING.md "Fault tolerance"):
    # preemption urgent-save + auto-resume, step watchdog, anomaly
    # rollback, training chaos injection (runtime/resilience.py)
    resilience: ResilienceConfig = Field(default_factory=ResilienceConfig)
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    seed: int = 1234
    zero_allow_untested_optimizer: bool = True
    zero_force_ds_cpu_optimizer: bool = True
    compression_training: Dict[str, Any] = Field(default_factory=dict)
    data_efficiency: Dict[str, Any] = Field(default_factory=dict)
    curriculum_learning: Dict[str, Any] = Field(default_factory=dict)

    # ------------------------------------------------------------ dtype helpers
    @property
    def precision(self) -> DtypeEnum:
        if self.bf16.enabled:
            return DtypeEnum.bf16
        if self.fp16.enabled:
            return DtypeEnum.fp16
        return DtypeEnum.fp32

    @property
    def zero_enabled(self) -> bool:
        return self.zero_optimization.stage > 0

    # ------------------------------------------------------- batch resolution
    def resolve_batch_sizes(self, dp_world_size: int) -> None:
        """Reference runtime/config.py _batch_assertion/_set_batch_related_parameters:
        any two of (train_batch, micro_batch, gas) determine the third."""
        train = self.train_batch_size if isinstance(self.train_batch_size, int) else None
        micro = (self.train_micro_batch_size_per_gpu
                 if isinstance(self.train_micro_batch_size_per_gpu, int) else None)
        gas = (self.gradient_accumulation_steps
               if isinstance(self.gradient_accumulation_steps, int) else None)

        if train is not None and micro is not None and gas is not None:
            pass
        elif train is not None and micro is not None:
            gas = train // (micro * dp_world_size)
        elif train is not None and gas is not None:
            micro = train // (gas * dp_world_size)
        elif micro is not None and gas is not None:
            train = micro * gas * dp_world_size
        elif micro is not None:
            gas = 1
            train = micro * dp_world_size
        elif train is not None:
            gas = 1
            micro = train // dp_world_size
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu must be set")

        if train != micro * gas * dp_world_size:
            raise DeepSpeedConfigError(
                f"Inconsistent batch config: train_batch_size={train} != "
                f"micro({micro}) * gas({gas}) * dp_world_size({dp_world_size})")
        if train <= 0 or micro <= 0 or gas <= 0:
            raise DeepSpeedConfigError(
                f"Batch sizes must be positive: train={train} micro={micro} gas={gas}")
        self.train_batch_size = train
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = gas

    def print_config(self, name: str = "DeepSpeedTpuConfig") -> None:
        logger.info(f"{name}:\n{json.dumps(self.model_dump(mode='json'), indent=2, default=str)}")


def load_config(config: Union[str, dict, DeepSpeedTpuConfig, None]) -> DeepSpeedTpuConfig:
    """Accepts a path to a JSON file, a dict, an existing config object, or
    None (all defaults)."""
    if config is None:
        return DeepSpeedTpuConfig()
    if isinstance(config, DeepSpeedTpuConfig):
        return config
    if isinstance(config, str):
        with open(config) as fh:
            config = json.load(fh, object_pairs_hook=dict_raise_error_on_duplicate_keys)
    if not isinstance(config, dict):
        raise DeepSpeedConfigError(f"Unsupported config type: {type(config)}")
    return DeepSpeedTpuConfig(**config)
