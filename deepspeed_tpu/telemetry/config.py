"""``telemetry: {...}`` config block (docs/OBSERVABILITY.md, docs/CONFIG.md).

Mounted on both :class:`~deepspeed_tpu.serving.config.ServingConfig`
(request tracing + flight recorder) and
:class:`~deepspeed_tpu.runtime.config.DeepSpeedTpuConfig` (training step
spans). Defaults to disabled — the no-op tracer — so nothing pays for
telemetry it didn't ask for.
"""

from __future__ import annotations

from typing import Optional

from ..runtime.config_utils import DSConfigModel


class TelemetryConfig(DSConfigModel):
    enabled: bool = False
    # completed-span ring capacity (the flight recorder's history window);
    # open spans are capped at the same number
    max_spans: int = 8192
    # metric-registry snapshots kept alongside the spans
    max_metric_snapshots: int = 32
    # write a flight-recorder dump when a replica/scheduler dies, at most
    # max_error_dumps per error_dump_window_s (sliding window)
    dump_on_error: bool = True
    max_error_dumps: int = 3
    error_dump_window_s: float = 3600.0
    # where dumps land (None = <tmpdir>/deepspeed_tpu_telemetry)
    dump_dir: Optional[str] = None
    # mirror context-manager spans into jax.profiler.TraceAnnotation so
    # host spans line up with XLA traces in the same Perfetto view
    xla_annotations: bool = False

    def build_tracer(self):
        """The configured tracer — the shared NOOP singleton when
        disabled, so call sites hold one object either way."""
        from .tracer import NOOP_TRACER, Tracer

        if not self.enabled:
            return NOOP_TRACER
        return Tracer(enabled=True, max_spans=self.max_spans,
                      xla_annotations=self.xla_annotations)

    def build_recorder(self, tracer, metrics=None, role="frontend"):
        """Flight recorder over ``tracer``; ``metrics`` (an object with
        ``snapshot()``) is registered as the first snapshot provider.
        ``role`` lands in dump filenames so fleet processes sharing a
        dump dir never collide."""
        from .flight_recorder import FlightRecorder

        rec = FlightRecorder(tracer, max_snapshots=self.max_metric_snapshots,
                             dump_dir=self.dump_dir,
                             max_error_dumps=self.max_error_dumps,
                             error_dump_window_s=self.error_dump_window_s,
                             role=role)
        if metrics is not None:
            rec.add_metrics_provider("serving", metrics.snapshot)
        return rec
