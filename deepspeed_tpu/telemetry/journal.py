"""Unified ops event journal: one bounded, schema-validated stream.

Before this module every operationally-significant event logged to its
own corner: supervisor restarts to the Python logger, brownout flips to
a gauge, handoff fallbacks to a counter, anomaly rollbacks to a training
stats dict. Reconstructing "what happened to the fleet between 14:02 and
14:05" meant grepping four surfaces with four formats. The journal is
the single answer: every lifecycle event — serving (replica restarts and
parks, brownout transitions, KV handoffs and their fallbacks, request
failovers, alert transitions) and training (restarts, parks, preemption
saves, anomaly rollbacks, checkpoint publications, wedges) — lands in
one in-memory ring of schema-validated records, queryable through
``ServingFrontend.health_report()`` / ``TrainingSupervisor.
health_report()`` and dumpable as JSONL.

Design rules:

- **Bounded.** A deque of ``capacity`` events; an optional streaming
  JSONL sink is byte-capped (``max_file_bytes``) — a crash-looping fleet
  must not fill the disk with its own obituary.
- **Schema-validated at emit.** ``EVENT_SCHEMAS`` names each kind's
  required detail fields; an unknown kind or a missing field raises
  immediately (call sites are framework code — a schema violation is a
  bug to catch in tests, not a condition to tolerate). Extra fields are
  allowed; every value must be JSON-serializable.
- **Ordered.** ``seq`` increments under the lock and ``t`` is the host
  monotonic clock, so events sort identically by either; consumers and
  the chaos suite assert monotonic timestamps.
- **Passive.** Emitting never blocks on I/O beyond the optional
  append-only sink and never mutates the systems it describes.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from ..utils.locks import RankedLock
from ..utils.logging import logger

#: kind -> required detail-field names. Extra fields are welcome (they
#: make events MORE diagnosable); missing required ones are a bug.
EVENT_SCHEMAS: Dict[str, frozenset] = {
    # ------------------------------------------------------------ serving
    # supervisor replaced a DEAD replica (docs/SERVING.md "Fault
    # tolerance"); recovery_s = death -> replacement serving
    "replica_restart": frozenset({"replica", "attempt", "recovery_s"}),
    # circuit breaker gave up on a replica slot
    "replica_parked": frozenset({"replica", "crashes_in_window"}),
    # a dead replica's request re-enqueued (stream resumes elsewhere)
    "request_failover": frozenset({"uid", "attempt"}),
    # admission queue entered/left degraded-capacity shedding
    "brownout_enter": frozenset({"healthy_fraction"}),
    "brownout_exit": frozenset({"healthy_fraction"}),
    # disaggregated serving (docs/SERVING.md "Disaggregated serving"):
    # a finished prompt's KV staged for a decode-role replica, or the
    # handoff degraded to re-prefill ("where": export/staging_full/import)
    "handoff_staged": frozenset({"uid", "from_replica"}),
    "handoff_fallback": frozenset({"uid", "where"}),
    # SLO burn-rate alerting (docs/OBSERVABILITY.md "SLOs and burn-rate
    # alerts"): rule transitions of the AlertEngine state machine
    "alert_firing": frozenset({"alert", "request_class", "slo_kind",
                               "burn_fast", "burn_slow"}),
    "alert_resolved": frozenset({"alert", "firing_s"}),
    # tiered KV memory (docs/SERVING.md "KV tiering"): the fleet's
    # prefix-cache spill tier churned since the last ~1s look — deltas
    # of blocks spilled/restored/dropped plus current host residency
    "kv_tier_pressure": frozenset({"spilled", "restored", "dropped",
                                   "host_bytes"}),
    # admission overhaul (docs/SERVING.md "Admission and preemption"):
    # the scheduler spilled a running sequence's KV to the tier to make
    # room — blocks freed, the replica it happened on
    "sequence_preempted": frozenset({"uid", "blocks", "replica"}),
    # elastic autoscaling (docs/SERVING.md "Elastic autoscaling"): the
    # FleetController grew/shrank the pool (replica added/removed, the
    # resulting fleet size, and why), flipped a replica's role, or
    # toggled proactive brownout from slow-window budget burn. Each
    # fires exactly once per completed controller action — the churn
    # suite cross-checks against the controller's decision log.
    "scale_up": frozenset({"replica", "fleet_size", "reason"}),
    "scale_down": frozenset({"replica", "fleet_size", "reason"}),
    "replica_reroled": frozenset({"replica", "from_role", "to_role"}),
    "brownout_proactive": frozenset({"active", "fraction"}),
    # fleet KV locality (docs/SERVING.md "Fleet KV locality"): the grow
    # path warmed a new replica's prefix cache from a donor's exported
    # blocks before rotation — how many blocks landed, whose cache they
    # came from, and how long the warm-up took
    "replica_warmup": frozenset({"replica", "blocks", "source",
                                 "warmup_s"}),
    # serving fabric (docs/SERVING.md "Multi-host serving"): a remote
    # replica handle lost its transport (the handle went DEAD and its
    # in-flight requests failed over) / a rebuilt handle re-attached to
    # its replica server (supervisor restart or reconnect)
    "replica_disconnected": frozenset({"replica", "reason"}),
    "replica_reconnected": frozenset({"replica"}),
    # gray-failure quarantine (docs/SERVING.md "Fleet fault tolerance"):
    # a remote replica left the routable set for slow RPCs / deadline
    # misses (in-flight streams continue) / a probe RPC re-admitted it
    # after this long in quarantine
    "replica_quarantined": frozenset({"replica", "reason"}),
    "replica_readmitted": frozenset({"replica", "quarantined_s"}),
    # frontend federation (docs/SERVING.md "Frontend federation"): a
    # peer frontend's hello was accepted / a peer connection died (its
    # federated in-flight work fails over on the ADOPTING side) / one
    # local replica was bound to a peer's export channel
    "peer_connected": frozenset({"peer", "epoch"}),
    "peer_lost": frozenset({"peer", "reason"}),
    "replica_exported": frozenset({"replica", "peer"}),
    # partition tolerance (docs/SERVING.md "Frontend federation"): a
    # peer's bootstrap channel went silent past the staleness window
    # (once per silence episode) / an export channel's seat lease
    # expired — the exporter cancelled its mirrors and took the
    # borrowed seats back
    "peer_partition": frozenset({"peer", "idle_s"}),
    "lease_expired": frozenset({"peer", "replica", "idle_s"}),
    # fleet observability (docs/OBSERVABILITY.md "Fleet observability"):
    # the frontend's scrape endpoint came up (where operators should
    # point fleetctl/Prometheus), and a fleet-wide debug dump completed
    # (how many processes contributed, where the files landed)
    "obs_listen": frozenset({"address"}),
    "fleet_dump": frozenset({"sources", "dir"}),
    # a replica server accepted a frontend hello (emitted SERVER-side;
    # reaches the frontend's FleetJournal over the status stream, so
    # every server process contributes at least one sourced event)
    "server_hello": frozenset({"replica", "role", "reset"}),
    # multi-tenant serving (docs/SERVING.md "Multi-model & multi-tenant
    # serving"): a tenant crossed into throttled state — its sliding-
    # window dispatch rate exceeded token_rate, or a KV budget refusal
    # ("reason": token_rate/kv_budget). Fires on the edge, not per
    # refused request; the tenant_over_quota_<tenant> gauge tracks state.
    "tenant_throttled": frozenset({"tenant", "reason"}),
    # ----------------------------------------------------------- training
    # supervised restart (docs/TRAINING.md "Fault tolerance")
    "train_restart": frozenset({"reason", "attempt", "steps_lost",
                                "resumed_step"}),
    "train_parked": frozenset({"failures"}),
    # SIGTERM urgent checkpoint inside the grace window
    "train_preempt_save": frozenset({"step", "save_s"}),
    # K consecutive anomalies rolled the run back to the last good state
    "train_anomaly_rollback": frozenset({"step", "resumed_step"}),
    # a checkpoint became 'latest' (periodic or urgent)
    "checkpoint_saved": frozenset({"step", "urgent"}),
    # watchdog abandoned a wedged step
    "train_wedge": frozenset({"step"}),
}


def validate_event(event: dict) -> List[str]:
    """Problems with one journal record (empty list = valid)."""
    problems = []
    for field in ("seq", "t", "wall_time", "source", "kind", "detail"):
        if field not in event:
            problems.append(f"missing field {field!r}")
    kind = event.get("kind")
    if kind is not None and kind not in EVENT_SCHEMAS:
        problems.append(f"unknown kind {kind!r}")
    detail = event.get("detail")
    if not isinstance(detail, dict):
        problems.append("detail: not an object")
    elif kind in EVENT_SCHEMAS:
        for req in sorted(EVENT_SCHEMAS[kind] - set(detail)):
            problems.append(f"{kind}: missing detail field {req!r}")
    return problems


def validate_events(events: Sequence[dict]) -> List[str]:
    """Schema + ordering problems across a whole event list (empty =
    valid): per-event schema, strictly-increasing seq, non-decreasing
    monotonic timestamps. The chaos suite and the bench ``slo`` phase
    run this over live journals."""
    problems = []
    prev_seq, prev_t = None, None
    for ev in events:
        for p in validate_event(ev):
            problems.append(f"seq={ev.get('seq')}: {p}")
        seq, t = ev.get("seq"), ev.get("t")
        if prev_seq is not None and isinstance(seq, int) and seq <= prev_seq:
            problems.append(f"seq={seq}: not increasing after {prev_seq}")
        if prev_t is not None and isinstance(t, (int, float)) and t < prev_t:
            problems.append(f"seq={seq}: timestamp went backwards")
        prev_seq = seq if isinstance(seq, int) else prev_seq
        prev_t = t if isinstance(t, (int, float)) else prev_t
    return problems


class OpsJournal:
    # lock discipline (docs/CONCURRENCY.md): ring, seq counter and sink
    # accounting move together under one lock — seq order in the ring
    # and in the JSONL sink must agree (see emit). The sink write under
    # the lock is a BASELINED blocking-while-locked exception: it is the
    # documented durability contract, bounded to one line per event.
    _GUARDED_BY = {
        "_ring": "_lock",
        "_seq": "_lock",
        "_emitted": "_lock",
        "_file_bytes": "_lock",
        "_file_capped": "_lock",
    }

    def __init__(self, capacity: int = 512, source: str = "serving",
                 path: Optional[str] = None,
                 max_file_bytes: int = 8 * 1024 * 1024,
                 clock=time.monotonic):
        self.source = str(source)
        self.capacity = max(1, int(capacity))
        self.path = path
        self.max_file_bytes = int(max_file_bytes)
        self.clock = clock
        self._lock = RankedLock("telemetry.journal")
        self._ring: "deque[dict]" = deque(maxlen=self.capacity)
        self._seq = 0
        self._emitted = 0                   # total ever (ring evicts)
        self._file_bytes = 0
        self._file_capped = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def total_emitted(self) -> int:
        with self._lock:
            return self._emitted

    # ------------------------------------------------------------- emitting
    def emit(self, kind: str, **detail) -> dict:
        """Append one validated event; returns the record. Raises
        ``ValueError`` on an unknown kind, a missing required field, or a
        non-JSON-serializable detail value — schema violations are bugs
        in framework call sites, caught by the test suite, never silent
        garbage in the stream."""
        if kind not in EVENT_SCHEMAS:
            raise ValueError(f"unknown journal event kind {kind!r} "
                             f"(known: {sorted(EVENT_SCHEMAS)})")
        missing = EVENT_SCHEMAS[kind] - set(detail)
        if missing:
            raise ValueError(f"journal event {kind!r} missing required "
                             f"detail fields {sorted(missing)}")
        try:
            line_detail = json.dumps(detail, sort_keys=True)
        except (TypeError, ValueError) as e:
            raise ValueError(f"journal event {kind!r} detail is not "
                             f"JSON-serializable: {e}") from None
        # ring append AND sink append happen under ONE lock hold: two
        # emitting threads (router tick vs supervisor) must not be able
        # to write their JSONL lines out of seq order — the durable
        # record has to pass validate_events during exactly the
        # multi-threaded incidents it exists to capture. Journal traffic
        # is a handful of events per incident, so serialized I/O is noise.
        with self._lock:
            self._seq += 1
            self._emitted += 1
            event = {"seq": self._seq, "t": self.clock(),
                     "wall_time": time.time(), "source": self.source,
                     "kind": kind, "detail": detail}
            self._ring.append(event)
            self._append_file_locked(event, line_detail)
        return event

    def _append_file_locked(self, event: dict, line_detail: str) -> None:
        """Append one line to the optional JSONL sink; caller holds the
        lock. Byte-capped and failure-capped — the journal must never
        kill (or fill the disk of) its host."""
        if self.path is None or self._file_capped:
            return
        line = json.dumps({**{k: event[k] for k in
                              ("seq", "t", "wall_time", "source", "kind")},
                           "detail": json.loads(line_detail)},
                          sort_keys=True) + "\n"
        if self._file_bytes + len(line) > self.max_file_bytes:
            self._file_capped = True
            logger.warning(
                f"ops journal sink {self.path} reached its "
                f"{self.max_file_bytes}-byte cap; further events stay "
                "in-memory only (dump() still writes the ring)")
            return
        try:
            with open(self.path, "a") as fh:
                fh.write(line)
            self._file_bytes += len(line)
        except OSError as e:
            self._file_capped = True
            logger.warning(f"ops journal sink {self.path} failed "
                           f"({e!r}); further events stay in-memory only")

    # ------------------------------------------------------------- querying
    def events(self, kinds: Optional[Sequence[str]] = None,
               since_seq: int = 0,
               limit: Optional[int] = None) -> List[dict]:
        """Events currently in the ring (oldest first), optionally
        filtered by kind / sequence number, truncated to the LAST
        ``limit`` matches (the recent past is the interesting part)."""
        with self._lock:
            out = [ev for ev in self._ring if ev["seq"] > since_seq]
        if kinds is not None:
            want = set(kinds)
            out = [ev for ev in out if ev["kind"] in want]
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def count(self, kind: str) -> int:
        with self._lock:
            return sum(1 for ev in self._ring if ev["kind"] == kind)

    # ------------------------------------------------------------ rendering
    def dump(self, path: str) -> int:
        """Write the current ring as JSONL; returns the event count."""
        events = self.events()
        with open(path, "w") as fh:
            for ev in events:
                fh.write(json.dumps(ev, sort_keys=True, default=str) + "\n")
        return len(events)

    def render_text(self, limit: int = 20) -> str:
        """Human-readable tail — the ``health_report()`` text block."""
        lines = []
        for ev in self.events(limit=limit):
            detail = " ".join(f"{k}={ev['detail'][k]}"
                              for k in sorted(ev["detail"]))
            lines.append(f"[{ev['t']:12.3f}] {ev['source']:8s} "
                         f"{ev['kind']:22s} {detail}")
        return "\n".join(lines)
