"""Fleet observability plane: the frontend-side aggregation half of
cross-process telemetry (docs/OBSERVABILITY.md "Fleet observability").

PR 15's fabric and PR 18's federation made the serving fleet
multi-process; this module is where the per-process telemetry streams
those processes forward (fabric/remote.py ``_ev_status``) land and
become one pane of glass:

- :func:`ingest_remote_spans` rebases remote span dicts onto the local
  monotonic clock (the transport's heartbeat-derived offset), offsets
  their span ids into a per-source id range (two processes both count
  span ids from 1), and re-parents the cross-process edge via the
  ``remote_parent_id`` attr the replica server stamped on its root span
  — after which the local tracer holds ONE gap-free ``req-<uid>`` chain.
- :class:`FleetJournal` holds schema-validated remote journal events in
  bounded per-source rings next to the local :class:`OpsJournal`,
  exactly-once per source (seq-deduped), merged on read.
- :func:`fleet_chrome_trace` renders a merged span set with
  process→pid and replica→tid mapping, so a fleet trace opens in
  Perfetto as one timeline with a named track per process.
- :class:`ObsEndpoint` is the stdlib ``http.server`` scrape surface
  (``/metrics``, ``/health``, ``/trace``, ``/dump``) that
  ``scripts/fleetctl.py`` and Prometheus talk to.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..utils.locks import RankedLock
from ..utils.logging import logger
from .journal import OpsJournal, validate_event

#: span-id range width per forwarding source: remote span ids (each
#: process counts from 1) are offset by ``(index + 1) << SOURCE_ID_BITS``
#: so merged chains never alias. 2^32 spans per process outlives any
#: bounded ring by orders of magnitude.
SOURCE_ID_BITS = 32


def source_id_offset(index: int) -> int:
    """The span-id offset for forwarding source ``index`` (0-based);
    the frontend's own spans occupy range 0."""
    return (int(index) + 1) << SOURCE_ID_BITS


def ingest_remote_spans(tracer, spans: Sequence[Dict[str, Any]], *,
                        offset: int, clock_offset_s: float,
                        source: str, pid: Optional[int] = None) -> int:
    """Adopt forwarded span dicts into ``tracer``: ids shifted by
    ``offset``, timestamps rebased by ``clock_offset_s`` (remote
    monotonic minus local monotonic — the transport's heartbeat
    estimate), and ``source``/``pid`` stamped into attrs for the
    chrome-trace pid mapping. A span whose attrs carry
    ``remote_parent_id`` parents onto that FRONTEND-local span id
    verbatim (the cross-process edge); any other parent id is a
    remote-local id and shifts with the span. Returns spans adopted."""
    n = 0
    for d in spans:
        if not isinstance(d, dict):
            continue
        e = dict(d)
        attrs = dict(e.get("attrs") or {})
        e["span_id"] = int(e.get("span_id") or 0) + offset
        rp = attrs.get("remote_parent_id")
        if rp is not None:
            e["parent_id"] = int(rp)
        elif e.get("parent_id") is not None:
            e["parent_id"] = int(e["parent_id"]) + offset
        try:
            e["t_start"] = float(e["t_start"]) - clock_offset_s
        except (KeyError, TypeError, ValueError):
            continue
        if e.get("t_end") is not None:
            e["t_end"] = float(e["t_end"]) - clock_offset_s
        attrs.setdefault("source", source)
        if pid is not None:
            attrs.setdefault("pid", int(pid))
        e["attrs"] = attrs
        tracer.ingest(e)
        n += 1
    return n


class FleetJournal:
    """The local :class:`OpsJournal` plus bounded per-source rings of
    REMOTE journal events, exactly-once per source.

    Remote events arrive on the fabric status stream already carrying
    their origin's ``seq``/``source`` stamps; ingest validates each
    against :data:`EVENT_SCHEMAS` (a remote peer speaking an unknown
    kind is dropped and counted, never trusted into the merged view) and
    dedupes by per-source high-water seq — a reconnect replaying the
    tail of a journal delivers each event once."""

    # lock discipline (docs/CONCURRENCY.md): per-source rings and seq
    # watermarks move together under one lock; the wrapped local journal
    # has its own (higher-ranked) lock and is never called while ours is
    # held.
    _GUARDED_BY = {
        "_remote": "_lock",
        "_last_seq": "_lock",
        "_dropped": "_lock",
        "_duplicates": "_lock",
    }

    def __init__(self, local: OpsJournal, capacity_per_source: int = 512):
        self.local = local
        self.capacity_per_source = max(1, int(capacity_per_source))
        self._lock = RankedLock("telemetry.fleet")
        self._remote: Dict[str, deque] = {}
        self._last_seq: Dict[str, int] = {}
        self._dropped: Dict[str, int] = {}
        self._duplicates: Dict[str, int] = {}

    # ------------------------------------------------------------- ingest
    def ingest(self, source: str,
               events: Iterable[dict]) -> Tuple[int, int]:
        """Adopt a batch of remote events from ``source`` (oldest
        first). Returns ``(accepted, dropped)`` — duplicates (seq at or
        below the source's high-water mark, e.g. a reconnect replay) are
        neither, they are silently skipped and counted separately."""
        accepted = dropped = 0
        for ev in events:
            problems = validate_event(ev) if isinstance(ev, dict) else \
                ["not an object"]
            with self._lock:
                ring = self._remote.get(source)
                if ring is None:
                    ring = self._remote[source] = deque(
                        maxlen=self.capacity_per_source)
                if problems:
                    self._dropped[source] = \
                        self._dropped.get(source, 0) + 1
                    dropped += 1
                    continue
                seq = int(ev["seq"])
                if seq <= self._last_seq.get(source, 0):
                    self._duplicates[source] = \
                        self._duplicates.get(source, 0) + 1
                    continue
                self._last_seq[source] = seq
                ring.append(dict(ev))
                accepted += 1
        if dropped:
            logger.warning(f"fleet journal: dropped {dropped} "
                           f"schema-invalid event(s) from {source!r}")
        return accepted, dropped

    def last_seq(self, source: str) -> int:
        with self._lock:
            return self._last_seq.get(source, 0)

    # ------------------------------------------------------------ reading
    def events(self, kinds: Optional[Sequence[str]] = None,
               limit: Optional[int] = None,
               sources: Optional[Sequence[str]] = None) -> List[dict]:
        """Merged view (local + every remote source), ordered by wall
        time — the one clock every process shares well enough for a
        human-readable incident timeline. Per-source seq order is
        preserved by the stable sort (wall-time ties keep arrival
        order)."""
        out = [] if (sources is not None and
                     self.local.source not in sources) \
            else list(self.local.events(kinds=kinds))
        with self._lock:
            for src, ring in self._remote.items():
                if sources is not None and src not in sources:
                    continue
                out.extend(ev for ev in ring
                           if kinds is None or ev["kind"] in kinds)
        out.sort(key=lambda ev: ev.get("wall_time", 0.0))
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def sources(self) -> Dict[str, Dict[str, int]]:
        """Per-source ingest accounting (the fleet ops surface's
        "who is reporting" table); the local journal appears under its
        own source name."""
        out = {self.local.source: {
            "events": len(self.local), "last_seq": self.local.total_emitted,
            "dropped": 0, "duplicates": 0, "remote": 0}}
        with self._lock:
            for src, ring in self._remote.items():
                out[src] = {"events": len(ring),
                            "last_seq": self._last_seq.get(src, 0),
                            "dropped": self._dropped.get(src, 0),
                            "duplicates": self._duplicates.get(src, 0),
                            "remote": 1}
        return out

    def count(self, kind: str) -> int:
        n = self.local.count(kind)
        with self._lock:
            for ring in self._remote.values():
                n += sum(1 for ev in ring if ev["kind"] == kind)
        return n


# ------------------------------------------------------------ chrome trace

def fleet_chrome_trace(spans: Sequence[Dict[str, Any]],
                       meta: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
    """Merged-fleet Chrome trace: one pid per PROCESS (the ``source``
    attr :func:`ingest_remote_spans` stamped; frontend-local spans land
    in pid 1, "frontend"), one tid per replica/track within it (the
    ``replica`` attr where present, else the span's thread). Named via
    ``process_name``/``thread_name`` metadata events, so Perfetto shows
    `frontend` / `replica-0@host` tracks on one shared timeline — the
    ingest-time clock rebase is what makes the x-axis honest."""
    procs: Dict[str, int] = {}
    tids: Dict[Tuple[str, Any], int] = {}
    events: List[Dict[str, Any]] = []

    def _pid(src: str) -> int:
        pid = procs.get(src)
        if pid is None:
            pid = procs[src] = len(procs) + 1
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "args": {"name": src}})
        return pid

    for s in spans:
        attrs = dict(s.get("attrs") or {})
        src = str(attrs.get("source", "frontend"))
        pid = _pid(src)
        track = attrs.get("replica")
        track_key = (src, track if track is not None
                     else f"trace:{s.get('trace_id')}")
        tid = tids.get(track_key)
        if tid is None:
            tid = tids[track_key] = \
                sum(1 for k in tids if k[0] == src) + 1
            name = (f"replica-{track}" if track is not None
                    else str(s.get("trace_id") or "untraced"))
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": name}})
        args = attrs
        args["span_id"] = s.get("span_id")
        if s.get("parent_id") is not None:
            args["parent_id"] = s["parent_id"]
        if s.get("trace_id"):
            args["trace_id"] = s["trace_id"]
        ev = {"name": s["name"], "cat": "telemetry",
              "ts": float(s["t_start"]) * 1e6, "pid": pid, "tid": tid,
              "args": args}
        if s.get("t_end") is not None:
            ev["ph"] = "X"
            ev["dur"] = max(0.0, (s["t_end"] - s["t_start"]) * 1e6)
        else:
            ev["ph"] = "B"
        events.append(ev)
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    if meta:
        out["otherData"] = dict(meta)
    return out


# ------------------------------------------------------------ ops endpoint

class ObsEndpoint:
    """Stdlib HTTP scrape surface over a :class:`ServingFrontend`
    (duck-typed — anything with ``render_prometheus`` / ``health_report``
    / ``tracer`` / ``debug_dump`` works):

    - ``GET /metrics`` — Prometheus text exposition
    - ``GET /health``  — ``health_report()`` JSON
    - ``GET /trace``   — recent merged fleet Chrome trace JSON
    - ``GET /dump``    — trigger ``debug_dump()``, return the paths

    One daemon thread per request (``ThreadingHTTPServer``); handlers
    hold NO framework lock themselves — every route reads through the
    frontend's public snapshot surfaces. Never on unless the
    ``observability:`` config block says so."""

    def __init__(self, frontend, listen: str = "127.0.0.1:0"):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        host, _, port = listen.rpartition(":")
        if not host:
            raise ValueError(f"observability listen {listen!r} "
                             "is not host:port")
        endpoint = self
        self.frontend = frontend

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):       # quiet: the journal is the log
                pass

            def do_GET(self):
                try:
                    endpoint._route(self)
                except BrokenPipeError:      # scraper went away mid-write
                    pass
                except Exception as e:  # pragma: no cover - defensive
                    logger.error(f"obs endpoint: {self.path} failed: {e!r}")
                    try:
                        self.send_error(500, explain=str(e))
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="obs-endpoint", daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    def _route(self, handler) -> None:
        fe = self.frontend
        path = handler.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            fe.metrics.counter("obs_requests").inc()
        except Exception:
            pass
        if path == "/metrics":
            body = fe.render_prometheus().encode()
            ctype = "text/plain; version=0.0.4"
        elif path == "/health":
            body = json.dumps(fe.health_report(), default=str,
                              sort_keys=True).encode()
            ctype = "application/json"
        elif path == "/trace":
            trace = fleet_chrome_trace(
                fe.tracer.export(include_open=True),
                meta={"endpoint": self.address})
            body = json.dumps(trace, default=str).encode()
            ctype = "application/json"
        elif path == "/dump":
            body = json.dumps(fe.debug_dump(), default=str,
                              sort_keys=True).encode()
            ctype = "application/json"
        else:
            handler.send_error(404)
            return
        handler.send_response(200)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
