"""Low-overhead tracing core: monotonic-clock spans in a bounded ring.

One :class:`Tracer` serves every telemetry consumer in the framework
(docs/OBSERVABILITY.md): serving request traces (queue→route→admit→
prefill→decode→finish, serving/), per-forward engine spans
(inference/v2/scheduler.py), and training step spans (runtime/engine.py).
Design constraints, in priority order:

- **Disabled must cost nothing.** ``Tracer(enabled=False).span(...)``
  returns one shared no-op singleton — no allocation, no lock, no clock
  read on the hot path (tests/test_telemetry.py pins this with
  tracemalloc). Call sites guard attribute-dict construction on
  ``tracer.enabled``.
- **Bounded memory.** Completed spans land in a ``deque(maxlen=...)``
  ring — the flight recorder's "recent history" window. Open spans are
  tracked separately (so a crash dump shows what was *in flight*) with a
  hard cap against leaks from error paths that never ``end()``.
- **Explicit trace ids.** A trace is any string key (``req-17``,
  ``replica-0``, ``train``); spans carry it verbatim. Parenting within a
  thread is automatic for context-manager spans (a thread-local stack);
  cross-thread chains (serving requests hop submit→router→replica
  threads) pass ``parent=`` explicitly via :meth:`Tracer.begin`.

Timestamps are ``time.monotonic()`` seconds; :func:`chrome_trace` turns a
span list into Chrome ``trace_event`` JSON (chrome://tracing / Perfetto),
mapping trace ids to pids so each request/replica/train trace renders as
its own named track.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..utils.locks import RankedLock


class _NoopSpan:
    """Shared do-nothing span returned by a disabled tracer. One instance
    for the whole process — identity is the allocation-free guarantee."""

    __slots__ = ()

    def set(self, key: str, value: Any = None) -> "_NoopSpan":
        return self

    def end(self) -> None:
        return None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


class Span:
    """One timed interval: ``[t_start, t_end]`` on the monotonic clock,
    a ``trace_id`` naming the chain it belongs to, an optional parent
    span id, and a free-form ``attrs`` dict. ``end()`` is idempotent —
    stage code and terminal cleanup may both call it; the first wins."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "t_start", "t_end", "attrs", "tid", "_xla_ctx")

    def __init__(self, tracer: "Tracer", name: str, trace_id: Optional[str],
                 parent_id: Optional[int], attrs: Optional[dict] = None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = next(tracer._ids)
        self.parent_id = parent_id
        self.attrs = dict(attrs) if attrs else {}
        self.tid = threading.get_ident()
        self._xla_ctx = None
        self.t_end: Optional[float] = None
        self.t_start = tracer.clock()          # last: exclude setup time
        tracer._note_open(self)

    def set(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def end(self) -> None:
        if self.t_end is not None:
            return
        self.t_end = self.tracer.clock()
        self.tracer._record(self)

    # -- context-manager form: auto-parents off the thread-local stack and
    # (optionally) mirrors into jax.profiler.TraceAnnotation so host spans
    # line up with XLA device traces in the same Perfetto view.
    def __enter__(self) -> "Span":
        self.tracer._push(self)
        if self.tracer.xla_annotations:
            try:
                from jax.profiler import TraceAnnotation

                self._xla_ctx = TraceAnnotation(self.name)
                self._xla_ctx.__enter__()
            except Exception:
                self._xla_ctx = None
        return self

    def __exit__(self, *exc) -> bool:
        if self._xla_ctx is not None:
            try:
                self._xla_ctx.__exit__(*exc)
            finally:
                self._xla_ctx = None
        self.tracer._pop(self)
        self.end()
        return False

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "t_start": self.t_start, "t_end": self.t_end,
                "tid": self.tid, "attrs": dict(self.attrs)}


class Tracer:
    """Thread-safe span factory + bounded completed-span ring.

    ``span(...)`` is the context-manager form (auto-parented within the
    thread); ``begin(...)`` returns a span the caller must ``end()`` —
    the form for intervals that start and finish on different threads.
    Both return :data:`NOOP_SPAN` when disabled."""

    # lock discipline (docs/CONCURRENCY.md): the span rings are written
    # from every instrumented thread; the thread-local nesting stack
    # needs no lock by construction
    _GUARDED_BY = {"_spans": "_lock", "_open": "_lock",
                   "_completed_total": "_lock"}

    def __init__(self, enabled: bool = True, max_spans: int = 8192,
                 clock=time.monotonic, xla_annotations: bool = False):
        self.enabled = bool(enabled)
        self.clock = clock
        self.xla_annotations = bool(xla_annotations)
        self.max_spans = int(max_spans)
        self._spans: "deque[Span]" = deque(maxlen=self.max_spans)
        # open (started, un-ended) spans, so crash dumps show in-flight
        # work; insertion-ordered for the leak cap below
        self._open: Dict[int, Span] = {}
        self._lock = RankedLock("telemetry.tracer")
        self._ids = itertools.count(1)
        # monotone count of spans EVER completed (the ring forgets, this
        # doesn't) — the cursor base for drain_completed()
        self._completed_total = 0
        self._local = threading.local()

    # ------------------------------------------------------------- creation
    def span(self, name: str, trace_id: Optional[str] = None,
             parent: Optional[Span] = None, attrs: Optional[dict] = None):
        """Context-manager span. Parent defaults to the innermost span()
        currently entered on this thread (nesting)."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is None:
            parent = self.current()
        if parent is not None and trace_id is None:
            trace_id = parent.trace_id
        return Span(self, name, trace_id,
                    parent.span_id if parent is not None else None, attrs)

    def begin(self, name: str, trace_id: Optional[str] = None,
              parent: Optional[Span] = None, attrs: Optional[dict] = None):
        """Explicitly-ended span (cross-thread chains); never stacked."""
        if not self.enabled:
            return NOOP_SPAN
        return Span(self, name, trace_id,
                    parent.span_id if parent is not None else None, attrs)

    def current(self) -> Optional[Span]:
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None

    # ------------------------------------------------------------ internals
    def _push(self, span: Span) -> None:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        stack.append(span)

    def _pop(self, span: Span) -> None:
        stack = getattr(self._local, "stack", None)
        if stack and stack[-1] is span:
            stack.pop()
        elif stack and span in stack:       # mis-nested exit: best effort
            stack.remove(span)

    def _note_open(self, span: Span) -> None:
        with self._lock:
            self._open[span.span_id] = span
            # leak cap: error paths may abandon spans without end(); keep
            # at most max_spans of them (oldest dropped, they were likely
            # abandoned long ago)
            while len(self._open) > self.max_spans:
                self._open.pop(next(iter(self._open)))

    def _record(self, span: Span) -> None:
        with self._lock:
            self._open.pop(span.span_id, None)
            self._spans.append(span)
            self._completed_total += 1

    # -------------------------------------------------------------- reading
    def export(self, include_open: bool = True) -> List[Dict[str, Any]]:
        """Snapshot of recorded spans (oldest first), plus — by default —
        currently-open spans with ``t_end=None`` and ``attrs["open"]``
        set, so dumps taken mid-flight (or on a crash) show what was
        running."""
        with self._lock:
            done = [s.to_dict() for s in self._spans]
            open_ = [s.to_dict() for s in self._open.values()] \
                if include_open else []
        for d in open_:
            d["attrs"]["open"] = True
        return done + open_

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._open.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    # --------------------------------------------- cross-process forwarding
    @property
    def completed_total(self) -> int:
        """Monotone count of spans ever completed — pass it as the
        cursor to :meth:`drain_completed` to skip existing history."""
        with self._lock:
            return self._completed_total

    def drain_completed(self, cursor: int,
                        limit: int = 256) -> Tuple[List[Dict[str, Any]],
                                                   int]:
        """Completed spans recorded after ``cursor`` (a value this method
        previously returned; start at 0), oldest first, at most ``limit``
        per call — the fabric status stream's delta feed. Spans that
        aged out of the ring before being drained are silently lost (the
        ring is the retention policy; forwarding rides it, it does not
        extend it). Returns ``(span_dicts, new_cursor)``."""
        with self._lock:
            total = self._completed_total
            pending = total - int(cursor)
            if pending <= 0:
                return [], total
            avail = min(pending, len(self._spans))
            take = min(avail, int(limit))
            start = len(self._spans) - avail
            out = [self._spans[i].to_dict()
                   for i in range(start, start + take)]
            return out, total - (avail - take)

    def ingest(self, d: Dict[str, Any]) -> None:
        """Adopt one remote span dict (a :meth:`Span.to_dict` shipped
        over the fabric) into the completed ring verbatim — no id
        allocation, no clock read; the caller owns id-collision avoidance
        (telemetry/fleet.py offsets remote ids per source) and clock
        alignment (timestamps must already be rebased to this process's
        monotonic clock). No-op when disabled."""
        if not self.enabled:
            return
        s = Span.__new__(Span)
        s.tracer = self
        s.name = str(d.get("name", "remote"))
        s.trace_id = d.get("trace_id")
        s.span_id = int(d.get("span_id") or 0)
        s.parent_id = d.get("parent_id")
        s.t_start = float(d.get("t_start") or 0.0)
        s.t_end = d.get("t_end")
        s.attrs = dict(d.get("attrs") or {})
        s.tid = int(d.get("tid") or 0)
        s._xla_ctx = None
        with self._lock:
            self._spans.append(s)
            self._completed_total += 1


#: Process-wide disabled tracer: the default everywhere a tracer is
#: optional, so un-configured call sites pay only an attribute check.
NOOP_TRACER = Tracer(enabled=False, max_spans=1)


# --------------------------------------------------------------- chrome trace

def chrome_trace(spans: Sequence[Dict[str, Any]],
                 meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Render exported span dicts as Chrome ``trace_event`` JSON
    (the object form — ``chrome://tracing`` and Perfetto both load it).

    Each distinct ``trace_id`` becomes a pid with a ``process_name``
    metadata event, so requests/replicas/train render as separate named
    tracks; span attrs land in ``args``. Open spans (no ``t_end``) are
    emitted as ``B`` (begin-only) events — Perfetto shows them as
    unterminated slices, which is exactly what an in-flight crash dump
    means."""
    pids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for s in spans:
        tid_key = s.get("trace_id") or "untraced"
        if tid_key not in pids:
            pid = len(pids) + 1
            pids[tid_key] = pid
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "args": {"name": tid_key}})
    for s in spans:
        pid = pids[s.get("trace_id") or "untraced"]
        args = dict(s.get("attrs") or {})
        args["span_id"] = s.get("span_id")
        if s.get("parent_id") is not None:
            args["parent_id"] = s["parent_id"]
        ev = {"name": s["name"], "cat": "telemetry",
              "ts": float(s["t_start"]) * 1e6,
              "pid": pid, "tid": int(s.get("tid") or 0), "args": args}
        if s.get("t_end") is not None:
            ev["ph"] = "X"
            ev["dur"] = max(0.0, (s["t_end"] - s["t_start"]) * 1e6)
        else:
            ev["ph"] = "B"
        events.append(ev)
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    if meta:
        out["otherData"] = dict(meta)
    return out


def validate_chrome_trace(obj: Any) -> List[str]:
    """Structural check of a Chrome-trace object (or its JSON string):
    returns a list of problems, empty when the trace is loadable. Used by
    ``bench.py``'s telemetry phase and tests so saved artifacts are
    verified, not assumed."""
    problems: List[str] = []
    if isinstance(obj, (str, bytes)):
        try:
            obj = json.loads(obj)
        except Exception as e:
            return [f"not valid JSON: {e}"]
    if not isinstance(obj, dict):
        return [f"top level must be an object, got {type(obj).__name__}"]
    events = obj.get("traceEvents")
    if not isinstance(events, list):
        return ["missing 'traceEvents' list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        if not isinstance(ev.get("name"), str) or not ev.get("name"):
            problems.append(f"{where}: missing 'name'")
        ph = ev.get("ph")
        if ph not in ("X", "B", "E", "M", "i", "I", "C"):
            problems.append(f"{where}: bad phase {ph!r}")
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int):
                problems.append(f"{where}: '{key}' must be an int")
        if ph != "M":
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)):
                problems.append(f"{where}: 'ts' must be a number")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: 'X' event needs dur >= 0")
    return problems


def trace_coverage(spans: Iterable[Dict[str, Any]], t0: float,
                   t1: float) -> float:
    """Fraction of the window ``[t0, t1]`` covered by the union of the
    given spans' intervals (open spans count up to ``t1``). The bench
    telemetry phase uses this to enforce that a request's span chain
    accounts for ≥95% of its measured TTFT — coverage, not vibes."""
    if t1 <= t0:
        return 1.0
    ivals: List[Tuple[float, float]] = []
    for s in spans:
        a = max(float(s["t_start"]), t0)
        b = min(float(s["t_end"]) if s.get("t_end") is not None else t1, t1)
        if b > a:
            ivals.append((a, b))
    if not ivals:
        return 0.0
    ivals.sort()
    covered = 0.0
    cur_a, cur_b = ivals[0]
    for a, b in ivals[1:]:
        if a > cur_b:
            covered += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    covered += cur_b - cur_a
    return covered / (t1 - t0)
