"""Unified telemetry: tracing core, flight recorder, config block.

One subsystem behind the framework's three observability surfaces
(docs/OBSERVABILITY.md):

- **request tracing** — every serving request carries a span chain
  queue→route→admit→prefill→decode→finish (serving/, inference/v2/);
- **step profiling** — training fwd+bwd / optimizer brackets in
  runtime/engine.py, published through monitor/;
- **flight recorder** — a bounded ring of recent spans + metric
  snapshots, dumped as raw JSON and Chrome ``trace_event`` JSON on
  demand and on replica/scheduler errors.

Importable without JAX: the tracer is pure stdlib; the optional
``jax.profiler.TraceAnnotation`` pass-through imports lazily.
"""

from .config import TelemetryConfig  # noqa: F401
from .flight_recorder import FlightRecorder  # noqa: F401
from .tracer import (NOOP_SPAN, NOOP_TRACER, Span, Tracer,  # noqa: F401
                     chrome_trace, trace_coverage, validate_chrome_trace)

__all__ = ["Tracer", "Span", "NOOP_TRACER", "NOOP_SPAN", "TelemetryConfig",
           "FlightRecorder", "chrome_trace", "validate_chrome_trace",
           "trace_coverage"]
