"""Unified telemetry: tracing core, flight recorder, config block.

One subsystem behind the framework's three observability surfaces
(docs/OBSERVABILITY.md):

- **request tracing** — every serving request carries a span chain
  queue→route→admit→prefill→decode→finish (serving/, inference/v2/);
- **step profiling** — training fwd+bwd / optimizer brackets in
  runtime/engine.py, published through monitor/;
- **flight recorder** — a bounded ring of recent spans + metric
  snapshots, dumped as raw JSON and Chrome ``trace_event`` JSON on
  demand and on replica/scheduler errors;
- **SLO observability** (docs/OBSERVABILITY.md "SLOs and burn-rate
  alerts") — sliding-window quantiles/rates over the cumulative metrics
  (windowed.py), per-class SLO burn-rate alerting (slo.py), and the
  unified ops event journal (journal.py) behind
  ``ServingFrontend.health_report()`` /
  ``TrainingSupervisor.health_report()``.

Importable without JAX: the tracer is pure stdlib; the optional
``jax.profiler.TraceAnnotation`` pass-through imports lazily.
"""

from .config import TelemetryConfig  # noqa: F401
from .flight_recorder import FlightRecorder  # noqa: F401
from .tracer import (NOOP_SPAN, NOOP_TRACER, Span, Tracer,  # noqa: F401
                     chrome_trace, trace_coverage, validate_chrome_trace)
from .journal import (EVENT_SCHEMAS, OpsJournal,  # noqa: F401
                      validate_event, validate_events)
from .windowed import WindowedMetrics  # noqa: F401
from .slo import (AlertEngine, AlertRule, SLOClassTarget,  # noqa: F401
                  SLOConfig)

__all__ = ["Tracer", "Span", "NOOP_TRACER", "NOOP_SPAN", "TelemetryConfig",
           "FlightRecorder", "chrome_trace", "validate_chrome_trace",
           "trace_coverage", "OpsJournal", "EVENT_SCHEMAS",
           "validate_event", "validate_events", "WindowedMetrics",
           "AlertEngine", "AlertRule", "SLOClassTarget", "SLOConfig"]
