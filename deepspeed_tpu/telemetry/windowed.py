"""Sliding-window metrics over a cumulative :class:`MetricsRegistry`.

Every histogram and counter in the serving registry is cumulative since
boot — the right surface for Prometheus (rates and windows are the
*scraper's* job) but useless for in-process questions a production
operator actually pages on: "p95 interactive TTFT over the last minute",
"shed rate over the last five". This module adds the windowed view
WITHOUT touching the cumulative surface: a ring of interval snapshots
(``bucket_s`` apart, ``history_s`` deep) of the registry's raw counter
values and histogram bucket counts, and window queries computed as
*deltas* between the newest snapshot and the one at the window's start.

Quantiles don't subtract; bucket counts do — so the windowed percentile
is exact bucket math (the same interpolation as the cumulative
:meth:`Histogram.percentile`, via the shared
:meth:`Histogram.percentile_from`), not an approximation layered on
summaries. Correctness leans on :meth:`Histogram.buckets_snapshot`
being one atomic read: per-bucket deltas between two snapshots are
non-negative and internally consistent even with ``observe`` racing
(regression-tested with racing threads). Deltas are additionally
clamped at zero so a histogram re-declared with ``reset=True``
mid-flight degrades to "window restarts here" instead of negative
counts.

Ticks come from the serving router's ~1/s loop (the same place the
flight recorder snapshots metrics); anything may also call
:meth:`tick` directly (tests, the bench ``slo`` phase). The whole layer
is passive — nothing here mutates the registry.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

from ..utils.locks import RankedLock


def _percentile_from(bounds, counts, q):
    """The shared bucket interpolation (lazy import: serving.metrics is
    stdlib-only but its package __init__ pulls in the whole serving
    stack, which itself imports telemetry — resolving at call time keeps
    the module import order unconstrained)."""
    from ..serving.metrics import Histogram

    return Histogram.percentile_from(bounds, counts, q)


def _fraction_over_from(bounds, counts, threshold):
    """Shared bucket-boundary convention for "fraction over threshold"
    (same lazy-import rationale as :func:`_percentile_from`)."""
    from ..serving.metrics import Histogram

    return Histogram.fraction_over_from(bounds, counts, threshold)


class WindowedMetrics:
    # lock discipline (docs/CONCURRENCY.md): uncoordinated tickers (the
    # router loop + every health_report caller) mutate the ring
    _GUARDED_BY = {"_ring": "_lock"}

    def __init__(self, registry, bucket_s: float = 1.0,
                 history_s: float = 900.0,
                 clock=time.monotonic):
        self.registry = registry
        self.bucket_s = max(0.05, float(bucket_s))
        self.max_snapshots = max(2, int(float(history_s) / self.bucket_s))
        self.clock = clock
        self._lock = RankedLock("telemetry.windowed")
        # ring of {"t": monotonic, "counters": {...}, "hists": {...}}
        # snapshots; each snapshot is immutable after append
        self._ring: List[dict] = []

    # ------------------------------------------------------------- ticking
    def tick(self, now: Optional[float] = None) -> None:
        """Capture one snapshot, safe to call at ANY rate. Two rules
        keep the ring healthy under uncoordinated tickers (the router
        loop plus every ``health_report()`` caller):

        - **Out-of-order snapshots are dropped**: concurrent tickers can
          capture t1 < t2 yet race to append t2 first; appending t1
          after would make the "newest" snapshot older (and staler) than
          its predecessor, and window math would read a busy second as
          empty.
        - **Faster-than-cadence ticks refresh the head instead of
          appending**: the ring is count-bounded, so a dashboard polling
          at a few Hz would otherwise evict old snapshots until the
          "slow" window silently shrank to seconds. Replacing the head
          keeps reports up-to-the-moment while persistent entries stay
          ~``bucket_s`` apart (worst case every other entry, so the ring
          always covers at least ``history_s/2``)."""
        now = now if now is not None else self.clock()
        raw = self.registry.raw_snapshot()
        snap = {"t": now, "counters": raw["counters"], "hists": raw["hists"]}
        with self._lock:
            if self._ring and now <= self._ring[-1]["t"]:
                return
            if len(self._ring) >= 2 and \
                    now - self._ring[-2]["t"] < self.bucket_s:
                self._ring[-1] = snap
                return
            self._ring.append(snap)
            if len(self._ring) > self.max_snapshots:
                del self._ring[:len(self._ring) - self.max_snapshots]

    def maybe_tick(self, now: Optional[float] = None) -> None:
        """Cadence-gated tick for polling loops: cheap no-op while the
        last snapshot is younger than ``bucket_s``."""
        now = now if now is not None else self.clock()
        with self._lock:
            last = self._ring[-1]["t"] if self._ring else None
        if last is None or now - last >= self.bucket_s:
            self.tick(now)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # ------------------------------------------------------------ windows
    def _window_pair(self, window_s: float):
        """(baseline, newest) snapshots spanning AT MOST ``window_s``:
        newest is the latest snapshot, baseline the oldest one still
        inside the window (t >= newest.t - window_s). Under-spanning is
        the contract an alerting consumer needs — a window NEVER
        includes observations older than asked for, so stale incidents
        age out on schedule even when tick cadence was irregular. Early
        in life (ring younger than the window) it degrades to "since
        start of history". None when fewer than two snapshots exist OR
        when no snapshot besides the newest lies inside the window
        (ticks stalled longer than the window): that is *no data*, and
        answering from an older baseline would smuggle the stale
        incident back into the window — the exact staleness this
        contract precludes. Consequence: ``window_s`` below the tick
        cadence (``bucket_s``) always reads as no data."""
        with self._lock:
            ring = list(self._ring)
        if len(ring) < 2:
            return None
        newest = ring[-1]
        cutoff = newest["t"] - float(window_s)
        base = next((snap for snap in ring[:-1] if snap["t"] >= cutoff),
                    None)
        if base is None:
            return None
        return base, newest

    @staticmethod
    def _hist_delta(base_h, new_h):
        """Non-negative per-bucket delta between two bucket snapshots
        (``(bounds, counts, sum, count)``). A missing/reset baseline
        contributes zero — the delta becomes the newest counts whole."""
        bounds, counts, total_sum, total = new_h
        if base_h is None or base_h[0] != bounds:
            return bounds, list(counts), float(total_sum), int(total)
        d_counts = [max(0, a - b) for a, b in zip(counts, base_h[1])]
        return (bounds, d_counts,
                max(0.0, float(total_sum) - float(base_h[2])),
                max(0, int(total) - int(base_h[3])))

    def window_hist(self, name: str, window_s: float):
        """Delta bucket snapshot ``(bounds, counts, sum, count)`` of
        histogram ``name`` over the window, or None (unknown name / not
        enough history)."""
        pair = self._window_pair(window_s)
        if pair is None:
            return None
        base, newest = pair
        new_h = newest["hists"].get(name)
        if new_h is None:
            return None
        return self._hist_delta(base["hists"].get(name), new_h)

    def window_percentile(self, name: str, q: float,
                          window_s: float) -> Optional[float]:
        """q-th percentile of histogram ``name`` over the last
        ``window_s`` seconds (bucket resolution, same interpolation as
        the cumulative estimate). None when the window holds no
        observations — distinguishable from a genuine 0.0."""
        d = self.window_hist(name, window_s)
        if d is None or d[3] == 0:
            return None
        bounds, counts, _, _ = d
        return _percentile_from(bounds, counts, q)

    def window_count(self, name: str, window_s: float) -> int:
        """Histogram observations recorded inside the window."""
        d = self.window_hist(name, window_s)
        return 0 if d is None else d[3]

    def window_mean(self, name: str, window_s: float) -> Optional[float]:
        d = self.window_hist(name, window_s)
        if d is None or d[3] == 0:
            return None
        return d[2] / d[3]

    def window_fraction_over(self, name: str, threshold: float,
                             window_s: float) -> Optional[float]:
        """Fraction of the window's observations ABOVE ``threshold`` —
        the raw material of latency burn rates (an SLO "p95 ≤ T" means
        at most 5% of requests may exceed T). Bucket-grid resolution via
        the shared :meth:`Histogram.fraction_over_from` convention, so
        pick SLO thresholds on (or near) bucket bounds. None with no
        observations in the window."""
        d = self.window_hist(name, window_s)
        if d is None or d[3] == 0:
            return None
        bounds, counts, _, _ = d
        return _fraction_over_from(bounds, counts, threshold)

    @staticmethod
    def _delta_from_pair(pair, name: str) -> float:
        base, newest = pair
        now_v = newest["counters"].get(name, 0.0)
        base_v = base["counters"].get(name, 0.0)
        return max(0.0, float(now_v) - float(base_v))

    def window_delta(self, name: str, window_s: float) -> float:
        """Counter increase over the window (clamped non-negative)."""
        pair = self._window_pair(window_s)
        if pair is None:
            return 0.0
        return self._delta_from_pair(pair, name)

    def window_deltas(self, names: Sequence[str],
                      window_s: float) -> Optional[Dict[str, float]]:
        """Several counters' increases from ONE (baseline, newest) pair —
        the atomic read a ratio needs (shed/submitted burn rates must
        not mix numerator and denominator from different windows when a
        tick lands between two separate queries). None without enough
        history."""
        pair = self._window_pair(window_s)
        if pair is None:
            return None
        return {n: self._delta_from_pair(pair, n) for n in names}

    def window_rate(self, name: str, window_s: float) -> Optional[float]:
        """Counter rate (per second) over the window — delta divided by
        the *actual* covered span (snapshot cadence jitters; dividing by
        the nominal window would bias the rate). Delta and span come
        from the SAME snapshot pair. None without history."""
        pair = self._window_pair(window_s)
        if pair is None:
            return None
        base, newest = pair
        span = newest["t"] - base["t"]
        if span <= 0:
            return None
        return self._delta_from_pair(pair, name) / span

    # ------------------------------------------------------------ summary
    def summary(self, names: Sequence[str], window_s: float,
                qs: Sequence[float] = (50, 95, 99)) -> Dict[str, dict]:
        """Windowed percentile/count/mean per histogram name — the
        ``health_report()`` building block."""
        out: Dict[str, dict] = {}
        for name in names:
            d = self.window_hist(name, window_s)
            if d is None:
                out[name] = {"count": 0}
                continue
            bounds, counts, total_sum, total = d
            entry = {"count": total,
                     "mean": (total_sum / total) if total else 0.0}
            for q in qs:
                entry[f"p{int(q)}"] = (
                    _percentile_from(bounds, counts, q)
                    if total else 0.0)
            out[name] = entry
        return out
