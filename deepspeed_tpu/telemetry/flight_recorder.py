"""Flight recorder: a crash-dump surface over the tracer's span ring.

Serving incidents are diagnosed after the fact — "why did TTFT spike at
14:03?", "what was in flight when replica 2 died?" — so the recorder
keeps the *recent past* resident (the tracer's bounded span ring plus a
small ring of metric-registry snapshots) and writes it out on demand
(:meth:`ServingFrontend.debug_dump`), and automatically on unhandled
scheduler/replica errors. Two formats per dump: the raw JSON record
(machine-greppable) and Chrome ``trace_event`` JSON loadable in
``chrome://tracing`` / Perfetto (docs/OBSERVABILITY.md walks through
opening one).

Error dumps are rate-limited (a dying fleet must not fill the disk) and
the dump path itself is exception-proof — telemetry must never turn a
degraded service into a dead one.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..utils.locks import RankedLock
from ..utils.logging import logger
from .tracer import Tracer, chrome_trace


class FlightRecorder:
    # lock discipline (docs/CONCURRENCY.md): snapshot ring, dump
    # sequence and the rate-limiter window are shared between the
    # router tick, replica death paths and on-demand dumps.
    # ``_providers`` is append-at-wiring-time, read-only afterwards.
    _GUARDED_BY = {
        "_snapshots": "_lock",
        "_last_snapshot_t": "_lock",
        "_dump_seq": "_lock",
        "_error_dump_times": "_lock",
    }

    def __init__(self, tracer: Tracer, max_snapshots: int = 32,
                 dump_dir: Optional[str] = None, max_error_dumps: int = 3,
                 error_dump_window_s: float = 3600.0,
                 role: str = "frontend"):
        self.tracer = tracer
        self.dump_dir = dump_dir
        # dump filenames are stamped <seq>_<reason>_<role>_<pid>: a
        # subprocess replica fleet shares one dump dir (the kv_tier
        # kvtier_<pid> precedent), and per-process seq counters alone
        # would collide
        self.role = str(role)
        # error dumps are limited to max_error_dumps per sliding window
        # (NOT per lifetime — a long-running service must still capture
        # next week's incident after this week's burned a few slots)
        self.max_error_dumps = int(max_error_dumps)
        self.error_dump_window_s = float(error_dump_window_s)
        self._providers: List[tuple] = []       # (name, fn() -> dict)
        self._snapshots: "deque[Dict[str, Any]]" = deque(maxlen=max_snapshots)
        self._lock = RankedLock("telemetry.recorder")
        self._last_snapshot_t = 0.0
        self._dump_seq = 0
        self._error_dump_times: "deque[float]" = deque()
        if dump_dir:
            self._sweep_stale_dumps(dump_dir)

    @staticmethod
    def _sweep_stale_dumps(dump_dir: str) -> int:
        """Delete dump files whose owning pid (the trailing filename
        token) is dead — a bench/test fleet's previous run must not leave
        its obituaries to be mistaken for this run's. Files of LIVE
        processes (including this one) and unparseable names are never
        touched; any OS error ends the sweep silently (telemetry must
        never kill its host over housekeeping)."""
        swept = 0
        try:
            names = os.listdir(dump_dir)
        except OSError:
            return 0
        for name in names:
            if not (name.startswith("flightrec_")
                    or name.startswith("trace_")) \
                    or not name.endswith(".json"):
                continue
            stem = name[:-len(".json")]
            pid_s = stem.rsplit("_", 1)[-1]
            if not pid_s.isdigit() or int(pid_s) == os.getpid():
                continue
            try:
                os.kill(int(pid_s), 0)
            except ProcessLookupError:
                try:
                    os.remove(os.path.join(dump_dir, name))
                    swept += 1
                except OSError:
                    return swept
            except OSError:
                pass                        # alive or not ours: keep
        return swept

    def add_metrics_provider(self, name: str,
                             fn: Callable[[], dict]) -> None:
        """Register a snapshot source (e.g. ``MetricsRegistry.snapshot``);
        called at snapshot time, guarded — a raising provider is skipped."""
        self._providers.append((name, fn))

    # ------------------------------------------------------------ snapshots
    def snapshot_metrics(self) -> None:
        snap: Dict[str, Any] = {"t": self.tracer.clock(),
                                "wall_time": time.time()}
        for name, fn in self._providers:
            try:
                snap[name] = fn()
            except Exception as e:
                snap[name] = {"error": repr(e)}
        with self._lock:
            self._snapshots.append(snap)
            self._last_snapshot_t = snap["t"]

    def maybe_snapshot(self, interval_s: float = 1.0) -> None:
        """Periodic-snapshot hook for polling loops (the serving router
        calls this each iteration); cheap no-op when disabled or within
        the interval. The cadence check CLAIMS the watermark in the
        same locked section it reads it (concurrency lint,
        guarded-field): the router tick and the supervisor's
        restart-dump path race here, and a check-then-snapshot that
        isn't atomic lets both pass the interval test and snapshot back
        to back."""
        if not self.tracer.enabled:
            return
        now = self.tracer.clock()
        with self._lock:
            if now - self._last_snapshot_t < interval_s:
                return
            self._last_snapshot_t = now       # claim: the loser skips
        self.snapshot_metrics()

    # ---------------------------------------------------------------- dumps
    def record(self) -> Dict[str, Any]:
        """The in-memory flight record: recent spans (open ones included)
        + metric snapshots + provenance."""
        with self._lock:
            snapshots = list(self._snapshots)
        return {
            "format": "deepspeed_tpu.flight_recorder.v1",
            "wall_time": time.time(),
            "monotonic_time": self.tracer.clock(),
            "telemetry_enabled": self.tracer.enabled,
            "spans": self.tracer.export(include_open=True),
            "metric_snapshots": snapshots,
        }

    def _resolve_dir(self, dump_dir: Optional[str]) -> str:
        d = dump_dir or self.dump_dir or os.path.join(
            tempfile.gettempdir(), "deepspeed_tpu_telemetry")
        os.makedirs(d, exist_ok=True)
        return d

    def dump(self, dump_dir: Optional[str] = None,
             reason: str = "on_demand") -> Dict[str, str]:
        """Write the flight record as ``flightrec_*.json`` (raw) and
        ``trace_*.json`` (Chrome trace). Returns the two paths."""
        d = self._resolve_dir(dump_dir)
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
        tag = f"{seq:03d}_{reason}_{self.role}_{os.getpid()}"
        record = self.record()
        record["reason"] = reason
        raw_path = os.path.join(d, f"flightrec_{tag}.json")
        with open(raw_path, "w") as fh:
            json.dump(record, fh, indent=1, default=str)
        trace_path = os.path.join(d, f"trace_{tag}.json")
        with open(trace_path, "w") as fh:
            json.dump(chrome_trace(record["spans"],
                                   meta={"reason": reason,
                                         "wall_time": record["wall_time"]}),
                      fh, default=str)
        return {"json": raw_path, "chrome_trace": trace_path}

    def _acquire_dump_slot(self) -> bool:
        """One shared sliding-window budget for every automatic dump
        trigger (errors AND alert firings — an alert storm must not fill
        the disk any more than a crash loop may): True when a dump may
        proceed, False when the window's ``max_error_dumps`` are spent."""
        now = self.tracer.clock()
        with self._lock:
            while self._error_dump_times and \
                    now - self._error_dump_times[0] > self.error_dump_window_s:
                self._error_dump_times.popleft()
            if len(self._error_dump_times) >= self.max_error_dumps:
                return False
            self._error_dump_times.append(now)
        return True

    def _auto_dump(self, reason: str, what: str) -> Optional[Dict[str, str]]:
        """Shared body of every automatic dump trigger: telemetry gate,
        sliding rate-limit slot, snapshot + dump, never raises. ``what``
        is the human log phrasing; ``reason`` lands in the filenames."""
        if not self.tracer.enabled:
            return None
        if not self._acquire_dump_slot():
            return None
        try:
            self.snapshot_metrics()
            paths = self.dump(reason=reason)
            logger.warning(f"telemetry: flight-recorder dump for {what} "
                           f"-> {paths['json']}")
            return paths
        except Exception as dump_exc:  # pragma: no cover - defensive
            logger.warning(f"telemetry: flight-recorder dump failed: "
                           f"{dump_exc!r}")
            return None

    def on_error(self, where: str, exc: BaseException) -> Optional[Dict[str, str]]:
        """Crash hook for replica/scheduler error paths: best-effort dump,
        rate-limited to ``max_error_dumps`` per ``error_dump_window_s``
        (a dying fleet must not fill the disk, but a long-lived service
        keeps capturing later incidents), never raises (the caller is
        already handling a fault)."""
        return self._auto_dump(
            f"error_{where}",
            f"error in {where} ({type(exc).__name__}: {exc})")

    def on_event(self, reason: str) -> Optional[Dict[str, str]]:
        """Automatic dump for a non-error incident (a burn-rate alert
        firing — telemetry/slo.py): same telemetry gate, same sliding
        rate limiter as error dumps, never raises."""
        return self._auto_dump(reason, reason)
