"""Per-class SLO tracking and multi-window burn-rate alerting.

PR 8 gave the serving stack per-class SLO *policies* (priorities,
deadlines, class-ordered brownout); this module makes SLO *attainment*
measurable and pageable (docs/OBSERVABILITY.md "SLOs and burn-rate
alerts"). The model is the SRE-workbook one:

- An **SLO target** per request class: ``ttft_p95_ms`` / ``tpot_p95_ms``
  (latency: at most 5% of observations may exceed the threshold — the
  p95 contract stated as an error budget of 0.05) and ``availability``
  (at most ``1 - availability`` of submitted requests may be shed).
- **Burn rate** over a window = (bad fraction in the window) / (error
  budget). Burn 1.0 spends the budget exactly at the sustainable pace;
  burn 20 exhausts a 30-day budget in ~1.5 days.
- **Multi-window rules**: an alert fires only when BOTH a fast window
  and a slow window burn above the threshold — the fast window gives
  low detection latency, the slow window keeps a single straggler
  request from paging anyone; the rule resolves as soon as the fast
  window clears (recovery detection rides the short window).

The engine is evaluated on the serving router's ~1/s tick against the
:class:`~.windowed.WindowedMetrics` ring — cumulative metrics are
untouched; the window deltas ARE the measurement. Each rule runs a
firing→resolved state machine: transitions land in the ops journal
(telemetry/journal.py), flip the ``alerts_firing`` /
``alert_firing_<rule>`` gauges, and a NEW firing triggers a
flight-recorder dump through the same rate limiter as error dumps (an
alert storm must not fill the disk any more than a crash loop may).

Everything here is passive and default-off: with no ``slo:`` block the
engine is never constructed and the serving stack is byte-for-byte the
pre-SLO build.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from pydantic import Field

from ..runtime.config_utils import DSConfigModel
from ..utils.locks import RankedLock
from ..utils.logging import logger

#: error budget implied by a pXX latency target: p95 ⇒ 5% may exceed
LATENCY_BUDGET = 0.05


class SLOClassTarget(DSConfigModel):
    """One request class's SLO targets (``slo.classes.<cls>``). Unset
    targets generate no rules — declare only what you can stand behind."""

    # windowed p95 TTFT/TPOT must stay at or under these (milliseconds);
    # pick values on (or near) the registry's histogram bucket bounds —
    # windowed fractions resolve at bucket granularity
    ttft_p95_ms: Optional[float] = None
    tpot_p95_ms: Optional[float] = None
    # fraction of submitted requests that must NOT be shed
    # (0.999 = an error budget of 0.1%)
    availability: Optional[float] = None


class SLOConfig(DSConfigModel):
    """``slo: {...}`` block on :class:`ServingConfig`
    (docs/CONFIG.md, docs/OBSERVABILITY.md "SLOs and burn-rate
    alerts"). ``enabled: false`` (the default) builds no alert engine —
    byte-for-byte historical behavior; windowed metrics and the ops
    journal exist regardless (they are passive)."""

    enabled: bool = False
    # class name -> targets; classes with no entry are unmonitored
    classes: Dict[str, SLOClassTarget] = Field(default_factory=dict)
    # tenant name -> targets (docs/SERVING.md "Multi-model &
    # multi-tenant serving"): same shape, evaluated over the per-tenant
    # series (``ttft_s_tenant_<t>``, shed/submitted tenant counters) —
    # a tenant's burn is measured against ITS traffic only, so one
    # tenant's flood spending another's error budget is impossible by
    # construction. Tenants with no entry are unmonitored.
    tenants: Dict[str, SLOClassTarget] = Field(default_factory=dict)
    # burn-rate windows: fire on fast AND slow breach, resolve when the
    # fast window clears. Production-shaped defaults; the CPU bench and
    # the chaos suite shrink them to seconds.
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    # burn-rate threshold in error-budget multiples (1.0 = spending the
    # budget exactly at the sustainable pace)
    burn_rate_threshold: float = 4.0
    # a window with fewer observations than this cannot breach — one
    # slow request in an idle fleet is not an incident
    min_window_count: int = 3
    # evaluation cadence on the router tick, and the windowed-metrics
    # ring geometry (snapshot interval x history depth)
    eval_interval_s: float = 1.0
    window_bucket_s: float = 1.0
    window_history_s: float = 900.0
    # ops journal geometry (the journal itself is always on — it is a
    # bounded in-memory ring; the optional path streams JSONL, byte-capped)
    journal_capacity: int = 512
    journal_path: Optional[str] = None
    # write a flight-recorder dump on each NEW firing (telemetry-gated
    # and rate-limited like error dumps)
    dump_on_alert: bool = True


@dataclasses.dataclass
class AlertRule:
    """One derived burn-rate rule: (class-or-tenant, kind) -> thresholds."""

    name: str                   # e.g. "slo_ttft_interactive"
    request_class: str          # class name (or tenant name, scope="tenant")
    kind: str                   # "ttft" | "tpot" | "availability"
    metric: str                 # histogram or counter name observed
    threshold_s: Optional[float]  # latency rules: the target in seconds
    budget: float               # error budget (0.05 for p95 latency)
    # availability rules: the submitted-counter the shed count is a
    # fraction OF — per-class and per-tenant rules differ only here
    denominator: Optional[str] = None
    scope: str = "class"        # "class" | "tenant"


@dataclasses.dataclass
class AlertState:
    rule: AlertRule
    firing: bool = False
    fired_t: Optional[float] = None
    resolved_t: Optional[float] = None
    fire_count: int = 0
    burn_fast: float = 0.0
    burn_slow: float = 0.0


class AlertEngine:
    # lock discipline (docs/CONCURRENCY.md): rule states are read by
    # health_report/fleet_signals threads while evaluate mutates.
    # ``_last_eval`` stays unguarded: single evaluator by construction
    # (the router tick), and a stale read only double-evaluates.
    _GUARDED_BY = {"_states": "_lock"}

    def __init__(self, config: SLOConfig, windowed, metrics=None,
                 journal=None, recorder=None, clock=time.monotonic):
        self.config = config
        self.windowed = windowed
        self.metrics = metrics
        self.journal = journal
        self.recorder = recorder
        self.clock = clock
        self._lock = RankedLock("telemetry.slo")
        self._last_eval = 0.0
        self.rules: List[AlertRule] = []
        for cls, target in sorted(config.classes.items()):
            if target.ttft_p95_ms is not None:
                self.rules.append(AlertRule(
                    f"slo_ttft_{cls}", cls, "ttft", f"ttft_s_class_{cls}",
                    target.ttft_p95_ms / 1e3, LATENCY_BUDGET))
            if target.tpot_p95_ms is not None:
                self.rules.append(AlertRule(
                    f"slo_tpot_{cls}", cls, "tpot", f"tpot_s_class_{cls}",
                    target.tpot_p95_ms / 1e3, LATENCY_BUDGET))
            if target.availability is not None:
                self.rules.append(AlertRule(
                    f"slo_availability_{cls}", cls, "availability",
                    f"requests_shed_class_{cls}", None,
                    max(1e-9, 1.0 - target.availability),
                    denominator=f"requests_submitted_class_{cls}"))
        # per-tenant rules (docs/SERVING.md "Multi-model & multi-tenant
        # serving"): same machinery over the per-tenant series, with the
        # tenant's own submitted counter as the availability denominator
        for tenant, target in sorted(config.tenants.items()):
            if target.ttft_p95_ms is not None:
                self.rules.append(AlertRule(
                    f"slo_ttft_tenant_{tenant}", tenant, "ttft",
                    f"ttft_s_tenant_{tenant}",
                    target.ttft_p95_ms / 1e3, LATENCY_BUDGET,
                    scope="tenant"))
            if target.tpot_p95_ms is not None:
                self.rules.append(AlertRule(
                    f"slo_tpot_tenant_{tenant}", tenant, "tpot",
                    f"tpot_s_tenant_{tenant}",
                    target.tpot_p95_ms / 1e3, LATENCY_BUDGET,
                    scope="tenant"))
            if target.availability is not None:
                self.rules.append(AlertRule(
                    f"slo_availability_tenant_{tenant}", tenant,
                    "availability", f"requests_shed_tenant_{tenant}", None,
                    max(1e-9, 1.0 - target.availability),
                    denominator=f"requests_submitted_tenant_{tenant}",
                    scope="tenant"))
        self._states: Dict[str, AlertState] = {
            r.name: AlertState(r) for r in self.rules}
        # pre-declare per-rule gauges so the zero-valued series exist
        # before any alert ever fires (satellite rule: an absent series
        # is indistinguishable from a broken exporter)
        if self.metrics is not None:
            self.metrics.gauge("alerts_firing").set(0.0)
            for r in self.rules:
                self.metrics.gauge(f"alert_firing_{r.name}").set(0.0)

    # ------------------------------------------------------------- queries
    def firing(self) -> List[str]:
        with self._lock:
            return [n for n, s in self._states.items() if s.firing]

    def status(self) -> Dict[str, dict]:
        """Per-rule view for ``health_report()``: state, last burn rates,
        cumulative error-budget spend since boot."""
        out: Dict[str, dict] = {}
        with self._lock:
            states = {n: dataclasses.replace(s) for n, s in
                      self._states.items()}
        for name, s in states.items():
            out[name] = {
                "class": s.rule.request_class,
                "scope": s.rule.scope,
                "kind": s.rule.kind,
                "firing": s.firing,
                "fire_count": s.fire_count,
                "burn_fast": round(s.burn_fast, 3),
                "burn_slow": round(s.burn_slow, 3),
                "budget_spent_frac": round(
                    self._cumulative_bad_frac(s.rule) / s.rule.budget, 3),
            }
            if s.rule.threshold_s is not None:
                out[name]["target_ms"] = s.rule.threshold_s * 1e3
        return out

    # ---------------------------------------------------------- burn rates
    def _burn(self, rule: AlertRule,
              window_s: float) -> Optional[float]:
        """Burn rate over the window: bad fraction / budget. None when
        the window holds fewer than ``min_window_count`` observations —
        *no evidence*, which is different from burn 0: an empty window
        neither fires an alert (one straggler in an idle fleet is not an
        incident) nor resolves one (absence of traffic is not evidence
        of recovery — that asymmetry is what keeps a firing alert from
        flapping when the incident itself makes traffic sparse). Count
        and fraction derive from ONE atomic window read (a tick landing
        between two separate queries must not mix numerator and
        denominator from different windows)."""
        min_count = max(1, self.config.min_window_count)
        if rule.kind in ("ttft", "tpot"):
            d = self.windowed.window_hist(rule.metric, window_s)
            if d is None or d[3] < min_count:
                return None
            bounds, counts, _, _ = d
            from ..serving.metrics import Histogram

            frac = Histogram.fraction_over_from(bounds, counts,
                                                rule.threshold_s)
            return frac / rule.budget
        # availability: shed / submitted, both from one snapshot pair;
        # the denominator is scope-specific (per-class or per-tenant)
        submitted_name = (rule.denominator
                          or f"requests_submitted_class_{rule.request_class}")
        deltas = self.windowed.window_deltas((submitted_name, rule.metric),
                                             window_s)
        if deltas is None or deltas[submitted_name] < min_count:
            return None
        frac = min(1.0, deltas[rule.metric] / deltas[submitted_name])
        return frac / rule.budget

    def _cumulative_bad_frac(self, rule: AlertRule) -> float:
        """Since-boot bad fraction from the CUMULATIVE registry — the
        error-budget ledger (how much of the budget this process already
        spent), independent of window history. Same bucket-boundary
        convention as the windowed burn rates
        (:meth:`Histogram.fraction_over_from`)."""
        if self.metrics is None:
            return 0.0
        if rule.kind in ("ttft", "tpot"):
            from ..serving.metrics import Histogram

            bounds, counts, _, total = \
                self.metrics.histogram(rule.metric).buckets_snapshot()
            if total == 0:
                return 0.0
            return Histogram.fraction_over_from(bounds, counts,
                                                rule.threshold_s)
        submitted = self.metrics.counter(
            rule.denominator
            or f"requests_submitted_class_{rule.request_class}").value
        if submitted <= 0:
            return 0.0
        return min(1.0, self.metrics.counter(rule.metric).value / submitted)

    # ----------------------------------------------------------- evaluation
    def maybe_evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Cadence-gated :meth:`evaluate` for the router tick."""
        now = now if now is not None else self.clock()
        if now - self._last_eval < self.config.eval_interval_s:
            return []
        return self.evaluate(now)

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Run every rule's state machine once; returns the transitions
        (``{"alert", "transition": "firing"|"resolved", ...}``)."""
        now = now if now is not None else self.clock()
        self._last_eval = now
        thr = self.config.burn_rate_threshold
        transitions: List[dict] = []
        for rule in self.rules:
            fast = self._burn(rule, self.config.fast_window_s)
            slow = self._burn(rule, self.config.slow_window_s)
            with self._lock:
                s = self._states[rule.name]
                s.burn_fast = fast if fast is not None else 0.0
                s.burn_slow = slow if slow is not None else 0.0
                if not s.firing:
                    # firing needs positive evidence in BOTH windows
                    if (fast is not None and slow is not None
                            and fast > thr and slow > thr):
                        s.firing = True
                        s.fired_t = now
                        s.fire_count += 1
                        transitions.append({"alert": rule.name,
                                            "transition": "firing",
                                            "burn_fast": fast,
                                            "burn_slow": slow})
                elif fast is not None and fast <= thr:
                    # resolution ALSO needs evidence: a populated fast
                    # window burning at/below threshold (recovery
                    # detection rides the short window; a data-less
                    # window keeps the alert up rather than flapping it)
                    s.firing = False
                    s.resolved_t = now
                    transitions.append({
                        "alert": rule.name, "transition": "resolved",
                        "firing_s": (now - s.fired_t
                                     if s.fired_t is not None else 0.0)})
        if self.metrics is not None and self.rules:
            self.metrics.gauge("alerts_firing").set(len(self.firing()))
        for tr in transitions:
            self._on_transition(tr)
        return transitions

    def _on_transition(self, tr: dict) -> None:
        rule = next(r for r in self.rules if r.name == tr["alert"])
        if tr["transition"] == "firing":
            logger.warning(
                f"SLO alert FIRING: {rule.name} (class "
                f"{rule.request_class}, {rule.kind}) burn "
                f"fast={tr['burn_fast']:.1f} slow={tr['burn_slow']:.1f} "
                f"(threshold {self.config.burn_rate_threshold})")
            if self.metrics is not None:
                self.metrics.gauge(f"alert_firing_{rule.name}").set(1.0)
            if self.journal is not None:
                self.journal.emit("alert_firing", alert=rule.name,
                                  request_class=rule.request_class,
                                  slo_kind=rule.kind,
                                  burn_fast=round(tr["burn_fast"], 3),
                                  burn_slow=round(tr["burn_slow"], 3))
            if self.recorder is not None and self.config.dump_on_alert:
                # same limiter as error dumps: an alert storm must not
                # fill the disk; telemetry-off recorders no-op inside
                self.recorder.on_event(f"alert_{rule.name}")
        else:
            logger.warning(f"SLO alert resolved: {rule.name} after "
                           f"{tr['firing_s']:.1f}s")
            if self.metrics is not None:
                self.metrics.gauge(f"alert_firing_{rule.name}").set(0.0)
            if self.journal is not None:
                self.journal.emit("alert_resolved", alert=rule.name,
                                  firing_s=round(tr["firing_s"], 3))
