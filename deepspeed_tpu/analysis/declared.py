"""Static declared-name audits: metric names and ops-journal kinds.

The runtime invariant (tested dynamically since PR 9 by the
TestMetricNameAudit idiom) is promoted to an AST pass over the whole
package, so it holds for code paths no test exercises:

- every ``counter(...)``/``gauge(...)``/``histogram(...)`` name used
  anywhere in ``deepspeed_tpu/`` must be pre-declared by
  ``serving_metrics()`` (serving/metrics.py) or the
  :class:`AlertEngine` pre-declaration block (telemetry/slo.py) —
  including f-string names, matched against the declared templates
  (``ttft_s_class_{cls}`` etc.);
- every ``journal.emit(kind, ...)`` kind must exist in
  ``EVENT_SCHEMAS`` (telemetry/journal.py).

Name arguments that are variables are resolved one level: enclosing
``for``-loop bindings over literal iterables (including class-attribute
tables like ``_PREFIX_COUNTERS``, position-aware for tuple targets),
local assignments (all string constants in the bound expression), and —
for journal kinds — literal arguments at same-class call sites when the
kind is a function parameter. A name the resolver cannot pin down is
itself a finding (baseline it with a justification, or make it
resolvable)."""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .concurrency import Finding, _const_str

_METRIC_METHODS = ("counter", "gauge", "histogram")

#: (module, function-qualname) scopes whose metric calls DECLARE names
_DECLARING = (
    ("deepspeed_tpu/serving/metrics.py", None),        # whole module
    ("deepspeed_tpu/telemetry/slo.py", "AlertEngine.__init__"),
)

Template = Tuple[str, ...]     # static segments; gaps are placeholders


def _template_of(node: ast.JoinedStr) -> Template:
    segs: List[str] = [""]
    for part in node.values:
        if isinstance(part, ast.Constant):
            segs[-1] += str(part.value)
        else:
            segs.append("")
    return tuple(segs)


def _template_matches_const(tpl: Template, name: str) -> bool:
    if len(tpl) == 1:
        return tpl[0] == name
    if not name.startswith(tpl[0]) or not name.endswith(tpl[-1]):
        return False
    pos = len(tpl[0])
    for seg in tpl[1:-1]:
        i = name.find(seg, pos + 1)     # +1: placeholders are non-empty
        if i < 0:
            return False
        pos = i + len(seg)
    return len(name) - len(tpl[-1]) >= pos + 1


class _ForEnv:
    """Loop/assignment bindings visible to a name argument, resolved
    against literal iterables (position-aware for tuple targets)."""

    def __init__(self, fn: ast.AST, class_attrs: Dict[str, ast.AST]):
        self.bindings: Dict[str, List[str]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.For):
                self._bind_for(node, class_attrs)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                consts = _str_consts(node.value)
                if consts:
                    self.bindings.setdefault(
                        node.targets[0].id, []).extend(consts)

    def _bind_for(self, node: ast.For,
                  class_attrs: Dict[str, ast.AST]) -> None:
        it = node.iter
        if isinstance(it, ast.Attribute) and it.attr in class_attrs:
            it = class_attrs[it.attr]
        if not isinstance(it, (ast.Tuple, ast.List)):
            return
        if isinstance(node.target, ast.Name):
            vals = [s for e in it.elts for s in _str_consts(e)]
            if vals:
                self.bindings.setdefault(node.target.id, []).extend(vals)
        elif isinstance(node.target, ast.Tuple):
            names = [t.id if isinstance(t, ast.Name) else None
                     for t in node.target.elts]
            for idx, nm in enumerate(names):
                if nm is None:
                    continue
                vals = []
                for e in it.elts:
                    if isinstance(e, (ast.Tuple, ast.List)) \
                            and idx < len(e.elts):
                        s = _const_str(e.elts[idx])
                        if s is not None:
                            vals.append(s)
                if vals:
                    self.bindings.setdefault(nm, []).extend(vals)


def _str_consts(expr: ast.AST) -> List[str]:
    return [n.value for n in ast.walk(expr)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


# ----------------------------------------------------------- declarations

def _walk_qualnames(tree: ast.Module):
    """Yield (qualname, node) for every node, qualname = Class.method
    for nodes inside methods, else None-ish paths."""
    for cls in tree.body:
        if isinstance(cls, ast.ClassDef):
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield f"{cls.name}.{fn.name}", fn
        elif isinstance(cls, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield cls.name, cls


def declared_metrics(root: str) -> Tuple[Set[str], Set[Template]]:
    names: Set[str] = set()
    templates: Set[Template] = set()
    for rel, qual in _DECLARING:
        with open(os.path.join(root, rel)) as fh:
            tree = ast.parse(fh.read())
        scopes = []
        if qual is None:
            scopes = [tree]
        else:
            scopes = [fn for q, fn in _walk_qualnames(tree) if q == qual]
        for scope in scopes:
            env = _ForEnv(scope, {})
            for node in ast.walk(scope):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _METRIC_METHODS
                        and node.args):
                    continue
                arg = node.args[0]
                s = _const_str(arg)
                if s is not None:
                    names.add(s)
                elif isinstance(arg, ast.JoinedStr):
                    templates.add(_template_of(arg))
                elif isinstance(arg, ast.Name):
                    names.update(env.bindings.get(arg.id, ()))
    return names, templates


def declared_journal_kinds(root: str) -> Set[str]:
    path = os.path.join(root, "deepspeed_tpu", "telemetry", "journal.py")
    with open(path) as fh:
        tree = ast.parse(fh.read())
    for node in tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign):
            target, value = node.target, node.value
        else:
            continue
        if isinstance(target, ast.Name) and target.id == "EVENT_SCHEMAS" \
                and isinstance(value, ast.Dict):
            return {k for k in (_const_str(kn) for kn in value.keys)
                    if k is not None}
    raise ValueError(f"no EVENT_SCHEMAS dict literal in {path}")


# ----------------------------------------------------------------- usages

def _class_attr_literals(cls: ast.ClassDef) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            out[stmt.targets[0].id] = stmt.value
    return out


def _param_index(fn: ast.FunctionDef, name: str) -> Optional[int]:
    for i, a in enumerate(fn.args.args):
        if a.arg == name:
            return i
    return None


def _resolve_arg(arg: ast.AST, env: _ForEnv,
                 fn: ast.AST, cls: Optional[ast.ClassDef]
                 ) -> Tuple[List[str], List[Template], bool]:
    """(constant names, templates, resolved?) for a name argument."""
    s = _const_str(arg)
    if s is not None:
        return [s], [], True
    if isinstance(arg, ast.JoinedStr):
        return [], [_template_of(arg)], True
    if isinstance(arg, ast.IfExp):
        consts = _str_consts(arg)
        if consts:
            return consts, [], True
    if isinstance(arg, ast.Name):
        bound = env.bindings.get(arg.id)
        if bound:
            return list(bound), [], True
        # parameter: collect literal arguments at same-class call sites
        if cls is not None and isinstance(fn, (ast.FunctionDef,
                                               ast.AsyncFunctionDef)):
            idx = _param_index(fn, arg.id)
            if idx is not None:
                vals: List[str] = []
                for node in ast.walk(cls):
                    if not (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr == fn.name):
                        continue
                    # positional (receiver absorbs `self`) or keyword
                    pos = idx - 1
                    if 0 <= pos < len(node.args):
                        v = _const_str(node.args[pos])
                        if v is not None:
                            vals.append(v)
                    for kw in node.keywords:
                        if kw.arg == arg.id:
                            v = _const_str(kw.value)
                            if v is not None:
                                vals.append(v)
                if vals:
                    return vals, [], True
    return [], [], False


def _iter_package_files(root: str) -> List[str]:
    out = []
    pkg = os.path.join(root, "deepspeed_tpu")
    for dirpath, _, names in os.walk(pkg):
        for n in sorted(names):
            if n.endswith(".py"):
                out.append(os.path.relpath(os.path.join(dirpath, n), root))
    return sorted(out)


def check_declared_names(root: str) -> List[Finding]:
    """The two audits, package-wide; returns findings."""
    metric_names, metric_templates = declared_metrics(root)
    kinds = declared_journal_kinds(root)
    findings: List[Finding] = []

    def metric_ok(name: str) -> bool:
        return name in metric_names or any(
            _template_matches_const(t, name) for t in metric_templates)

    for rel in _iter_package_files(root):
        with open(os.path.join(root, rel)) as fh:
            try:
                tree = ast.parse(fh.read())
            except SyntaxError:      # pragma: no cover - defensive
                continue
        # every Call in the file, tagged with its NEAREST enclosing
        # class/function — module-level wiring and classes nested inside
        # functions are covered, not just top-level method bodies
        scoped_calls: List[tuple] = []

        def _collect(node, cls, fn):
            for child in ast.iter_child_nodes(node):
                ncls, nfn = cls, fn
                if isinstance(child, ast.ClassDef):
                    ncls, nfn = child, None
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    nfn = child
                if isinstance(child, ast.Call) \
                        and isinstance(child.func, ast.Attribute):
                    scoped_calls.append((cls, fn, child))
                _collect(child, ncls, nfn)

        _collect(tree, None, None)
        envs: Dict[int, _ForEnv] = {}
        for cls, fn, node in scoped_calls:
            qual = (f"{cls.name}.{fn.name}" if cls is not None
                    and fn is not None
                    else fn.name if fn is not None
                    else cls.name if cls is not None else "<module>")
            if any(rel == drel and (dq is None or dq == qual)
                   for drel, dq in _DECLARING):
                continue
            scope = fn if fn is not None else tree
            env = envs.get(id(scope))
            if env is None:
                attrs = (_class_attr_literals(cls)
                         if cls is not None else {})
                env = envs[id(scope)] = _ForEnv(scope, attrs)
            meth = node.func.attr
            if meth in _METRIC_METHODS and node.args:
                consts, tpls, ok = _resolve_arg(
                    node.args[0], env, fn, cls)
                if not ok:
                    findings.append(Finding(
                        "metric-name", rel, node.lineno, qual,
                        f"unresolved:{meth}",
                        f"{meth}(...) name argument is not "
                        "statically resolvable"))
                    continue
                for name in consts:
                    if not metric_ok(name):
                        findings.append(Finding(
                            "metric-name", rel, node.lineno, qual,
                            name,
                            f"{meth}({name!r}) is not pre-declared "
                            "by serving_metrics()"))
                for tpl in tpls:
                    # a usage template is declared when it IS a
                    # declared template, or when at least one
                    # declared constant instantiates it (the
                    # per-role gauges are declared as the three
                    # concrete names, used via one f-string)
                    if tpl not in metric_templates and not any(
                            _template_matches_const(tpl, n)
                            for n in metric_names):
                        findings.append(Finding(
                            "metric-name", rel, node.lineno, qual,
                            "*".join(tpl),
                            f"{meth}(f\"{'{…}'.join(tpl)}\") "
                            "matches no declared template"))
            elif meth == "emit" and node.args:
                recv_src = ""
                try:
                    recv_src = ast.unparse(node.func.value)
                except Exception:    # pragma: no cover
                    pass
                if "journal" not in recv_src:
                    continue
                consts, _, ok = _resolve_arg(
                    node.args[0], env, fn, cls)
                if not ok:
                    findings.append(Finding(
                        "journal-kind", rel, node.lineno, qual,
                        "unresolved:emit",
                        "journal.emit(...) kind is not "
                        "statically resolvable"))
                    continue
                for kind in consts:
                    if kind not in kinds:
                        findings.append(Finding(
                            "journal-kind", rel, node.lineno, qual,
                            kind,
                            f"emit({kind!r}) is not a kind in "
                            "EVENT_SCHEMAS"))
    return findings
