"""Static analysis for the threaded serving/telemetry stack.

``run_repo()`` is the one-call entry point the CLI
(``scripts/lint_concurrency.py``), tier-1 and the tests share: the three
concurrency checks (guarded fields, lock order, blocking-while-locked —
:mod:`.concurrency`), the declared-name audits (metric names, journal
kinds — :mod:`.declared`), and the audited-exception baseline with
stale-entry detection (:mod:`.baseline`). See docs/CONCURRENCY.md."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from .baseline import (DEFAULT_BASELINE, BaselineEntry, apply_baseline,
                       load_baseline, parse_baseline, render_baseline)
from .concurrency import (DEFAULT_PATHS, Finding, analyze, analyze_source,
                          build_model, parse_lock_ranks)
from .declared import (check_declared_names, declared_journal_kinds,
                       declared_metrics)

__all__ = [
    "DEFAULT_BASELINE", "DEFAULT_PATHS", "BaselineEntry", "Finding",
    "analyze", "analyze_source", "apply_baseline", "build_model",
    "check_declared_names", "declared_journal_kinds", "declared_metrics",
    "load_baseline", "parse_baseline", "parse_lock_ranks",
    "render_baseline", "run_repo",
]


def run_repo(root: str, paths: Optional[Sequence[str]] = None,
             baseline_path: str = DEFAULT_BASELINE,
             use_baseline: bool = True
             ) -> Tuple[List[Finding], List[Finding]]:
    """(active findings, suppressed findings) for the whole repo —
    concurrency checks over the threaded modules plus the package-wide
    declared-name audits, filtered through the baseline. Stale-entry
    detection only runs on full-scope (default-paths) invocations — a
    path-scoped run cannot tell "healed" from "out of scope"."""
    findings = analyze(root, tuple(paths) if paths else DEFAULT_PATHS)
    findings += check_declared_names(root)
    if not use_baseline:
        return findings, []
    entries, problems = load_baseline(root, baseline_path)
    active, suppressed = apply_baseline(findings, entries, baseline_path,
                                        report_stale=paths is None)
    return active + problems, suppressed
