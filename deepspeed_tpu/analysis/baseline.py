"""Audited-exception baseline for the concurrency lint.

``analysis/baseline.toml`` records findings a human audited and accepted
— each entry needs the finding's stable ``id`` (no line numbers, so it
survives unrelated edits) and a non-empty ``justification``. The
contract is anti-rot in both directions:

- a finding whose id is baselined is suppressed (but still reported as
  suppressed, for visibility);
- a baseline entry matching NO current finding is itself an error
  (``stale-baseline``) — fixed code must shed its exception;
- an entry with an empty justification is an error
  (``baseline-unjustified``) — the audit trail is the point.

The file is a TOML subset parsed here without third-party deps (the
container has no tomllib/tomli): comments, ``[[finding]]`` tables, and
``key = "string"`` pairs."""

from __future__ import annotations

import dataclasses
import os
import re
from typing import List, Sequence, Tuple

from .concurrency import Finding

DEFAULT_BASELINE = "deepspeed_tpu/analysis/baseline.toml"

_KV = re.compile(r'^(\w+)\s*=\s*"(.*)"\s*$')


@dataclasses.dataclass
class BaselineEntry:
    id: str
    justification: str
    line: int


def parse_baseline(text: str, path: str = DEFAULT_BASELINE
                   ) -> Tuple[List[BaselineEntry], List[Finding]]:
    """(entries, parse problems). Problems are findings so the CLI and
    tests treat a malformed baseline like any other lint failure."""
    entries: List[BaselineEntry] = []
    problems: List[Finding] = []
    current = None
    for i, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[finding]]":
            current = BaselineEntry("", "", i)
            entries.append(current)
            continue
        m = _KV.match(line)
        if m and current is not None:
            if m.group(1) == "id":
                current.id = m.group(2)
            elif m.group(1) == "justification":
                current.justification = m.group(2)
            continue
        problems.append(Finding(
            "baseline-parse", path, i, "<baseline>", f"line-{i}",
            f"unparseable baseline line: {raw!r}"))
    for e in entries:
        if not e.id:
            problems.append(Finding(
                "baseline-parse", path, e.line, "<baseline>",
                f"line-{e.line}", "baseline entry without an id"))
        elif not e.justification.strip():
            problems.append(Finding(
                "baseline-unjustified", path, e.line, "<baseline>", e.id,
                f"baseline entry {e.id!r} has no justification — every "
                "audited exception must say why it is safe"))
    return entries, problems


def load_baseline(root: str, path: str = DEFAULT_BASELINE
                  ) -> Tuple[List[BaselineEntry], List[Finding]]:
    full = os.path.join(root, path)
    if not os.path.exists(full):
        return [], []
    with open(full) as fh:
        return parse_baseline(fh.read(), path)


def apply_baseline(findings: Sequence[Finding],
                   entries: Sequence[BaselineEntry],
                   path: str = DEFAULT_BASELINE,
                   report_stale: bool = True
                   ) -> Tuple[List[Finding], List[Finding]]:
    """(active findings, suppressed findings). Stale entries — audited
    ids no current finding carries — are appended to the ACTIVE list:
    the baseline may only shrink when the code actually healed.
    ``report_stale=False`` is for path-SCOPED runs: an entry covering a
    file outside the analyzed paths is out of scope, not healed, and
    must not be reported for deletion."""
    ids = {e.id for e in entries if e.id}
    active = [f for f in findings if f.baseline_id not in ids]
    suppressed = [f for f in findings if f.baseline_id in ids]
    if report_stale:
        matched = {f.baseline_id for f in suppressed}
        for e in entries:
            if e.id and e.id not in matched:
                active.append(Finding(
                    "stale-baseline", path, e.line, "<baseline>", e.id,
                    f"baseline entry {e.id!r} matches no current "
                    "finding — the exception healed; delete the entry"))
    return active, suppressed


def render_baseline(findings: Sequence[Finding],
                    entries: Sequence[BaselineEntry]) -> str:
    """A fresh baseline covering ``findings``: existing justifications
    are preserved; new entries get an UNAUDITED placeholder a reviewer
    must replace (mechanically valid, visibly unreviewed)."""
    just = {e.id: e.justification for e in entries if e.justification}
    lines = [
        "# Concurrency-lint baseline — audited exceptions "
        "(docs/CONCURRENCY.md).",
        "# Every entry needs a justification; stale entries are errors.",
        "",
    ]
    for f in sorted({f.baseline_id: f for f in findings}.values(),
                    key=lambda f: f.baseline_id):
        lines.append("[[finding]]")
        lines.append(f'id = "{f.baseline_id}"')
        j = just.get(f.baseline_id,
                     f"UNAUDITED: {f.detail.splitlines()[0]}")
        lines.append(f'justification = "{j}"')
        lines.append("")
    return "\n".join(lines)
