"""AST-based concurrency lint for the threaded serving/telemetry stack.

Three checks over declared lock discipline (docs/CONCURRENCY.md):

1. **Guarded fields** — a class declares its guarded state::

       _GUARDED_BY = {"_inflight": "_lock",          # reads AND writes
                      "replicas": "_membership_lock:writes"}  # writes only

   (or per-field ``# guarded-by: _lock`` trailing comments on the
   ``__init__`` assignment). Every method's reads/writes of a guarded
   field must happen inside ``with self.<lock>``; helper-method
   indirection is resolved ONE level deep — an access in a helper is
   fine when every same-class call site of that helper holds the lock
   (the ``_foo_locked`` caller-holds-the-lock convention, verified
   instead of trusted). ``__init__`` is exempt (the object is not yet
   shared). The ``:writes`` mode covers the rebind-under-lock /
   lock-free-snapshot-read publication pattern.

2. **Lock order** — the cross-module graph of nested acquisitions:
   lexically nested ``with`` blocks plus calls made while holding a
   lock, resolved one level into the callee (same-class calls exactly;
   cross-object calls via constructor/parameter-annotation attribute
   types, falling back to unique-method-name matching). Lock identity
   is the :data:`~deepspeed_tpu.utils.locks.LOCK_RANKS` rank name when
   declared (``RankedLock("name")`` or a ``_LOCK_RANKS`` class hint for
   plain locks), else ``Class.attr``. Findings: any edge from a ranked
   lock to an equal-or-lower rank (the same inversion the runtime
   debug mode raises on), and any cycle in the whole graph.

3. **Blocking while locked** — ``join``/``Event.wait``/``time.sleep``/
   engine ``forward``/``block_until_ready``/file+disk I/O inside a
   ``with <lock>`` body (directly, or one call level deep) — the
   pattern behind past serving wedges.

Audited exceptions live in ``analysis/baseline.toml`` (see
:mod:`deepspeed_tpu.analysis.baseline`): every entry needs a
justification, and an entry matching no current finding is itself an
error — the baseline can only shrink silently, never rot.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

#: default analysis scope: the threaded modules (one entry per layer;
#: directories are walked recursively)
DEFAULT_PATHS = (
    "deepspeed_tpu/serving",
    "deepspeed_tpu/telemetry",
    "deepspeed_tpu/utils/locks.py",
    "deepspeed_tpu/utils/restart.py",
    "deepspeed_tpu/runtime/resilience.py",
)

#: method names never resolved by the unique-name fallback: they collide
#: with builtin-container methods on untyped receivers (``d.pop(...)``
#: must not resolve to ``AdmissionQueue.pop``). Typed receivers
#: (constructor / annotation attribute types) still resolve exactly.
_FALLBACK_BLOCKLIST = frozenset({
    "pop", "get", "put", "add", "remove", "clear", "update", "append",
    "extend", "discard", "count", "index", "copy", "keys", "values",
    "items", "setdefault", "popleft", "appendleft", "sort", "close",
    "start", "set", "join", "wait",
})

#: receiver names that mark ``.write``/``.flush`` as file I/O
_FILEISH = frozenset({"fh", "f", "_fh", "file", "_file", "sink", "_sink"})

_GUARDED_COMMENT = re.compile(
    r"self\.(\w+)\s*(?::[^=]+)?=.*#\s*guarded-by:\s*(\w+)(:writes)?")


@dataclasses.dataclass
class Finding:
    check: str       # guarded-field | lock-order | lock-cycle |
    #                # blocking-while-locked | metric-name | journal-kind |
    #                # stale-baseline | baseline-unjustified
    path: str        # repo-relative
    line: int
    qualname: str    # "Class.method" (or "<module>")
    token: str       # the stable discriminator (field / edge / op / name)
    detail: str

    @property
    def baseline_id(self) -> str:
        """Stable id for baseline matching: no line numbers, so audited
        exceptions survive unrelated edits."""
        return f"{self.check}:{self.path}:{self.qualname}:{self.token}"

    def render(self) -> str:
        return (f"LINT {self.check} {self.path}:{self.line} "
                f"[{self.qualname}] {self.token} — {self.detail}")


@dataclasses.dataclass
class LockDecl:
    attr: str
    rank_name: Optional[str]      # LOCK_RANKS key, or None (unranked)
    kind: str = "lock"            # "lock" | "condition"
    reentrant: bool = False       # RLock / RankedLock(reentrant=True)


class ClassModel:
    def __init__(self, name: str, path: str, node: ast.ClassDef):
        self.name = name
        self.path = path
        self.node = node
        self.guarded: Dict[str, Tuple[str, str]] = {}   # field -> (lock, mode)
        self.locks: Dict[str, LockDecl] = {}
        self.rank_hints: Dict[str, str] = {}            # _LOCK_RANKS
        self.attr_types: Dict[str, str] = {}            # self.x -> type name
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.scans: Dict[str, "_FnScan"] = {}

    def lock_id(self, attr: str) -> str:
        decl = self.locks.get(attr)
        if decl is not None and decl.rank_name:
            return decl.rank_name
        hint = self.rank_hints.get(attr)
        if hint:
            return hint
        return f"{self.name}.{attr}"


# --------------------------------------------------------------- extraction

def _const_str(node) -> Optional[str]:
    return node.value if isinstance(node, ast.Constant) \
        and isinstance(node.value, str) else None


def _dict_str_pairs(node) -> Dict[str, str]:
    out: Dict[str, str] = {}
    if isinstance(node, ast.Dict):
        for k, v in zip(node.keys, node.values):
            ks, vs = _const_str(k), _const_str(v)
            if ks is not None and vs is not None:
                out[ks] = vs
    return out


def _lock_ctor(value: ast.AST) -> Optional[LockDecl]:
    """LockDecl for ``threading.Lock()``/``RLock()``/``Condition()`` and
    ``RankedLock("name")``/``RankedCondition("name")`` constructor
    expressions; None for anything else."""
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    if name in ("Lock", "RLock"):
        return LockDecl("", None, "lock", reentrant=name == "RLock")
    if name == "Condition":
        return LockDecl("", None, "condition")
    if name in ("RankedLock", "RankedCondition"):
        rank = _const_str(value.args[0]) if value.args else None
        reentrant = any(
            kw.arg == "reentrant" and isinstance(kw.value, ast.Constant)
            and bool(kw.value.value) for kw in value.keywords)
        return LockDecl("", rank,
                        "condition" if name == "RankedCondition" else "lock",
                        reentrant=reentrant)
    return None


def _type_of_ctor(value: ast.AST) -> Optional[str]:
    """Best-effort static type of an assigned expression: constructor
    calls yield the class name, literals yield builtin names."""
    if isinstance(value, ast.Call):
        fn = value.func
        if isinstance(fn, ast.Name):
            return fn.id
        if isinstance(fn, ast.Attribute):
            return fn.attr
    if isinstance(value, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(value, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(value, (ast.Set, ast.SetComp)):
        return "set"
    return None


def _build_class_model(path: str, node: ast.ClassDef,
                       source_lines: Sequence[str]) -> ClassModel:
    cm = ClassModel(node.name, path, node)
    for stmt in node.body:
        # class-level declarations
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            tname = stmt.targets[0].id
            if tname == "_GUARDED_BY":
                for field, spec in _dict_str_pairs(stmt.value).items():
                    lock, _, mode = spec.partition(":")
                    cm.guarded[field] = (lock, mode or "all")
            elif tname == "_LOCK_RANKS":
                cm.rank_hints.update(_dict_str_pairs(stmt.value))
            else:
                decl = _lock_ctor(stmt.value)
                if decl is not None:
                    decl.attr = tname
                    cm.locks[tname] = decl
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cm.methods[stmt.name] = stmt
    init = cm.methods.get("__init__")
    if init is not None:
        # parameter annotations type the attrs they are stored into
        ann: Dict[str, str] = {}
        for a in init.args.args + init.args.kwonlyargs:
            if a.annotation is not None:
                t = a.annotation
                if isinstance(t, ast.Name):
                    ann[a.arg] = t.id
                elif isinstance(t, ast.Constant) and isinstance(t.value, str):
                    ann[a.arg] = t.value.split("[")[0].strip("\"'")
        for stmt in ast.walk(init):
            target = None
            value = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target, value = stmt.targets[0], stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                target, value = stmt.target, stmt.value
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            attr = target.attr
            decl = _lock_ctor(value)
            if decl is not None:
                decl.attr = attr
                cm.locks[attr] = decl
                continue
            if isinstance(value, ast.Name) and value.id in ann:
                cm.attr_types[attr] = ann[value.id]
            else:
                t = _type_of_ctor(value)
                if t is not None:
                    cm.attr_types[attr] = t
    # trailing-comment guards: ``self._x = ...  # guarded-by: _lock``
    lo = node.lineno - 1
    hi = max(getattr(node, "end_lineno", lo) or lo, lo)
    for raw in source_lines[lo:hi]:
        m = _GUARDED_COMMENT.search(raw)
        if m and m.group(1) not in cm.guarded:
            cm.guarded[m.group(1)] = (m.group(2),
                                      "writes" if m.group(3) else "all")
    return cm


# ------------------------------------------------------------- method scan

class _FnScan(ast.NodeVisitor):
    """One pass over a method body tracking the held self-lock stack."""

    def __init__(self, cm: ClassModel, fn: ast.FunctionDef):
        self.cm = cm
        self.fn = fn
        # held entries: a local lock attr name (str), or a foreign
        # descriptor ("typed", TypeName, attr) for another object's lock
        self.held: List[object] = []
        self.accesses: List[tuple] = []   # (field, is_write, held, line)
        self.calls: List[tuple] = []      # (recv_desc, meth, held, line)
        self.nested: List[tuple] = []     # (outer_desc, inner_desc, line)
        self.blocking: List[tuple] = []   # (op_token, held, line)
        self.acquired: List[str] = []     # every LOCAL lock attr taken
        self.method_refs: set = set()     # self.<m> taken as a VALUE
        self._callfuncs: set = set()      # id() of Call.func nodes
        # parameter annotations type foreign lock receivers
        self._param_types: Dict[str, str] = {}
        for a in fn.args.args + fn.args.kwonlyargs:
            t = a.annotation
            if isinstance(t, ast.Name):
                self._param_types[a.arg] = t.id
            elif isinstance(t, ast.Constant) and isinstance(t.value, str):
                self._param_types[a.arg] = \
                    t.value.split("[")[0].strip("\"'")
        self.visit(fn)

    # -------------------------------------------------------------- helpers
    def _lock_attr(self, expr) -> Optional[str]:
        """Lock attribute name when ``expr`` denotes one of this class's
        locks (``self._x`` or ``ClassName._x``)."""
        if isinstance(expr, ast.Attribute):
            v = expr.value
            if isinstance(v, ast.Name) and v.id in ("self", self.cm.name) \
                    and expr.attr in self.cm.locks:
                return expr.attr
        return None

    def _foreign_lock(self, expr) -> Optional[tuple]:
        """("typed", TypeName, attr) when ``expr`` denotes ANOTHER
        object's lock attribute and the receiver's type is statically
        known — ``replica._lock`` via a parameter annotation, or
        ``self.router._membership_lock`` via a constructor-typed attr.
        The edge resolves against that class's lock table at graph
        time, so cross-object lexical nesting joins the order checks."""
        if not isinstance(expr, ast.Attribute):
            return None
        recv = expr.value
        if isinstance(recv, ast.Name) and recv.id in self._param_types:
            return ("typed", self._param_types[recv.id], expr.attr)
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self" \
                and recv.attr in self.cm.attr_types:
            return ("typed", self.cm.attr_types[recv.attr], expr.attr)
        return None

    @staticmethod
    def _src(expr) -> str:
        try:
            return ast.unparse(expr)
        except Exception:   # pragma: no cover - py fallback
            return ""

    # ---------------------------------------------------------------- walk
    def visit_FunctionDef(self, node) -> None:
        if node is self.fn:
            for stmt in node.body:
                self.visit(stmt)
        # nested defs/lambdas run later, on an unknown lock context:
        # scan them with an EMPTY held stack (their guarded accesses
        # still register, attributed to this method)
        else:
            saved, self.held = self.held, []
            for stmt in node.body:
                self.visit(stmt)
            self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node) -> None:
        saved, self.held = self.held, []
        self.visit(node.body)
        self.held = saved

    def visit_With(self, node) -> None:
        taken: List[object] = []
        for item in node.items:
            self.visit(item.context_expr)
            attr = self._lock_attr(item.context_expr)
            desc: Optional[object] = attr
            if attr is not None:
                self.acquired.append(attr)
            else:
                desc = self._foreign_lock(item.context_expr)
            if desc is not None:
                decl = self.cm.locks.get(attr) if attr is not None \
                    else None
                if self.held and not (desc in self.held and decl
                                      and decl.reentrant):
                    # same-attribute re-entry of a reentrant lock is the
                    # one legal same-lock nesting; everything else —
                    # including a PEER instance's equally-named lock and
                    # a typed foreign lock — becomes an edge the order
                    # checks see
                    self.nested.append((self.held[-1], desc,
                                        item.context_expr.lineno))
                taken.append(desc)
        self.held.extend(taken)
        for stmt in node.body:
            self.visit(stmt)
        if taken:
            del self.held[-len(taken):]

    visit_AsyncWith = visit_With

    def visit_Attribute(self, node) -> None:
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            if node.attr in self.cm.guarded:
                self.accesses.append(
                    (node.attr,
                     isinstance(node.ctx, (ast.Store, ast.Del)),
                     tuple(self.held), node.lineno))
            # a method taken as a VALUE (callback wiring, not a call)
            # escapes the intra-class call graph — the guarded-field
            # fixpoint must treat it as an entry point
            if node.attr in self.cm.methods \
                    and isinstance(node.ctx, ast.Load) \
                    and id(node) not in self._callfuncs:
                self.method_refs.add(node.attr)
        self.generic_visit(node)

    # ------------------------------------------------------------ blocking
    def _blocking_token(self, node: ast.Call) -> Optional[str]:
        fn = node.func
        if isinstance(fn, ast.Name):
            return "open" if fn.id == "open" else None
        if not isinstance(fn, ast.Attribute):
            return None
        meth = fn.attr
        recv = fn.value
        recv_name = recv.id if isinstance(recv, ast.Name) else (
            recv.attr if isinstance(recv, ast.Attribute) else "")
        if self._lock_attr(recv) is not None:
            return None            # ops on our own locks (condition wait)
        if meth == "sleep" and recv_name == "time":
            return "time.sleep"
        if meth in ("fsync", "replace", "makedirs") and recv_name == "os":
            return f"os.{meth}"
        if meth == "dump" and recv_name == "json":
            return "json.dump"
        if meth in ("block_until_ready", "forward", "forward_verify"):
            return meth
        if meth == "join" and "thread" in self._src(recv).lower():
            return "join"
        if meth == "wait":
            return "wait"
        if meth in ("write", "flush") and recv_name in _FILEISH:
            return f"file.{meth}"
        return None

    def visit_Call(self, node) -> None:
        self._callfuncs.add(id(node.func))
        tok = self._blocking_token(node)
        if tok is not None:
            self.blocking.append((tok, tuple(self.held), node.lineno))
        fn = node.func
        if isinstance(fn, ast.Attribute) and self._lock_attr(fn) is None \
                and self._lock_attr(fn.value) is None:
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                desc = ("self",)
            elif isinstance(recv, ast.Attribute) \
                    and isinstance(recv.value, ast.Name) \
                    and recv.value.id == "self":
                desc = ("self_attr", recv.attr)
            elif isinstance(recv, ast.Name):
                desc = ("name", recv.id)
            else:
                desc = ("other",)
            self.calls.append((desc, fn.attr, tuple(self.held), node.lineno))
        self.generic_visit(node)


# --------------------------------------------------------------- the model

class RepoModel:
    def __init__(self, root: str, lock_ranks: Dict[str, int]):
        self.root = root
        self.lock_ranks = dict(lock_ranks)
        self.classes: List[ClassModel] = []
        self.by_name: Dict[str, ClassModel] = {}
        self.method_index: Dict[str, List[ClassModel]] = {}

    def add_source(self, path: str, source: str) -> None:
        tree = ast.parse(source)
        lines = source.splitlines()
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                cm = _build_class_model(path, node, lines)
                for mname, fn in cm.methods.items():
                    cm.scans[mname] = _FnScan(cm, fn)
                self.classes.append(cm)
                self.by_name[cm.name] = cm
                for mname in cm.methods:
                    self.method_index.setdefault(mname, []).append(cm)

    # ------------------------------------------------------------ resolution
    def _resolve_call(self, cm: ClassModel, desc, meth: str
                      ) -> List[ClassModel]:
        if desc[0] == "self":
            return [cm] if meth in cm.methods else []
        if desc[0] == "self_attr":
            t = cm.attr_types.get(desc[1])
            if t is not None:
                target = self.by_name.get(t)
                if target is not None:
                    return [target] if meth in target.methods else []
                return []          # typed to something un-analyzed: stop
        if meth in _FALLBACK_BLOCKLIST:
            return []
        # unique-name fallback over classes whose method takes locks or
        # blocks — the cross-object edges (router -> replica) the graph
        # needs; conservative (every candidate contributes edges)
        out = []
        for cand in self.method_index.get(meth, []):
            scan = cand.scans.get(meth)
            if scan is not None and (scan.acquired or any(
                    not h for _, h, _ in scan.blocking)):
                out.append(cand)
        return out

    # ------------------------------------------------------------ findings
    @staticmethod
    def _is_private(mname: str) -> bool:
        return mname.startswith("_") and not (
            mname.startswith("__") and mname.endswith("__"))

    def _entry_held(self, cm: ClassModel) -> Dict[str, frozenset]:
        """Locks provably held at EVERY same-class entry of each private
        helper (the caller-holds-the-lock convention, verified): a
        fixpoint over the intra-class call graph, so ``offer ->
        _push_locked -> _note_depth`` chains resolve. Public methods and
        dunders are entry points (anything may call them lock-free);
        ``__init__`` call sites are excluded (the object is not yet
        shared there, matching the access exemption)."""
        sites: Dict[str, List[tuple]] = {}
        refs: set = set()
        for mname, scan in cm.scans.items():
            refs |= scan.method_refs
            if mname == "__init__":
                continue
            for desc, meth, held, _ in scan.calls:
                if desc == ("self",) and meth in cm.methods:
                    sites.setdefault(meth, []).append(
                        (mname, frozenset(held)))
        all_locks = frozenset(cm.locks) | frozenset(cm.rank_hints)
        held_on_entry: Dict[str, frozenset] = {}
        for mname in cm.methods:
            # a method whose reference escapes (callback wiring like
            # ``self.cb = self._helper``) can run on any thread with
            # nothing held — it is an entry point no matter what its
            # same-class call sites hold, which also grounds otherwise
            # closed helper-call cycles that would keep the optimistic
            # seed forever
            held_on_entry[mname] = (
                all_locks if self._is_private(mname) and sites.get(mname)
                and mname not in refs
                else frozenset())
        changed = True
        while changed:
            changed = False
            for mname in cm.methods:
                slist = sites.get(mname)
                if not slist or not self._is_private(mname) \
                        or mname in refs:
                    continue
                new: Optional[frozenset] = None
                for caller, held in slist:
                    eff = held | held_on_entry.get(caller, frozenset())
                    new = eff if new is None else (new & eff)
                if new != held_on_entry[mname]:
                    held_on_entry[mname] = new
                    changed = True
        return held_on_entry

    def check_guarded(self) -> List[Finding]:
        findings: List[Finding] = []
        for cm in self.classes:
            if not cm.guarded:
                continue
            entry = self._entry_held(cm)
            for mname, scan in cm.scans.items():
                if mname == "__init__":
                    continue
                for field, is_write, held, line in scan.accesses:
                    spec = cm.guarded.get(field)
                    if spec is None:
                        continue
                    lock, mode = spec
                    if mode == "writes" and not is_write:
                        continue
                    if lock in held or lock in entry.get(mname, ()):
                        continue
                    findings.append(Finding(
                        "guarded-field", cm.path, line,
                        f"{cm.name}.{mname}", field,
                        f"{'write to' if is_write else 'read of'} "
                        f"{field!r} outside `with self.{lock}` "
                        f"(held: {list(held) or 'none'})"))
        return findings

    def _desc_lock_id(self, cm: ClassModel, desc) -> Optional[str]:
        """Lock id for a held-stack descriptor: a local attr name, or a
        typed foreign ("typed", TypeName, attr) entry — None when the
        foreign type is not an analyzed lock-owning class."""
        if isinstance(desc, str):
            return cm.lock_id(desc)
        if isinstance(desc, tuple) and desc[0] == "typed":
            target = self.by_name.get(desc[1])
            if target is not None and desc[2] in target.locks:
                return target.lock_id(desc[2])
        return None

    def _edges(self) -> List[tuple]:
        """(outer_id, inner_id, path, qualname, line) acquisition edges."""
        edges: List[tuple] = []
        for cm in self.classes:
            for mname, scan in cm.scans.items():
                qual = f"{cm.name}.{mname}"
                for outer, inner, line in scan.nested:
                    oid = self._desc_lock_id(cm, outer)
                    iid = self._desc_lock_id(cm, inner)
                    if oid is not None and iid is not None:
                        edges.append((oid, iid, cm.path, qual, line))
                for desc, meth, held, line in scan.calls:
                    if not held:
                        continue
                    oid = self._desc_lock_id(cm, held[-1])
                    if oid is None:
                        continue
                    for target in self._resolve_call(cm, desc, meth):
                        tscan = target.scans.get(meth)
                        if tscan is None:
                            continue
                        for attr in dict.fromkeys(tscan.acquired):
                            decl = target.locks.get(attr)
                            if (target is cm and attr == held[-1]
                                    and decl and decl.reentrant
                                    and desc == ("self",)):
                                continue    # legal reentrant re-entry
                            edges.append((oid, target.lock_id(attr),
                                          cm.path, qual, line))
        return edges

    def check_lock_order(self) -> List[Finding]:
        findings: List[Finding] = []
        edges = self._edges()
        seen = set()
        graph: Dict[str, set] = {}
        for outer, inner, path, qual, line in edges:
            # same-id edges stay: a PEER instance's equally-ranked lock
            # (two replicas merging into each other) is the classic
            # unordered AB-BA deadlock — ranked ids fail the rank check
            # below, unranked ids surface as a self-loop cycle
            graph.setdefault(outer, set()).add(inner)
            ro, ri = self.lock_ranks.get(outer), self.lock_ranks.get(inner)
            if ro is not None and ri is not None and ro >= ri:
                key = (outer, inner, qual)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    "lock-order", path, line, qual, f"{outer}->{inner}",
                    f"acquires {inner!r} (rank {ri}) while holding "
                    f"{outer!r} (rank {ro}) — rank order says "
                    f"{outer!r} must be inner"))
        findings.extend(self._cycles(graph))
        return findings

    def _cycles(self, graph: Dict[str, set]) -> List[Finding]:
        findings: List[Finding] = []
        seen_cycles = set()
        path: List[str] = []
        on_path: set = set()
        done: set = set()

        def dfs(node: str) -> None:
            path.append(node)
            on_path.add(node)
            for nxt in sorted(graph.get(node, ())):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    # normalize rotation for a stable token
                    body = cyc[:-1]
                    k = body.index(min(body))
                    norm = tuple(body[k:] + body[:k])
                    if norm not in seen_cycles:
                        seen_cycles.add(norm)
                        findings.append(Finding(
                            "lock-cycle", "<graph>", 0, "<lock-graph>",
                            "->".join(norm),
                            "cyclic lock acquisition (potential "
                            "deadlock): " + " -> ".join(norm + (norm[0],))))
                elif nxt not in done:
                    dfs(nxt)
            on_path.discard(node)
            path.pop()
            done.add(node)

        for n in sorted(graph):
            if n not in done:
                dfs(n)
        return findings

    @staticmethod
    def _held_repr(desc) -> str:
        if isinstance(desc, str):
            return f"self.{desc}"
        return f"{desc[1]}.{desc[2]}"

    def check_blocking(self) -> List[Finding]:
        findings: List[Finding] = []
        seen = set()
        # methods that block directly with no lock held (candidates for
        # the one-level call resolution)
        blocks_directly: Dict[Tuple[str, str], List[str]] = {}
        for cm in self.classes:
            for mname, scan in cm.scans.items():
                for tok, held, line in scan.blocking:
                    if held:
                        key = (cm.path, f"{cm.name}.{mname}", tok)
                        if key in seen:
                            continue
                        seen.add(key)
                        findings.append(Finding(
                            "blocking-while-locked", cm.path, line,
                            f"{cm.name}.{mname}", tok,
                            f"{tok} inside `with "
                            f"{self._held_repr(held[-1])}` — a blocked "
                            "holder wedges every waiter"))
                    else:
                        blocks_directly.setdefault(
                            (cm.name, mname), []).append(tok)
        for cm in self.classes:
            for mname, scan in cm.scans.items():
                for desc, meth, held, line in scan.calls:
                    if not held:
                        continue
                    all_toks: List[str] = []
                    for target in self._resolve_call(cm, desc, meth):
                        all_toks.extend(
                            blocks_directly.get((target.name, meth), ()))
                    if not all_toks:
                        continue
                    # one finding per (call site method, callee) with the
                    # CALLEE name alone as the stable token: the op list
                    # depends on which unique-name candidates exist
                    # elsewhere in the tree, and a baseline id must
                    # survive unrelated file additions (the ops stay in
                    # the detail text)
                    token = meth
                    key = (cm.path, f"{cm.name}.{mname}", token)
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(Finding(
                        "blocking-while-locked", cm.path, line,
                        f"{cm.name}.{mname}", token,
                        f"calls {meth}() (which does "
                        f"{', '.join(sorted(set(all_toks)))}) while "
                        f"holding {self._held_repr(held[-1])}"))
        return findings


# ----------------------------------------------------------------- drivers

def parse_lock_ranks(root: str) -> Dict[str, int]:
    """The rank table, read from utils/locks.py BY AST — the same
    declaration the runtime enforces, without importing the package."""
    path = os.path.join(root, "deepspeed_tpu", "utils", "locks.py")
    with open(path) as fh:
        tree = ast.parse(fh.read())
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "LOCK_RANKS" \
                and isinstance(node.value, ast.Dict):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                ks = _const_str(k)
                if ks is not None and isinstance(v, ast.Constant):
                    out[ks] = int(v.value)
            return out
    raise ValueError(f"no LOCK_RANKS dict literal in {path}")


def iter_py_files(root: str, paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        full = os.path.join(root, p)
        if os.path.isfile(full):
            out.append(p)
        elif os.path.isdir(full):
            for dirpath, _, names in os.walk(full):
                for n in sorted(names):
                    if n.endswith(".py"):
                        out.append(os.path.relpath(
                            os.path.join(dirpath, n), root))
    return sorted(dict.fromkeys(out))


def build_model(root: str,
                paths: Sequence[str] = DEFAULT_PATHS) -> RepoModel:
    model = RepoModel(root, parse_lock_ranks(root))
    for rel in iter_py_files(root, paths):
        with open(os.path.join(root, rel)) as fh:
            model.add_source(rel, fh.read())
    return model


def analyze(root: str,
            paths: Sequence[str] = DEFAULT_PATHS) -> List[Finding]:
    """Run the three concurrency checks; returns raw (un-baselined)
    findings."""
    model = build_model(root, paths)
    return (model.check_guarded() + model.check_lock_order()
            + model.check_blocking())


def analyze_source(source: str, path: str = "<fixture>.py",
                   lock_ranks: Optional[Dict[str, int]] = None
                   ) -> List[Finding]:
    """Analyze one source string (the test-fixture entry point)."""
    if lock_ranks is None:
        from ..utils.locks import LOCK_RANKS
        lock_ranks = dict(LOCK_RANKS)
    model = RepoModel("<memory>", lock_ranks)
    model.add_source(path, source)
    return (model.check_guarded() + model.check_lock_order()
            + model.check_blocking())
