"""ZeRO++ — quantized ZeRO-3 collectives (qwZ, qgZ) and hpZ sharding.

Counterpart of the reference's ZeRO++ machinery:

- qwZ — quantized weight all-gather (`runtime/zero/partition_parameters.py:679``
  ``CUDAQuantizer``: int8 block quant around the stage-3 param all-gather);
- qgZ — quantized gradient reduce (``runtime/comm/coalesced_collectives.py:31``
  ``all_to_all_quant_reduce``: quantize, all-to-all, dequantize, reduce —
  replacing the fp reduce-scatter);
- hpZ — hierarchical partitioning (``zero/config.py:256-272``): weight
  shards gathered over a *small* group while optimizer state shards over a
  larger one (see ``ZeroShardingPlan.opt_state``'s hpz extension).

TPU-native formulation: under GSPMD the stage-3 weight all-gather is
implicit (XLA inserts it per layer inside the scan). To quantize it, the
gather is made *explicit* for exactly the weight leaves: a ``shard_map``
over the mesh wraps each scan iteration's layer params, all-gathering the
int8 payload + f32 block scales over the ``fsdp`` axis and dequantizing in
VMEM-adjacent fused ops. The backward (via ``jax.custom_vjp``) is the
gradient reduce: quantize → ``lax.all_to_all`` → dequantize → sum when qgZ
is on (the all_to_all_quant_reduce pattern), else a plain
``lax.psum_scatter``. Comm rides ICI with 1/4 (int8) or 1/8 (packed int4)
of the fp32 byte volume.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec

from ..ops.quantizer import (choose_block, dequantize_blockwise, pack_int4,
                             quantize_blockwise, unpack_int4)
from . import topology as topo


def _gather_dim(spec: PartitionSpec, axis: str) -> Optional[int]:
    """Index of the dim sharded over ``axis`` in a PartitionSpec (None if
    the leaf isn't sharded over it)."""
    for i, entry in enumerate(spec):
        names = entry if isinstance(entry, tuple) else (entry,)
        if axis in names:
            return i
    return None


def _without_axis(spec: PartitionSpec, axis: str) -> PartitionSpec:
    out = []
    for entry in spec:
        names = entry if isinstance(entry, tuple) else (entry,)
        kept = tuple(n for n in names if n != axis)
        out.append(kept if len(kept) > 1 else (kept[0] if kept else None))
    return PartitionSpec(*out)


def quantized_all_gather(x, axis_name: str, gdim: int, *, qw_bits: Optional[int],
                         qg_bits: Optional[int], out_dtype):
    """All-gather ``x`` (one device's shard) over ``axis_name`` along dim
    ``gdim``, int-quantized on the wire; backward is the (optionally
    quantized) gradient reduce-scatter. Must run inside shard_map."""

    @jax.custom_vjp
    def gather(x):
        return _fwd(x)[0]

    def _fwd(x):
        if qw_bits is None:
            return lax.all_gather(x, axis_name, axis=gdim, tiled=True), None
        block = choose_block(x.shape[-1])
        q, s = quantize_blockwise(x, bits=qw_bits, block=block)
        if qw_bits == 4 and q.shape[-1] % 2 == 0:
            payload = pack_int4(q)
            payload = lax.all_gather(payload, axis_name, axis=gdim, tiled=True)
            q_full = unpack_int4(payload)
        else:
            q_full = lax.all_gather(q, axis_name, axis=gdim, tiled=True)
        # s has x's rank (last dim = n_blocks), so the gather dim carries over
        s_full = lax.all_gather(s, axis_name, axis=gdim, tiled=True)
        return dequantize_blockwise(q_full, s_full, block=block,
                                    dtype=out_dtype), None

    def _bwd(_, g):
        from ..compat import axis_size
        world = axis_size(axis_name)
        if qg_bits is None:
            return (lax.psum_scatter(g, axis_name, scatter_dimension=gdim,
                                     tiled=True),)
        # all_to_all_quant_reduce: split my full gradient into per-owner
        # chunks, quantize each, exchange, dequantize, and sum the world
        # partial contributions of my shard.
        chunks = jnp.stack(jnp.split(g, world, axis=gdim), axis=0)
        block = choose_block(chunks.shape[-1])
        q, s = quantize_blockwise(chunks, bits=qg_bits, block=block)
        # stacked [world, ...] exchange: slice j goes to device j, received
        # slices stack back on dim 0 (one partial contribution per peer)
        if qg_bits == 4 and q.shape[-1] % 2 == 0:
            payload = pack_int4(q)
            payload = lax.all_to_all(payload, axis_name, split_axis=0,
                                     concat_axis=0)
            q = unpack_int4(payload)
        else:
            q = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
        s = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)
        parts = dequantize_blockwise(q, s, block=block, dtype=jnp.float32)
        return (jnp.sum(parts, axis=0).astype(g.dtype),)

    gather.defvjp(lambda x: (_fwd(x)[0], None), _bwd)
    return gather(x)


def make_quantized_gather_transform(mesh: Mesh, leaf_specs: Dict[str, Any],
                                    *, qw_bits: Optional[int] = 8,
                                    qg_bits: Optional[int] = None,
                                    dtype=jnp.float32,
                                    axis: str = topo.FSDP_AXIS):
    """Build a transform(dict-of-arrays) -> dict-of-arrays that explicitly
    all-gathers every fsdp-sharded leaf with quantized payloads.

    ``leaf_specs``: leaf name → PartitionSpec of that leaf (per-layer view,
    i.e. without the stacked-layers dim). Leaves without an fsdp-sharded
    dim pass through untouched (XLA handles them as before).
    """
    if mesh.shape.get(axis, 1) <= 1:
        return None

    gathered: Dict[str, int] = {}
    for name, spec in leaf_specs.items():
        gd = _gather_dim(spec, axis)
        if gd is not None:
            gathered[name] = gd
    if not gathered:
        return None

    in_specs = {name: leaf_specs[name] for name in leaf_specs}
    out_specs = {name: (_without_axis(leaf_specs[name], axis)
                        if name in gathered else leaf_specs[name])
                 for name in leaf_specs}

    from ..compat import shard_map

    def body(lp: Dict[str, Any]) -> Dict[str, Any]:
        out = {}
        for name, w in lp.items():
            if name in gathered:
                out[name] = quantized_all_gather(
                    w, axis, gathered[name], qw_bits=qw_bits,
                    qg_bits=qg_bits, out_dtype=w.dtype)
            else:
                out[name] = w
        return out

    def transform(lp: Dict[str, Any]) -> Dict[str, Any]:
        fn = shard_map(body, mesh=mesh,
                       in_specs=({k: in_specs[k] for k in lp},),
                       out_specs={k: out_specs[k] for k in lp},
                       check_vma=False)
        return fn(lp)

    return transform
