"""Device-mesh topology: the TPU-native process-group registry.

Counterpart of reference ``deepspeed/utils/groups.py`` (group creation
:51,64,113) and ``runtime/pipe/topology.py:12`` (``ProcessTopology`` /
``PipeModelDataParallelTopology``). Where the reference builds
torch.distributed process groups out of rank lists, the TPU-native design is
one :class:`jax.sharding.Mesh` with named axes — every parallelism form is an
axis, every "process group" is an axis (or tuple of axes), and XLA inserts
the collectives. Axis sizes come from the config's ``mesh`` block.

Axes (ordered outermost→innermost by default so that tensor/sequence axes
land on the fastest ICI links):

- ``pipe``    — pipeline-parallel stages
- ``data``    — pure data parallel (params replicated)
- ``fsdp``    — ZeRO parameter/optimizer sharding axis
- ``sequence``— Ulysses sequence parallelism
- ``expert``  — MoE expert parallelism
- ``tensor``  — megatron-style tensor parallelism
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
SEQUENCE_AXIS = "sequence"
EXPERT_AXIS = "expert"
TENSOR_AXIS = "tensor"

ALL_AXES = (PIPE_AXIS, DATA_AXIS, FSDP_AXIS, SEQUENCE_AXIS, EXPERT_AXIS, TENSOR_AXIS)

# Axes over which gradients are averaged (data parallel replicas).
GRAD_REDUCE_AXES = (DATA_AXIS, FSDP_AXIS)
# Axes over which a batch is split.
BATCH_AXES = (DATA_AXIS, FSDP_AXIS)


class MeshTopology:
    """Wraps a ``jax.sharding.Mesh`` with accessors mirroring the
    reference's groups API (utils/groups.py:420-465 etc.)."""

    def __init__(self, mesh):
        self.mesh = mesh

    # -- factory ----------------------------------------------------------
    @classmethod
    def build(cls, mesh_config=None, devices: Optional[Sequence] = None,
              **axis_sizes) -> "MeshTopology":
        """Build from a MeshConfig (runtime/config.py) or explicit axis sizes.

        One axis may be -1 ("all remaining devices"); by default that is the
        data axis.
        """
        import jax
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        n = len(devices)

        sizes = {a: 1 for a in ALL_AXES}
        sizes[DATA_AXIS] = -1  # default: data axis soaks up all devices
        order = list(ALL_AXES)
        if mesh_config is not None:
            for a in ALL_AXES:
                sizes[a] = getattr(mesh_config, a)
            order = list(mesh_config.axis_order)
        sizes.update(axis_sizes)

        wildcard = [a for a in ALL_AXES if sizes[a] == -1]
        if len(wildcard) > 1:
            raise ValueError(f"Only one mesh axis may be -1, got {wildcard}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wildcard:
            if n % fixed != 0:
                raise ValueError(
                    f"Cannot infer {wildcard[0]} axis: {n} devices not divisible by {fixed}")
            sizes[wildcard[0]] = n // fixed
        elif fixed != n:
            raise ValueError(f"Mesh sizes {sizes} product {fixed} != device count {n}")

        shape = [sizes[a] for a in order]
        dev_array = np.array(devices).reshape(shape)
        return cls(Mesh(dev_array, tuple(order)))

    # -- axis info --------------------------------------------------------
    @property
    def axis_names(self) -> tuple:
        return tuple(self.mesh.axis_names)

    def axis_size(self, axis: str) -> int:
        return self.mesh.shape[axis]

    @property
    def world_size(self) -> int:
        return math.prod(self.mesh.shape.values())

    # -- reference-compatible accessors ----------------------------------
    def get_data_parallel_world_size(self) -> int:
        """DP world size includes the fsdp (ZeRO) axis — batch is split over
        both, matching the reference where ZeRO shards over the DP group."""
        return self.axis_size(DATA_AXIS) * self.axis_size(FSDP_AXIS)

    def get_model_parallel_world_size(self) -> int:
        return self.axis_size(TENSOR_AXIS)

    def get_pipe_parallel_world_size(self) -> int:
        return self.axis_size(PIPE_AXIS)

    def get_expert_parallel_world_size(self) -> int:
        return self.axis_size(EXPERT_AXIS)

    def get_sequence_parallel_world_size(self) -> int:
        return self.axis_size(SEQUENCE_AXIS)

    def get_sequence_data_parallel_world_size(self) -> int:
        return self.get_sequence_parallel_world_size() * self.get_data_parallel_world_size()

    # -- sharding helpers -------------------------------------------------
    def sharding(self, *spec_axes):
        """NamedSharding for a PartitionSpec over this mesh."""
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec(*spec_axes))

    def batch_sharding(self):
        """Sharding for a [batch, ...] array: batch split over data+fsdp axes.
        (Sequence sharding happens on *activations* via in-model constraints —
        raw token arrays are often seq+1 long and not divisible.)"""
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec(BATCH_AXES))

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec())

    def __repr__(self) -> str:
        return f"MeshTopology({dict(self.mesh.shape)})"


# ------------------------------------------------------------------ registry
_topology: Optional[MeshTopology] = None


def set_topology(topo: MeshTopology) -> None:
    global _topology
    _topology = topo


def get_topology() -> MeshTopology:
    global _topology
    if _topology is None:
        _topology = MeshTopology.build()
    return _topology


def has_topology() -> bool:
    return _topology is not None


def reset_topology() -> None:
    global _topology
    _topology = None
