from .topology import (
    ALL_AXES,
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    PIPE_AXIS,
    SEQUENCE_AXIS,
    TENSOR_AXIS,
    MeshTopology,
    get_topology,
    has_topology,
    reset_topology,
    set_topology,
)

__all__ = [n for n in dir() if not n.startswith("_")]
