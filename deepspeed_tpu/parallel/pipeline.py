"""SPMD pipeline parallelism: GPipe over the ``pipe`` mesh axis.

Counterpart of reference ``runtime/pipe/engine.py`` (``PipelineEngine``
:55, ``train_batch`` :312, ``_exec_schedule`` :1331 interpreting
``PipeInstruction`` streams over P2P sends). The TPU-native design collapses
the instruction interpreter + p2p protocol into ONE jitted collective
program: the layer stack's leading dim is sharded over the ``pipe`` axis
(each device holds L/P contiguous layers), microbatch activations rotate
stage→stage via ``lax.ppermute`` inside a ``lax.scan`` over schedule ticks,
and XLA's autodiff of that scan *is* the backward pipeline (reversed
ppermutes, exact 1F1B-equivalent data flow in reverse). The warm-up/drain
bubble is (P-1)/(M+P-1), identical to GPipe/the reference's TrainSchedule.

Runs under ``shard_map`` with ONLY the pipe axis manual (``axis_names=
{'pipe'}``): data/fsdp/tensor stay in GSPMD auto mode inside the body, so
ZeRO sharding and tensor parallelism compose with the pipeline untouched.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from . import topology as topo


def spmd_pipeline(layer_fn: Callable, local_layers, x, num_micro: int,
                  axis_name: str = topo.PIPE_AXIS):
    """Run a pipelined scan-over-layers inside shard_map.

    ``layer_fn(carry, layer_slice, micro_idx) -> (carry, aux)`` — one
    transformer block; ``micro_idx`` is the microbatch id (for per-microbatch
    RNG folding) and ``aux`` a scalar auxiliary loss (e.g. MoE load
    balancing; return 0.0 if unused). ``local_layers`` — pytree with leading
    dim L/P (this stage's layers, as sliced by shard_map); ``x`` [B, T, H]
    full activations (replicated over the pipe axis); ``num_micro`` M
    pipeline microbatches (B % M == 0).

    Returns ``(out [B, T, H], aux)`` on every stage: the last stage's output
    broadcast, and the aux loss summed over layers/stages, averaged over
    microbatches (comparable to the unpipelined full-batch value).
    """
    P = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    B = x.shape[0]
    M = num_micro
    assert B % M == 0, f"batch {B} not divisible by pipeline microbatches {M}"
    mb = B // M
    x_m = x.reshape(M, mb, *x.shape[1:])
    n_ticks = M + P - 1

    def run_local_layers(carry, micro_idx):
        def body(c, lp):
            y, aux = layer_fn(c, lp, micro_idx)
            return y, aux

        y, auxes = lax.scan(body, carry, local_layers)
        return y, jnp.sum(auxes)

    perm = [(i, (i + 1) % P) for i in range(P)]

    def compute(state, t):
        """One schedule tick: ingest, run local layers, record output."""
        cur, out, aux_acc = state
        # stage 0 ingests microbatch t (garbage ticks masked by clip+where)
        m_in = jnp.clip(t, 0, M - 1)
        inject = x_m[m_in]
        cur = jnp.where(stage == 0, jnp.where(t < M, inject, cur), cur)
        # this stage processes microbatch t-stage (may be out of range
        # during fill/drain — masked below)
        m_here = t - stage
        y, aux = run_local_layers(cur, jnp.clip(m_here, 0, M - 1))
        aux_acc = aux_acc + jnp.where((m_here >= 0) & (m_here < M), aux, 0.0)
        # last stage records microbatch t-(P-1)
        m_out = t - (P - 1)
        upd = lax.dynamic_update_index_in_dim(
            out, y.astype(out.dtype), jnp.clip(m_out, 0, M - 1), 0)
        valid = (m_out >= 0) & (stage == P - 1)
        out = jnp.where(valid, upd, out)
        return y, out, aux_acc

    def tick(state, t):
        y, out, aux_acc = compute(state, t)
        # hand activations to the next stage
        nxt = lax.ppermute(y, axis_name, perm)
        return (nxt, out, aux_acc), None

    # carries become stage-varying after the first tick; mark them so
    from ..compat import pcast
    var = lambda a: pcast(a, (axis_name,), to="varying")  # noqa: E731
    cur0 = var(jnp.zeros((mb,) + x.shape[1:], x.dtype))
    out0 = var(jnp.zeros_like(x_m))
    aux0 = var(jnp.zeros((), jnp.float32))
    state = (cur0, out0, aux0)
    if n_ticks > 1:
        # rotate on all but the final tick (its ppermute result would be
        # discarded — wasted ICI transfer each way)
        state, _ = lax.scan(tick, state, jnp.arange(n_ticks - 1))
    _, out, aux_acc = compute(state, n_ticks - 1)

    # broadcast the last stage's output to all stages (final norm/unembed
    # run replicated, exactly like the reference's loss broadcast
    # pipe/engine.py:545 _aggregate_total_loss)
    out = jnp.where(stage == P - 1, out, jnp.zeros_like(out))
    out = lax.psum(out, axis_name)
    aux = lax.psum(aux_acc, axis_name) / M
    return out.reshape(B, *x.shape[1:]), aux


def pipelined_layer_apply(layer_fn: Callable, stacked_layers, x,
                          num_micro: int, mesh=None,
                          axis_name: str = topo.PIPE_AXIS):
    """Host-level wrapper: shard_map ``spmd_pipeline`` with only the pipe
    axis manual. ``stacked_layers`` leaves have leading dim L (divisible by
    the pipe axis size); ``x`` [B, T, H]. Returns ``(out, aux)``."""
    from ..compat import shard_map
    from jax.sharding import PartitionSpec as Pspec

    if mesh is None:
        mesh = topo.get_topology().mesh

    layer_specs = jax.tree.map(lambda _: Pspec(axis_name), stacked_layers)
    fn = shard_map(
        partial(spmd_pipeline, layer_fn, num_micro=num_micro,
                axis_name=axis_name),
        mesh=mesh,
        in_specs=(layer_specs, Pspec()),
        out_specs=(Pspec(), Pspec()),
        axis_names={axis_name})
    return fn(stacked_layers, x)
