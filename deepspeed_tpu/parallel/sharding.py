"""ZeRO + tensor-parallel sharding rules: logical param axes → mesh axes.

This module is the TPU-native re-design of the reference's entire ZeRO
partitioning machinery (``runtime/zero/stage_1_and_2.py:96``,
``stage3.py:72``, ``partition_parameters.py:734``): instead of imperative
flatten/partition/all-gather bookkeeping, each ZeRO stage is a *sharding
rule* applied to the parameter / gradient / optimizer-state pytrees inside
one jitted train step. XLA then inserts exactly the collectives the
reference hand-codes:

- stage 1: optimizer state sharded over the ``fsdp`` axis → the optimizer
  update runs on a shard and the new params all-gather back (the reference's
  ``stage_1_and_2.py:1699 step`` + allgather).
- stage 2: + gradients reduce-scattered onto the ``fsdp`` axis (the
  reference's hook-driven ``average_tensor :956`` reduce-scatter engine).
- stage 3: + parameters stored sharded; XLA's SPMD partitioner inserts
  per-layer all-gathers at use and discards them after (the reference's
  fetch/release hooks ``parameter_offload.py:342`` + prefetch coordinator —
  replaced by XLA's latency-hiding scheduler).

Tensor parallelism: model code annotates each param with *logical* axis
names (``('embed','mlp')``…); a rules table maps logical names to mesh axes
(Megatron-style column/row sharding = mapping ``mlp``/``heads`` to the
``tensor`` axis). ZeRO-3 then shards one *remaining* dim over ``fsdp`` —
preferring the ``embed`` dim (see ``_FSDP_PREFERRED``), else the largest.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from . import topology as topo

# ---------------------------------------------------------------- logical axes
# Default logical-axis → mesh-axis rules (flax partitioning idiom).
# Model code uses these names in its param specs.
DEFAULT_RULES: Dict[str, Optional[str]] = {
    "vocab": None,            # embedding vocab dim (sharded over tensor for TP-vocab)
    "embed": None,            # model/hidden dim — kept replicated for TP (row inputs)
    "mlp": topo.TENSOR_AXIS,  # MLP intermediate dim (column-parallel)
    "heads": topo.TENSOR_AXIS,  # attention heads dim (column-parallel QKV)
    "kv_heads": topo.TENSOR_AXIS,
    "head_dim": None,
    "layers": topo.PIPE_AXIS,  # stacked-layer leading dim → pipeline stages
    "expert": topo.EXPERT_AXIS,
    "seq": topo.SEQUENCE_AXIS,
    "batch": topo.DATA_AXIS,
}


class ParamSpec(tuple):
    """A tuple of logical axis names (or None) — one per array dim."""
    __slots__ = ()


def spec(*names) -> ParamSpec:
    return ParamSpec(names)


def logical_to_mesh_axes(logical: Sequence[Optional[str]],
                         rules: Optional[Dict[str, Optional[str]]] = None) -> list:
    rules = rules or DEFAULT_RULES
    return [rules.get(name) if name is not None else None for name in logical]


# Logical dims preferred for the fsdp shard, in order. Sharding every param's
# ``embed`` dim (rather than its largest dim) keeps all grad/param shardings
# mutually consistent with the batch-sharded backward: e.g. putting fsdp on the
# embedding table's *vocab* dim makes the SPMD partitioner reshard the
# [batch, seq, vocab] logits cotangent from batch-sharded to vocab-sharded,
# which XLA can only do by full rematerialization (a per-step collective tax
# observed in the tp×fsdp×dp dryrun). MaxText's logical rules make the same
# choice (embed→fsdp, vocab→tensor).
_FSDP_PREFERRED = ("embed",)


def _assign_fsdp(mesh_axes: list, shape: Tuple[int, ...], mesh: Mesh,
                 logical: Optional[Sequence[Optional[str]]] = None,
                 fsdp_axes: Tuple[str, ...] = (topo.FSDP_AXIS,)) -> list:
    """Shard one not-yet-sharded dim over the fsdp axis group (must divide).

    Preference: a dim with a logical name in ``_FSDP_PREFERRED`` (see above),
    else the largest eligible dim (memory balance). ``fsdp_axes`` longer
    than one (e.g. ``('fsdp', 'data')``) shards the dim over the product —
    the ZeRO++ hpZ "primary partition": optimizer state spread over more
    devices than the weight-gather group (reference zero/config.py:256).
    """
    axes = tuple(a for a in fsdp_axes if mesh.shape.get(a, 1) > 1)
    size = math.prod(mesh.shape.get(a, 1) for a in axes)
    if size <= 1:
        return mesh_axes
    entry = axes if len(axes) > 1 else axes[0]
    logical = logical or [None] * len(shape)
    for name in _FSDP_PREFERRED:
        for i, (ax, dim, lname) in enumerate(zip(mesh_axes, shape, logical)):
            if ax is None and lname == name and dim % size == 0:
                mesh_axes[i] = entry
                return mesh_axes
    # fallback: unsharded, divisible by the axis-group size; pick the largest
    best, best_size = None, 0
    for i, (ax, dim) in enumerate(zip(mesh_axes, shape)):
        if ax is None and dim % size == 0 and dim > best_size:
            best, best_size = i, dim
    if best is not None:
        mesh_axes[best] = entry
    return mesh_axes


def shard_spec_for(shape: Tuple[int, ...],
                   logical: Optional[Sequence[Optional[str]]],
                   mesh: Mesh,
                   zero_stage: int = 0,
                   rules: Optional[Dict[str, Optional[str]]] = None,
                   force_fsdp: bool = False,
                   fsdp_axes: Tuple[str, ...] = (topo.FSDP_AXIS,)) -> PartitionSpec:
    """PartitionSpec for one parameter.

    ``force_fsdp`` is used for optimizer state / gradients under stages 1-2,
    where the *param* stays replicated but state is sharded.
    """
    if logical is None:
        logical = [None] * len(shape)
    mesh_axes = logical_to_mesh_axes(logical, rules)
    # drop tensor-axis assignments that don't divide
    for i, ax in enumerate(mesh_axes):
        if ax is not None:
            n = mesh.shape.get(ax, 1)
            if n <= 1 or shape[i] % n != 0:
                mesh_axes[i] = None
    if zero_stage >= 3 or force_fsdp:
        mesh_axes = _assign_fsdp(mesh_axes, shape, mesh, logical, fsdp_axes)
    return PartitionSpec(*mesh_axes)


def tree_shardings(params_or_shapes, spec_tree, mesh: Mesh, zero_stage: int = 0,
                   rules=None, force_fsdp: bool = False,
                   fsdp_axes: Tuple[str, ...] = (topo.FSDP_AXIS,)):
    """Tree of NamedShardings matching a param (or ShapeDtypeStruct) tree.

    ``spec_tree`` mirrors the param tree with ParamSpec leaves (or None).
    """
    def one(leaf, lspec):
        shape = leaf.shape if hasattr(leaf, "shape") else tuple(leaf)
        ps = shard_spec_for(shape, lspec, mesh, zero_stage, rules, force_fsdp,
                            fsdp_axes)
        return NamedSharding(mesh, ps)

    if spec_tree is None:
        return jax.tree.map(
            lambda l: NamedSharding(
                mesh, shard_spec_for(l.shape, None, mesh, zero_stage, rules,
                                     force_fsdp, fsdp_axes)),
            params_or_shapes)
    return jax.tree.map(one, params_or_shapes, spec_tree,
                        is_leaf=lambda x: isinstance(x, ParamSpec) or x is None)


class ZeroShardingPlan:
    """The full sharding plan for a train state under a given ZeRO stage.

    Replaces the reference's partitioning subsystems with four sharding
    trees: params, grads (accumulator), optimizer moments, and batch.
    """

    def __init__(self, topology: topo.MeshTopology, zero_stage: int,
                 spec_tree=None, rules=None, hpz: bool = False):
        self.topo = topology
        self.mesh = topology.mesh
        self.stage = zero_stage
        self.spec_tree = spec_tree
        self.rules = rules
        # ZeRO++ hpZ: optimizer state sharded over fsdp×data (the "primary"
        # partition spanning all DP replicas) while params/grads stay on the
        # fsdp axis only, so weight gathers ride the small group.
        self.hpz = hpz

    def params(self, shapes):
        return tree_shardings(shapes, self.spec_tree, self.mesh, self.stage,
                              self.rules)

    def grads(self, shapes):
        # stage >=2: reduce-scatter grads onto fsdp axis
        return tree_shardings(shapes, self.spec_tree, self.mesh, self.stage,
                              self.rules, force_fsdp=self.stage >= 2)

    def opt_state(self, moment_shapes):
        # stage >=1: shard optimizer moments over fsdp axis. ``moment_shapes``
        # is a dict of param-shaped pytrees ({"m": ..., "v": ...}), so the
        # param spec tree is replicated per moment key.
        spec = (None if self.spec_tree is None
                else {k: self.spec_tree for k in moment_shapes})
        axes = ((topo.FSDP_AXIS, topo.DATA_AXIS) if self.hpz
                else (topo.FSDP_AXIS,))
        return tree_shardings(moment_shapes, spec, self.mesh, self.stage,
                              self.rules, force_fsdp=self.stage >= 1,
                              fsdp_axes=axes)

    def batch(self):
        return self.topo.batch_sharding()

    def replicated(self):
        return self.topo.replicated()
