"""ZeRO-Inference weight-only quantization: int8/int4 params, dequant on use.

Counterpart of reference ``deepspeed/inference/quantization/``
(``quantization.py``, ``layers.py``: 4/8-bit weight-only quantization with
dequant-on-the-fly linear layers — the "ZeRO-Inference 20× cheaper serving"
path). The torch design swaps nn.Linear for QuantizedLinear modules; the
TPU-native design needs no module surgery: param leaves become
``QuantTensor`` pytree nodes (int8 payload + f32 block scales in HBM,
int4 optionally nibble-packed) that dequantize lazily at their point of
use inside jit — XLA fuses the ``q * scale`` multiply into the consuming
matmul, so HBM holds 1/4 (int8) or 1/8 (int4) of the fp32 bytes and the
MXU still sees bf16/f32 operands.

``QuantTensor`` duck-types exactly the array surface the model layer code
touches (``astype``, ``.T``, ``[indices]``, ``shape``): embedding lookups
index the int8 rows *before* dequantizing, so a [V, H] table never
materializes in fp.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.quantizer import (choose_block, dequantize_blockwise, pack_int4,
                             quantize_blockwise, unpack_int4)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantTensor:
    """Blockwise-quantized array leaf. ``q`` int8 (or nibble-packed uint8
    when ``packed``); ``scales`` f32 [..., N/block]."""
    q: Any
    scales: Any
    block: int
    bits: int
    packed: bool
    out_dtype: Any

    # -- pytree -------------------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.scales), (self.block, self.bits, self.packed,
                                       self.out_dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scales = children
        return cls(q, scales, *aux)

    # -- array duck-typing (the surface models/transformer.py touches) ------
    @property
    def shape(self) -> Tuple[int, ...]:
        s = tuple(self.q.shape)
        return s[:-1] + (s[-1] * 2,) if self.packed else s

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def dtype(self):
        return self.out_dtype

    def _values(self):
        return unpack_int4(self.q) if self.packed else self.q

    def dequantize(self, dtype=None):
        return dequantize_blockwise(self._values(), self.scales,
                                    block=self.block,
                                    dtype=dtype or self.out_dtype)

    def astype(self, dtype):
        return self.dequantize(dtype)

    @property
    def T(self):
        return self.dequantize().T

    def __getitem__(self, idx):
        """Row indexing (embedding lookup): gather int8 rows + their scales,
        dequantize only the gathered rows. Last-dim indexing unsupported."""
        return dequantize_blockwise(self._values()[idx], self.scales[idx],
                                    block=self.block, dtype=self.out_dtype)

    def __matmul__(self, other):
        return self.dequantize() @ other

    def __rmatmul__(self, other):
        return other @ self.dequantize()

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.q.shape) * self.q.dtype.itemsize
                   + np.prod(self.scales.shape) * self.scales.dtype.itemsize)


def quantize_array(x, bits: int = 8, block: Optional[int] = None,
                   pack: bool = True) -> QuantTensor:
    block = block or choose_block(x.shape[-1])
    q, s = quantize_blockwise(x, bits=bits, block=block)
    packed = bits == 4 and pack and q.shape[-1] % 2 == 0
    if packed:
        q = pack_int4(q)
    return QuantTensor(q=q, scales=s, block=block, bits=bits, packed=packed,
                       out_dtype=x.dtype)


def quantize_param_tree(params, bits: int = 8, min_dims: int = 2,
                        min_size: int = 4096):
    """Weight-only quantization of a param pytree: matrices become
    QuantTensors, small/1-D leaves (norms, biases) stay fp (reference
    layers.py quantizes Linear/Embedding weights only)."""
    def one(leaf):
        if leaf.ndim < min_dims or leaf.size < min_size:
            return leaf
        return quantize_array(leaf, bits=bits)

    return jax.tree.map(one, params)


def tree_nbytes(params) -> int:
    total = 0
    for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QuantTensor)):
        if isinstance(leaf, QuantTensor):
            total += leaf.nbytes
        else:
            total += int(np.prod(leaf.shape) * jnp.dtype(leaf.dtype).itemsize)
    return total
