"""Inference config (reference deepspeed/inference/config.py:
``DeepSpeedInferenceConfig``). Keeps the reference's key surface
(dtype/tensor_parallel/max_out_tokens/replace_with_kernel_inject...) mapped
onto the TPU runtime: kernel injection is a no-op (JAX models are already
compiled+fused), tensor_parallel.tp_size maps to the mesh's tensor axis."""

from __future__ import annotations

from typing import Any, Dict, Optional

from pydantic import Field

from ..runtime.config_utils import DSConfigModel


class DeepSpeedTPConfig(DSConfigModel):
    enabled: bool = True
    tp_size: int = 1


class QuantizationConfig(DSConfigModel):
    enabled: bool = False
    bits: int = 8


class InferenceConfig(DSConfigModel):
    dtype: str = "bf16"
    tensor_parallel: DeepSpeedTPConfig = Field(default_factory=DeepSpeedTPConfig,
                                               alias="tp")
    max_out_tokens: int = 1024
    min_out_tokens: int = 1
    max_tokens: int = 1024
    replace_with_kernel_inject: bool = False   # accepted; meaningless on TPU
    replace_method: str = "auto"
    quant: QuantizationConfig = Field(default_factory=QuantizationConfig)
    checkpoint: Optional[str] = None
    zero_allow_untested_optimizer: bool = True
    enable_cuda_graph: bool = False            # XLA compiles whole graphs anyway
    set_empty_params: bool = False
    save_mp_checkpoint_path: Optional[str] = None
    ep_size: int = 1
    moe: Dict[str, Any] = Field(default_factory=dict)
