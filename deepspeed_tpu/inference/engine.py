"""Inference engine v1 — compiled generate with KV cache and TP sharding.

Counterpart of reference ``deepspeed/inference/engine.py:39``
(``InferenceEngine``): the reference's pipeline is kernel injection
(``_apply_injection_policy :401`` swapping HF modules for fused CUDA
blocks), AutoTP slicing, and CUDA-graph capture. The TPU-native design needs
none of those as subsystems: the model is already a functional graph, so
"injection" reduces to compiling it (XLA fuses), "AutoTP" to the tensor-axis
sharding rules (parallel/sharding.py), and "CUDA graphs" to jit. What
remains — and is implemented here — is the serving surface: cache-backed
``generate`` with greedy/temperature/top-k sampling, a jitted
prefill + scan-decode loop, and TP placement of the weights.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel import topology as topo
from ..parallel.sharding import ZeroShardingPlan
from ..utils.logging import logger
from .config import InferenceConfig


class InferenceEngine:
    """``deepspeed_tpu.init_inference(model, config)`` product.

    ``model``: a CausalLM (or registered model name); ``params`` may be
    passed or initialized fresh. ``forward``/``generate`` mirror the
    reference engine surface (inference/engine.py:577 forward, HF-style
    generate)."""

    def __init__(self, model, config=None, params=None, mesh=None, **kwargs):
        merged: Dict[str, Any] = {}
        if isinstance(config, dict):
            merged.update(config)
        merged.update(kwargs)
        self.config = config if isinstance(config, InferenceConfig) \
            else InferenceConfig(**merged)

        if isinstance(model, str):
            from ..models import build_model

            model = build_model(model)
        if model is None:
            # model inferred from an HF checkpoint directory's config.json
            from ..models import convert

            ckpt = self.config.checkpoint
            if not (ckpt and convert.is_hf_checkpoint(ckpt)):
                raise ValueError("init_inference needs a model or an HF "
                                 "checkpoint dir in config.checkpoint")
            model = convert.model_from_checkpoint(ckpt)
        self.module = model

        # topology: tp_size maps onto the tensor mesh axis
        if mesh is not None:
            self.topology = mesh if isinstance(mesh, topo.MeshTopology) \
                else topo.MeshTopology(mesh)
        elif topo.has_topology():
            self.topology = topo.get_topology()
        else:
            tp = self.config.tensor_parallel.tp_size
            self.topology = topo.MeshTopology.build(tensor=tp, data=-1)
        topo.set_topology(self.topology)

        dtype = {"fp32": jnp.float32, "fp16": jnp.float16, "bf16": jnp.bfloat16,
                 "float32": jnp.float32, "float16": jnp.float16,
                 "bfloat16": jnp.bfloat16}.get(str(self.config.dtype), jnp.bfloat16)
        if hasattr(self.module, "cfg") and self.module.cfg.dtype != dtype:
            # works for CausalLM and EncoderLM alike (same ctor contract)
            self.module = type(self.module)(
                dataclasses.replace(self.module.cfg, dtype=dtype))

        spec_tree = (self.module.param_specs()
                     if hasattr(self.module, "param_specs") else None)
        # zero_stage=0: params replicated except TP-sharded dims
        self.plan = ZeroShardingPlan(self.topology, 0, spec_tree)

        ckpt = self.config.checkpoint
        if params is None and ckpt is not None:
            from ..models import convert

            if convert.is_hf_checkpoint(ckpt):
                # TP-sharded load straight from HF files: each device's
                # shard is read from disk via the leaf plans (reference
                # module_inject/load_checkpoint.py role). Params stored at
                # the serving dtype (fp32 would double weight HBM).
                _, params = convert.load_hf_checkpoint(
                    ckpt, model=self.module, sharding_plan=self.plan,
                    param_dtype=dtype)
            else:
                # native universal-layout checkpoint
                from ..runtime.checkpointing import _load_tree

                shapes = jax.eval_shape(self.module.init, jax.random.PRNGKey(0))
                shardings = self.plan.params(shapes)
                params = _load_tree(shapes, shardings, ckpt)
        if params is None:
            shapes = jax.eval_shape(self.module.init, jax.random.PRNGKey(0))
            shardings = self.plan.params(shapes)
            params = jax.jit(self.module.init,
                             out_shardings=shardings)(jax.random.PRNGKey(0))
        else:
            shardings = self.plan.params(params)
            params = jax.tree.map(jax.device_put, params, shardings)
        if self.config.quant.enabled:
            # ZeRO-Inference weight-only quantization (inference/quantization
            # .py): int8/int4 params in HBM, dequant fused into consumers
            from .quantization import quantize_param_tree

            params = quantize_param_tree(params, bits=self.config.quant.bits)
        self.params = params
        self._is_encoder = not hasattr(self.module, "decode_step")
        if not self._is_encoder:
            self._decode_jit = jax.jit(self.module.decode_step)
            self._prefill_jit = jax.jit(self.module.prefill)
        else:
            # encoder serving (reference ds_bert.py role): one jitted
            # bidirectional forward, no cache/decode machinery
            self._encode_jit = jax.jit(self.module.apply)
            self._mlm_jit = (jax.jit(self.module.mlm_logits)
                             if self.module.cfg.with_mlm_head else None)
            # head-only jit: classify() reuses encode()'s compiled trunk
            self._cls_jit = (
                jax.jit(self.module._classifier_head)
                if getattr(self.module.cfg, "num_labels", 0) else None)
        self._gen_cache: Dict[tuple, Any] = {}

    # ------------------------------------------------------------------ API
    def forward(self, tokens, *args, **kwargs):
        """Plain forward → logits (reference engine forward). For encoder
        models this is ``encode`` (hidden states + pooled output)."""
        if self._is_encoder:
            return self.encode(tokens, *args, **kwargs)
        tokens = jnp.asarray(tokens)
        return self.module.apply(self.params, tokens)

    __call__ = forward

    def encode(self, input_ids, attention_mask=None, token_type_ids=None):
        """Encoder forward: ``(hidden [B,T,H], pooled [B,H] | None)`` —
        the BertModel serving surface (reference ds_bert.py)."""
        if not self._is_encoder:
            raise ValueError("encode() is for encoder models; use forward()")
        args = [jnp.asarray(np.asarray(input_ids), jnp.int32)]
        for a in (attention_mask, token_type_ids):
            args.append(None if a is None
                        else jnp.asarray(np.asarray(a), jnp.int32))
        return self._encode_jit(self.params, *args)

    def mlm(self, input_ids, attention_mask=None, token_type_ids=None):
        """Masked-LM logits [B, T, V] (BertForMaskedLM serving surface)."""
        if not self._is_encoder or self._mlm_jit is None:
            raise ValueError("model has no MLM head (not an encoder, or "
                             "with_mlm_head=False)")
        hidden, _ = self.encode(input_ids, attention_mask, token_type_ids)
        return self._mlm_jit(self.params, hidden)

    def classify(self, input_ids, attention_mask=None, token_type_ids=None):
        """Classification logits (task-checkpoint serving surface).
        Sequence heads (Bert/Roberta/DistilBertForSequenceClassification)
        → [B, num_labels]; token heads (ForTokenClassification) →
        [B, T, num_labels]; QA span heads (ForQuestionAnswering) →
        [B, T, 2] (split dim -1 into start/end logits). Reuses encode()'s
        compiled trunk + a jitted head (the mlm() pattern)."""
        if not self._is_encoder or self._cls_jit is None:
            raise ValueError("model has no classification head (not an "
                             "encoder, or num_labels=0)")
        hidden, pooled = self.encode(input_ids, attention_mask,
                                     token_type_ids)
        if self.module.cfg.cls_head in ("token", "qa"):
            # per-token heads consume the full hidden states
            return self._cls_jit(self.params, hidden, pooled)
        # sequence heads: pass only [CLS] — a full [B, T, H] hidden would
        # retrace the head jit per sequence length
        return self._cls_jit(self.params, hidden[:, :1], pooled)

    @staticmethod
    def _sample(logits, rng, temperature, top_k: int):
        """Greedy when traced ``temperature`` <= 0, else top-k/temperature
        sampling. ``temperature`` is a traced scalar (no recompile per
        setting); ``top_k`` must be static (it shapes the sort)."""
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
        if top_k > 0:
            kth = jnp.sort(scaled, axis=-1)[..., -top_k][..., None]
            scaled = jnp.where(scaled < kth, -1e30, scaled)
        sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
        return jnp.where(temperature <= 0, greedy, sampled)

    # Paged-cache block size for the decode loop. 128 = one full VMEM tile
    # of KV per (block, kv-head) slab in the Pallas kernel.
    DECODE_BLOCK = 128

    def _generate_fn(self, max_len: int, max_new: int, top_k: int,
                     eos_token_id=None, pad_token_id: int = 0):
        """Build (and cache) the jitted prefill+scan-decode program. Cache
        key is shapes + top_k + eos ids (each distinct eos set is its own
        compiled program); temperature stays a traced argument.

        The decode loop runs through the paged-attention kernel over a
        pool-layout cache (the contiguous cache is the trivial-block-table
        case), so per-token attention cost follows each sequence's live
        context length — never the [B, S] mask materialization of the old
        reference-attention path (reference decode hot loop:
        csrc/transformer/inference/csrc/pt_binding.cpp)."""
        if eos_token_id is not None and not isinstance(eos_token_id, int):
            # HF accepts lists of eos ids; normalize to a hashable tuple
            eos_token_id = tuple(int(e) for e in eos_token_id)
        key = (max_len, max_new, top_k, eos_token_id, pad_token_id)
        if key in self._gen_cache:
            return self._gen_cache[key]
        module = self.module

        def gen(params, tokens, prompt_len, rng, temperature):
            B, T = tokens.shape
            # fixed 128-slot blocks: the kernel's [bs, D] KV slab must stay
            # tile-aligned; a short sequence just under-fills its one block
            cache, tables = module.init_paged_cache(B, max_len,
                                                    self.DECODE_BLOCK)
            logits, cache = module.prefill_paged(params, tokens, prompt_len,
                                                 cache, tables)
            # logits at the last *real* prompt token (ragged prompts)
            last = jnp.take_along_axis(
                logits, (prompt_len - 1)[:, None, None], axis=1)[:, 0]

            def step(carry, i):
                cache, cur, rng, done = carry
                rng, sub = jax.random.split(rng)
                nxt = self._sample(cur, sub, temperature, top_k)
                if eos_token_id is not None:
                    # HF semantics: the EOS itself is emitted; every token
                    # after a finished sequence is pad. The scan keeps
                    # running (fixed shapes) but finished rows emit pad.
                    eos_ids = jnp.asarray(
                        eos_token_id if isinstance(eos_token_id, tuple)
                        else (eos_token_id,), jnp.int32)
                    nxt = jnp.where(done, pad_token_id, nxt)
                    done = done | jnp.isin(nxt, eos_ids)
                pos = prompt_len + i               # per-sequence positions
                logits, cache = module.decode_step_paged(
                    params, cache, tables, nxt, pos)
                return (cache, logits, rng, done), nxt

            (_, _, _, _), out_tokens = jax.lax.scan(
                step, (cache, last, rng, jnp.zeros((B,), bool)),
                jnp.arange(max_new))
            out_tokens = out_tokens.T              # [B, max_new]
            # place each sequence's new tokens right after its prompt
            out = jnp.full((B, T + max_new), pad_token_id, jnp.int32)
            out = out.at[:, :T].set(tokens)
            idx = prompt_len[:, None] + jnp.arange(max_new)[None, :]
            return jax.vmap(lambda row, i, v: row.at[i].set(v))(
                out, idx, out_tokens)

        fn = jax.jit(gen)
        self._gen_cache[key] = fn
        return fn

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0, rng=None,
                 prompt_len=None, eos_token_id=None, pad_token_id: int = 0,
                 **kwargs):
        """HF-style generate with ragged-prompt support.

        ``input_ids``: [B, T] array, or a list of per-sequence token
        sequences (ragged — right-padded internally, like the reference v1
        engine's variable-length serving). ``prompt_len`` [B] optionally
        marks the real length of each row of a padded [B, T] array.
        ``eos_token_id``: sequences that emit it produce ``pad_token_id``
        for the remaining steps (HF early-stop semantics under fixed
        shapes). Returns [B, T + n] with each sequence's new tokens placed
        directly after its prompt and ``pad_token_id`` (default 0 — pass
        the tokenizer's id when 0 is a real token) beyond
        ``prompt_len[b] + n``."""
        if self._is_encoder:
            raise ValueError("generate() needs a causal LM; encoder models "
                             "serve via encode()/mlm()")
        if isinstance(input_ids, (list, tuple)) and input_ids \
                and isinstance(input_ids[0], (list, tuple, np.ndarray)):
            lens = [len(p) for p in input_ids]
            T = max(lens)
            padded = np.full((len(input_ids), T), pad_token_id, np.int32)
            for i, p in enumerate(input_ids):
                padded[i, :len(p)] = p
            tokens = jnp.asarray(padded)
            prompt_len = jnp.asarray(lens, jnp.int32)
        else:
            tokens = jnp.asarray(np.asarray(input_ids), jnp.int32)
        B, T = tokens.shape
        if prompt_len is None:
            prompt_len = jnp.full((B,), T, jnp.int32)
        else:
            prompt_len = jnp.asarray(np.asarray(prompt_len), jnp.int32)
            pl = np.asarray(prompt_len)
            if pl.shape != (B,) or (pl < 1).any() or (pl > T).any():
                raise ValueError(
                    f"prompt_len must be [B]={B} values in [1, {T}]; got "
                    f"shape {pl.shape}, range [{pl.min()}, {pl.max()}]")
            # re-pad past each prompt so the region beyond prompt_len+n
            # is deterministic regardless of the caller's padding
            tokens = jnp.where(jnp.arange(T)[None, :] < prompt_len[:, None],
                               tokens, pad_token_id)
        ctx = self.module.cfg.max_seq_len
        if T >= ctx:
            raise ValueError(f"prompt length {T} >= max_seq_len {ctx}")
        max_new = min(max_new_tokens, ctx - T)
        if max_new < max_new_tokens:
            logger.warning(
                f"max_new_tokens clamped {max_new_tokens} → {max_new} "
                f"(context window {ctx}, prompt {T})")
        max_len = T + max_new
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        fn = self._generate_fn(max_len, max_new, top_k, eos_token_id,
                               int(pad_token_id))
        return fn(self.params, tokens, prompt_len, rng,
                  jnp.asarray(temperature, jnp.float32))

    # parity helpers --------------------------------------------------------
    def profile_model_time(self, use_cuda_events: bool = False):
        logger.warning("profile_model_time: use jax.profiler traces on TPU")

    def load_checkpoint(self, path):
        from ..runtime.checkpointing import _load_tree

        shardings = self.plan.params(self.params)
        self.params = _load_tree(self.params, shardings, path)
        return path
