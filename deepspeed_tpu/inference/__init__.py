from .engine import InferenceEngine  # noqa: F401
from .config import InferenceConfig  # noqa: F401
