"""int8/fp8 weight serving for the v2 ragged engine.

Counterpart of the reference's ZeRO-Inference weight-only quantization
(``deepspeed/inference/quantization/quantize.py`` /
``layers.py`` dequant-on-the-fly linear) and the FastGen fp8 path,
rebuilt on the TPU-native blockwise kernel set (``ops/quantizer.py``).
On memory-bound decode the weight stream — not FLOPs — is the wall, and
weight bytes are what cap replicas per host: quantizing the CausalLM
param tree to int8 (or float8_e4m3fn) once at engine build cuts the
resident param bytes ~3.9x vs fp32 (1 byte + 4/B scale bytes per
element) and the per-step HBM weight traffic with it (PAPERS.md: arxiv
2605.25645 low-precision serving; arxiv 2506.17615 quantize-at-the-
boundary idiom).

Representation: each quantized matmul weight ``w[..., in, out]`` becomes
a two-leaf pytree node ``{"qw": int8/fp8 [..., in, out], "qs": f32
[..., in, out/B]}`` — symmetric blockwise scales along the output dim
(``ops/quantizer.py`` format), stored alongside the payload. The node
shape is what ``models/transformer._linear`` dispatches on: a dict
weight routes through ``ops/quantizer.quantized_matmul``
(dequantize-in-kernel on the Pallas path, fused dequant-then-dot on the
XLA fallback, fp32 accumulation), an array weight takes the historical
``x @ w`` byte for byte — so ``forward``/``forward_verify``/prefill all
ride the same quantized tree with no forward-path forks.

Only the dense matmul whitelist quantizes: attention projections
(``wq``/``wk``/``wv``/``wo``), the dense MLP (``w_in``/``w_out``/
``w_gate``), and the untied ``lm_head``. Embeddings (a gather, not a
matmul), norms, biases, and MoE expert stacks (they run through the
grouped einsum path, not ``_linear``) never quantize; ``skip`` prunes
the whitelist further by name.

Under TP the scale planes shard with their weight shards: the per-leaf
block size is chosen to divide the per-shard output width (so no scale
group straddles a shard boundary — quantize-then-shard equals
shard-then-quantize), and :func:`expand_spec_tree` mirrors each
quantized leaf's logical-axis spec onto both ``qw`` and ``qs`` so
``ZeroShardingPlan`` places them together (the PR 6 KV scale-plane
treatment applied to weights; verified in the multichip dryrun).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ...ops.quantizer import _HAS_FP8, choose_block, quantize_blockwise

#: weight representations this module encodes (the config surface
#: rejects anything else up front)
WEIGHT_SUPPORTED_DTYPES = ("int8", "fp8_e4m3")

#: leaf names that may quantize — everything else in the param tree is
#: structurally not a dense matmul weight (embeddings, norms, biases)
QUANTIZABLE_LAYER_LEAVES = ("wq", "wk", "wv", "wo",
                            "w_in", "w_out", "w_gate")

#: default ``skip`` list: named subtrees/leaves excluded even though a
#: matmul could run from them — embeddings (tied unembed reads ``wte``
#: as a gather + transpose matmul and must stay exact) and the final
#: norm are listed for config self-documentation; both are *also*
#: structurally unquantizable here.
DEFAULT_SKIP = ("embed", "final_norm")


def validate_weight_quant(dtype: str, block: int) -> None:
    """Reject configurations this implementation does not encode."""
    if dtype not in WEIGHT_SUPPORTED_DTYPES:
        raise ValueError(f"weight_quant.dtype {dtype!r} not supported "
                         f"(implemented: {WEIGHT_SUPPORTED_DTYPES})")
    if dtype == "fp8_e4m3" and not _HAS_FP8:
        raise ValueError("weight_quant.dtype 'fp8_e4m3' needs a JAX "
                         "build with float8_e4m3fn")
    if int(block) < 1:
        raise ValueError(f"weight_quant.block must be >= 1, got {block}")


def is_quantized(leaf) -> bool:
    """True for the two-leaf quantized-weight node this module emits."""
    return (isinstance(leaf, dict) and set(leaf) == {"qw", "qs"})


def _eff_block(out_dim: int, want: int, tp: int) -> int:
    """Block size for one leaf: the largest divisor of the (per-shard)
    output width <= ``want``, so scale groups tile the dim and — under
    TP — never straddle a shard boundary."""
    if tp > 1 and out_dim % tp == 0:
        return choose_block(out_dim // tp, want)
    return choose_block(out_dim, want)


def quantize_weights(model_cfg, params, dtype: str = "int8",
                     block: int = 128, skip: Sequence[str] = (),
                     tp: int = 1) -> Tuple[dict, Dict[str, int]]:
    """Quantize a CausalLM param tree once (the engine-build path).

    Returns ``(new_params, stats)`` where quantized leaves are
    ``{"qw", "qs"}`` nodes and everything else is the original array
    (same objects — no copy). ``stats`` carries the byte accounting the
    serving gauges and bench phase publish."""
    validate_weight_quant(dtype, block)
    skip = set(skip) | set(DEFAULT_SKIP)
    moe = getattr(model_cfg, "moe_num_experts", 0) > 0

    def quant_leaf(name: str, w):
        eff = _eff_block(int(w.shape[-1]), int(block), int(tp))
        q, s = quantize_blockwise(w, block=eff, dtype=dtype)
        return {"qw": q, "qs": s}

    out = dict(params)
    layers = dict(params["layers"])
    for name in QUANTIZABLE_LAYER_LEAVES:
        if name not in layers or name in skip:
            continue
        if moe and name in ("w_in", "w_out", "w_gate"):
            continue            # expert stacks ride the grouped path
        layers[name] = quant_leaf(name, layers[name])
    out["layers"] = layers
    if "lm_head" in params and "lm_head" not in skip:
        head = dict(params["lm_head"])
        head["w"] = quant_leaf("lm_head.w", head["w"])
        out["lm_head"] = head
    return out, param_stats(out, dtype=dtype, block=int(block))


def _leaf_bytes(leaf) -> int:
    n = 1
    for d in leaf.shape:
        n *= int(d)
    return int(jnp.dtype(leaf.dtype).itemsize) * n


def param_stats(params, dtype: str = "", block: int = 0) -> Dict[str, int]:
    """Byte accounting of a (possibly quantized) param tree:
    ``param_bytes_total`` = resident bytes of every leaf (scale planes
    included), ``param_bytes_quantized`` = bytes of the quantized nodes
    (payload + scales), ``params_quantized`` = node count. The shape the
    ``param_bytes_total``/``param_bytes_quantized`` serving gauges and
    the bench phase stamps read."""
    total = quantized = nodes = 0
    for leaf in jax.tree.leaves(params, is_leaf=is_quantized):
        if is_quantized(leaf):
            b = _leaf_bytes(leaf["qw"]) + _leaf_bytes(leaf["qs"])
            quantized += b
            total += b
            nodes += 1
        else:
            total += _leaf_bytes(leaf)
    return {"param_bytes_total": total,
            "param_bytes_quantized": quantized,
            "params_quantized": nodes,
            "weight_quant_dtype": dtype,
            "weight_quant_block": block}


def expand_spec_tree(spec_tree, params):
    """Mirror a ``param_specs()`` logical-axis tree onto a quantized
    param tree: where ``params`` holds a ``{"qw", "qs"}`` node the spec
    leaf is duplicated for both members — ``qs``'s dims correspond 1:1
    to the weight's (last dim compressed by the block factor), and
    ``shard_spec_for`` already drops tensor assignments that don't
    divide, so a non-tileable scale dim degrades to replication (always
    correct: values are computed before placement)."""
    def walk(spec, par):
        if is_quantized(par):
            return {"qw": spec, "qs": spec}
        if isinstance(par, dict):
            return {k: walk(spec[k] if isinstance(spec, dict) else spec,
                            par[k])
                    for k in par}
        return spec

    if spec_tree is None:
        return None
    return walk(spec_tree, params)
