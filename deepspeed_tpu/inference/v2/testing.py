"""Shared correctness helpers for the v2 ragged engine.

One home for the greedy-token-parity machinery used by
``tests/test_prefix_cache.py``, ``tests/test_spec_decode.py``, and
``bench.py``'s shared-prefix and speculative phases: every engine-level
optimization here (prefix caching, speculative decoding) carries the hard
guarantee that greedy token streams are byte-identical with the feature on
and off — this module is the single definition of "run these prompts
greedily and give me the streams".
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .scheduler import ContinuousBatchingScheduler


def greedy_generate(engine=None, prompts: Sequence[Sequence[int]] = (),
                    uid_base: int = 0, max_new_tokens: int = 8,
                    eos_token_id: Optional[int] = None,
                    scheduler: Optional[ContinuousBatchingScheduler] = None,
                    sequential: bool = True,
                    **scheduler_kwargs) -> List[List[int]]:
    """Greedy-decode ``prompts`` through a ContinuousBatchingScheduler and
    return one generated-token list per prompt.

    ``sequential=True`` (default) runs each prompt to completion before
    submitting the next — the deterministic reference order parity checks
    compare against (it also warms prefix/speculation state in submission
    order). ``sequential=False`` submits everything up front and lets
    continuous batching interleave — same tokens, concurrent schedule.

    Pass ``scheduler`` to reuse one (e.g. to keep its engine's caches warm
    across passes), or ``scheduler_kwargs`` (``proposer=``,
    ``max_draft_tokens=``...) to build one on ``engine``.
    """
    if scheduler is None:
        if engine is None:
            raise ValueError("greedy_generate needs an engine or scheduler")
        scheduler = ContinuousBatchingScheduler(engine, **scheduler_kwargs)
    uids = []
    for i, p in enumerate(prompts):
        uid = uid_base + i
        uids.append(uid)
        scheduler.submit(uid, list(p), max_new_tokens=max_new_tokens,
                         eos_token_id=eos_token_id)
        if sequential:
            scheduler.run_to_completion()
    if not sequential:
        scheduler.run_to_completion()
    return [scheduler.finished[uid].generated for uid in uids]


def assert_greedy_parity(reference: Sequence[List[int]],
                         candidate: Sequence[List[int]],
                         label: str = "feature") -> None:
    """Byte-identical-stream check with a diagnostic that names the first
    diverging request and position (raw list comparison buries both)."""
    assert len(reference) == len(candidate), (
        f"{label}: {len(candidate)} streams vs {len(reference)} expected")
    for r, (ref, got) in enumerate(zip(reference, candidate)):
        if list(ref) == list(got):
            continue
        pos = next((j for j, (a, b) in enumerate(zip(ref, got)) if a != b),
                   min(len(ref), len(got)))
        raise AssertionError(
            f"greedy parity broken by {label}: request {r} diverges at "
            f"token {pos}: expected {list(ref)[max(0, pos - 2):pos + 3]}, "
            f"got {list(got)[max(0, pos - 2):pos + 3]} "
            f"(lens {len(ref)} vs {len(got)})")


def spec_summary(stats: Dict[str, int]) -> Dict[str, float]:
    """Derived speculative-decoding numbers from
    ``ContinuousBatchingScheduler.spec_stats()`` counters."""
    proposed = stats.get("proposed", 0)
    rows = stats.get("decode_rows", 0)
    return {
        "acceptance_rate": (stats.get("accepted", 0) / proposed
                            if proposed else 0.0),
        "tokens_per_forward": (stats.get("emitted", 0) / rows
                               if rows else 0.0),
    }
