"""int8/fp8 KV-cache quantization for the paged ragged engine.

The paged KV pool (``ragged/manager.py``: ``[L, NB, KH, bs, D]``) is the
HBM tensor that caps servable concurrency per chip — at production batch
sizes TPU serving is capacity-bound, not FLOPs-bound (PAPERS.md: arxiv
2605.25645). Storing K/V as **symmetric int8 with one scale per
(layer, block, kv-head)** halves the per-block bytes vs bf16, so a fixed
HBM byte budget buys ~2x the blocks → ~2x the concurrent sequences
(docs/SERVING.md "KV quantization"). Scales live in dense planes
``[L, NB, KH]`` alongside the pools, indexed by the same pool block id —
a prefix-cache-shared block therefore shares its scale for free.

Write path (``paged_model.py``): a ragged chunk's KV lands in at most
``TB = ceil((C-1)/bs) + 2`` pool blocks per sequence, a *static* bound —
so the quantized write is a read-modify-write of only the touched blocks:

1. gather the touched int8 blocks and their scales, dequantize;
2. zero stale slots (positions >= the sequence's context length — content
   from freed tenants or speculative rollback must not leak into scales);
3. scatter the new bf16 K/V into their (block, slot) positions;
4. re-quantize the whole touched block at a **monotone** scale:
   ``max(amax/127, previous scale)`` for blocks that already hold this
   sequence's tokens, plain ``amax/127`` for freshly allocated blocks
   (which is how a freed block's stale scale is invalidated — a new
   tenant's first write ignores the plane entry, no device traffic).

The monotone rule makes steady-state decode *exact*: while the scale is
unchanged, dequantize→requantize round-trips int8 values bit-for-bit
(``round(q·s/s) = q``), so a block is only ever re-coded when a genuinely
larger activation arrives. After a ``trim_sequence`` rollback the scale
may stay inflated by trimmed drafts — re-quantization on the next write
is correct but not byte-identical to a never-drafted run, which is why
speculation under kv_quant is bounded-divergent rather than byte-lossless
(docs/SERVING.md "KV quantization" interaction matrix).

Read path: the scale planes ride into ``ops/paged_attention.py`` as extra
operands (``k_scale``/``v_scale`` ``[NB, KH]`` per layer); the Pallas
kernel dequantizes each streamed block in VMEM with its scalar scale, the
XLA fallback multiplies the gathered context. TP serving shards the
planes over the kv-head axis exactly like the pools.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from ...ops.quantizer import _HAS_FP8, FP8_MAX

# Symmetric int8: values in [-127, 127] (−128 unused, keeps the code
# symmetric around zero) with scale = amax / 127.
Q_MAX = 127.0
# Floor for scales so an all-zero block can't divide by zero; far below
# any real activation scale.
SCALE_EPS = 1e-8

#: quantized KV representations: int8 (PR 6) and float8_e4m3fn on the
#: reserved ``kv_quant.dtype`` surface — same pool/scale machinery, the
#: pool dtype and the qmax the scale maps amax onto are the only
#: differences (scale = amax / 448 spreads each block over e4m3's
#: dynamic range; the floating mantissa keeps small values' relative
#: precision where int8 spends its codes uniformly).
SUPPORTED_DTYPES = ("int8", "fp8_e4m3")
SUPPORTED_GRANULARITIES = ("block",)


def pool_dtype(dtype: str):
    """The jnp dtype KV pool slabs are stored as for a quantized
    representation name (both are 1 byte/element — the 2x/4x byte cut
    vs bf16/fp32 is identical; fp8 trades int8's uniform code spacing
    for floating relative precision)."""
    if dtype == "fp8_e4m3":
        return jnp.float8_e4m3fn
    return jnp.int8


def qmax_of(dtype) -> float:
    """Symmetric range limit the per-block scale maps amax onto, from a
    representation name or a pool dtype."""
    if "float8" in str(dtype) or str(dtype) == "fp8_e4m3":
        return FP8_MAX
    return Q_MAX


def validate_kv_quant(dtype: str, scale_granularity: str) -> None:
    """Reject config combinations this implementation does not encode:
    ``int8``/``fp8_e4m3`` x ``block`` (per block x kv-head x layer) are
    real; coarser scale granularities remain reserved."""
    if dtype not in SUPPORTED_DTYPES:
        raise ValueError(f"kv_quant.dtype {dtype!r} not supported "
                         f"(implemented: {SUPPORTED_DTYPES})")
    if dtype == "fp8_e4m3" and not _HAS_FP8:
        raise ValueError("kv_quant.dtype 'fp8_e4m3' needs a JAX build "
                         "with float8_e4m3fn")
    if scale_granularity not in SUPPORTED_GRANULARITIES:
        raise ValueError(
            f"kv_quant.scale_granularity {scale_granularity!r} not "
            f"supported (implemented: {SUPPORTED_GRANULARITIES})")


def kv_bytes_per_block(model_cfg, block_size: int, quant: bool,
                       dtype=None) -> int:
    """HBM bytes one KV pool block costs across all layers: K and V slabs
    ``[L, KH, bs, D]`` at the pool dtype, plus (quantized) two f32 scale
    entries per (layer, kv-head). The unit of the fixed-byte-budget
    comparison: at equal ``num_blocks * kv_bytes_per_block`` an int8 pool
    holds ~2x the bf16 blocks."""
    slab = (model_cfg.num_layers * model_cfg.kv_heads * block_size
            * model_cfg.head_dim)
    if quant:
        return 2 * slab * 1 + 2 * model_cfg.num_layers * model_cfg.kv_heads * 4
    itemsize = jnp.dtype(dtype or model_cfg.dtype).itemsize
    return 2 * slab * itemsize


def blocks_for_budget(budget_bytes: int, model_cfg, block_size: int,
                      quant: bool, dtype=None) -> int:
    """How many pool blocks a KV byte budget buys at this representation
    (bench's concurrency-at-fixed-HBM comparison; at least 1)."""
    return max(1, int(budget_bytes)
               // kv_bytes_per_block(model_cfg, block_size, quant, dtype))


def touched_block_plan(block_tables, start_pos, n_tokens, chunk: int,
                       block_size: int, num_blocks: int) -> Dict[str, object]:
    """Static-shape plan of the pool blocks this step's KV writes touch.

    A row writing ``n_tokens`` new tokens from ``start_pos`` lands in the
    logical blocks ``start_pos//bs .. (start_pos+n_tokens-1)//bs`` — at
    most ``TB = (C-1)//bs + 2`` of them for a chunk width C, regardless of
    alignment. The plan is layer-invariant (same coordinates for every
    layer's pool), so ``paged_model`` computes it once per forward and
    closes over it in the scanned layer body.

    Ownership invariant (why the full-block scatter back is safe): the
    touched window starts at ``start_pos//bs``, and every block at or past
    that index belongs exclusively to the writing sequence — prefix-cache
    sharing only ever covers *full* blocks strictly below the matched
    length (block-aligned), trims into indexed blocks are refused, and
    padding rows (``n_tokens == 0``) produce an empty window.
    """
    N, MB = block_tables.shape
    bs = block_size
    TB = (chunk - 1) // bs + 2
    ctx_len = start_pos + n_tokens                                   # [N]
    first_blk = start_pos // bs                                      # [N]
    tidx = first_blk[:, None] + jnp.arange(TB)[None, :]              # [N, TB]
    ids = jnp.take_along_axis(block_tables,
                              jnp.clip(tidx, 0, MB - 1), axis=1)     # [N, TB]
    touched = (tidx * bs < ctx_len[:, None]) & (tidx < MB) & (ids >= 0)
    # gather side clamps (garbage rows are masked below); scatter side
    # uses the positive out-of-range sentinel NB, which mode="drop"
    # really drops (-1 would wrap — same trick as the unquantized write)
    gather_ids = jnp.where(touched, jnp.clip(ids, 0, num_blocks - 1), 0)
    scatter_ids = jnp.where(touched, ids, num_blocks)
    # live KV slots of each touched block: global position < ctx_len.
    # Slots past that hold stale content (freed tenant / trimmed drafts)
    # and are zeroed so they can neither inflate the scale nor survive
    # the re-quantized write-back.
    slot_pos = tidx[:, :, None] * bs + jnp.arange(bs)[None, None, :]
    live_slots = (slot_pos < ctx_len[:, None, None]) & touched[:, :, None]
    # per-token scatter coordinates into the gathered [N, TB, ...] view
    positions = start_pos[:, None] + jnp.arange(chunk)[None, :]      # [N, C]
    valid = jnp.arange(chunk)[None, :] < n_tokens[:, None]
    t_tok = positions // bs - first_blk[:, None]                     # [N, C]
    n_flat = jnp.repeat(jnp.arange(N), chunk)
    t_flat = jnp.where(valid, t_tok, TB).reshape(-1)                 # TB drops
    slot_flat = (positions % bs).reshape(-1)
    # blocks already holding this sequence's quantized tokens keep a
    # monotone scale; a freshly allocated block ignores the stale plane
    # entry of its previous tenant (the "scale invalidation on free")
    has_prior = (tidx * bs < start_pos[:, None]) & touched
    return {"gather_ids": gather_ids, "scatter_ids": scatter_ids,
            "live_slots": live_slots, "has_prior": has_prior,
            "n_flat": n_flat, "t_flat": t_flat, "slot_flat": slot_flat}


def quantized_block_write(pool, scale, new_vals, plan):
    """Merge new K or V rows into a quantized pool (the quantized
    counterpart of the reference ``linear_blocked_kv_rotary`` scatter).

    ``pool`` [NB, KH, bs, D] int8 or float8_e4m3fn — the representation
    is derived from ``pool.dtype``, so the paged forward needs no extra
    plumbing; ``scale`` [NB, KH] f32; ``new_vals`` [N*C, KH, D] (row
    order matches ``plan``'s flattened token coordinates). Returns the
    updated (pool, scale). The monotone-scale rule keeps steady-state
    decode exact for both representations: while the scale is unchanged,
    dequantize→requantize round-trips the stored code bit-for-bit
    (int8: ``round(q·s/s) = q``; fp8: the nearest-e4m3 cast of
    ``q·s/s`` is ``q``).
    """
    qmax = qmax_of(pool.dtype)
    deq = (pool[plan["gather_ids"]].astype(jnp.float32)
           * scale[plan["gather_ids"]][:, :, :, None, None])
    deq = jnp.where(plan["live_slots"][:, :, None, :, None], deq, 0.0)
    deq = deq.at[plan["n_flat"], plan["t_flat"], :, plan["slot_flat"], :].set(
        new_vals.astype(jnp.float32), mode="drop")
    amax = jnp.max(jnp.abs(deq), axis=(3, 4))                    # [N, TB, KH]
    prior = jnp.where(plan["has_prior"][:, :, None],
                      scale[plan["gather_ids"]], 0.0)
    new_scale = jnp.maximum(jnp.maximum(amax / qmax, prior), SCALE_EPS)
    scaled = deq / new_scale[:, :, :, None, None]
    if pool.dtype == jnp.int8:
        q = jnp.clip(jnp.round(scaled), -qmax, qmax).astype(jnp.int8)
    else:
        # float8: the cast rounds to nearest representable — no integer
        # rounding step, and the clip keeps inf out of the pool
        q = jnp.clip(scaled, -qmax, qmax).astype(pool.dtype)
    pool = pool.at[plan["scatter_ids"]].set(q, mode="drop")
    scale = scale.at[plan["scatter_ids"]].set(new_scale, mode="drop")
    return pool, scale
