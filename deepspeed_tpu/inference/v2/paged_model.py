"""Paged ragged-batch forward over a CausalLM.

Counterpart of the reference FastGen model stack
(``inference/v2/model_implementations/inference_transformer_base.py:616``
with the ragged kernel suite: ``linear_blocked_kv_rotary`` KV write,
``blocked_flash`` attention over atoms, ``logits_gather``). One jitted
function processes a mixed prefill/decode ragged batch with static shapes:

- tokens [N, C] padded chunks, per-seq ``start_pos`` (tokens already
  cached) and ``n_tokens`` (valid width) — Dynamic SplitFuse feeds both
  prompt chunks and single decode tokens through this same path;
- paged KV cache [L, NB, KH, bs, D] with per-seq block tables; writes are
  a drop-mode scatter at (block, slot), reads go through the Pallas
  paged-attention kernel (``ops/paged_attention.py``) which walks each
  sequence's block table directly — no dense [N, max_ctx, KH, D] gather,
  no GQA ``jnp.repeat`` (the XLA gather formulation remains as the
  off-TPU fallback inside ``paged_attention``);
- returns logits only at each sequence's last valid token (logits_gather);
- weight serving (``weight_quant.py``): when the param tree holds
  blockwise-quantized ``{"qw", "qs"}`` nodes, every projection/MLP/unembed
  matmul here runs straight from the int8/fp8 representation through
  ``models/transformer._linear``'s structural dispatch →
  ``ops/quantizer.quantized_matmul`` (dequantize-in-kernel on the Pallas
  path, fused dequant-then-dot on XLA, fp32 accumulation) —
  ``forward``/``forward_verify``/prefill all ride the same quantized tree,
  and an unquantized tree compiles the historical program byte for byte.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax

from ...models.transformer import (CausalLM, _linear, _norm, alibi_slopes,
                                   apply_rope, rope_table)


class PagedCausalLM:
    """Wraps a CausalLM's weights with a paged ragged forward.

    ``mesh``: optional ``jax.sharding.Mesh`` with a ``tensor`` axis — TP
    serving (reference inference/v2/model_implementations/sharding/
    qkv.py:166 head split). Projections/norms partition via GSPMD from the
    param shardings; the Pallas paged-attention kernel — which GSPMD cannot
    partition — runs inside ``shard_map`` over the tensor axis on each
    device's local heads (attention is embarrassingly parallel over heads).
    """

    def __init__(self, model: CausalLM, block_size: int,
                 max_blocks_per_seq: int, mesh=None,
                 attn_impl: str = None):
        self.model = model
        self.cfg = model.cfg
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.mesh = mesh
        self.tp = int(mesh.shape["tensor"]) if mesh is not None else 1
        if self.tp > 1:
            if self.cfg.kv_heads % self.tp or self.cfg.num_heads % self.tp:
                raise ValueError(
                    f"TP serving needs heads ({self.cfg.num_heads}) and "
                    f"kv_heads ({self.cfg.kv_heads}) divisible by the "
                    f"tensor axis ({self.tp})")
        # attention implementation via the module registry heuristics
        # (modules.py; reference heuristics.py:179) — overridable by name
        from .modules import instantiate_attn

        self._attn_raw = instantiate_attn(self.cfg, name=attn_impl)
        self.forward = jax.jit(self._forward)
        # trailing-positions logits variant for speculative verification
        # (spec/): same forward, but the unembed runs over each row's LAST
        # ``verify_width`` positions (right-aligned) so the target's
        # greedy choice is known at every draft offset — without
        # materializing [N, C, vocab] when only K+1 << C positions matter.
        # A separate compiled program per width bucket — the default path
        # stays byte-identical.
        self.forward_verify = jax.jit(self._forward,
                                      static_argnames=("verify_width",))

    def _attend(self, q, kc, vc, block_tables, start_pos, n_tokens, slopes,
                window=0, k_scale=None, v_scale=None):
        """Paged attention, shard_mapped over the tensor axis when TP>1.
        ``k_scale``/``v_scale`` [NB, KH]: per-(block, kv-head) dequant
        scales for int8 pools (kv_quant.py) — sharded over the kv-head
        axis exactly like the pools, so TP serving is preserved."""
        sm_scale = self.cfg.attn_scale
        quant_kw = ({} if k_scale is None
                    else {"k_scale": k_scale, "v_scale": v_scale})
        if self.tp == 1:
            return self._attn_raw(q, kc, vc, block_tables, start_pos,
                                  n_tokens, alibi_slopes=slopes,
                                  window=window, sm_scale=sm_scale,
                                  **quant_kw)
        from jax.sharding import PartitionSpec as P
        from ...compat import shard_map

        q_spec = P(None, None, "tensor", None)        # [N, C, H, D]
        kv_spec = P(None, "tensor", None, None)       # [NB, KH, bs, D]
        rep = P()

        operands = [q, kc, vc, block_tables, start_pos, n_tokens]
        in_specs = [q_spec, kv_spec, kv_spec, rep, rep, rep]
        if slopes is not None:
            operands.append(slopes)
            in_specs.append(P("tensor"))
        if k_scale is not None:
            operands += [k_scale, v_scale]
            in_specs += [P(None, "tensor"), P(None, "tensor")]  # [NB, KH]

        attn = self._attn_raw
        has_slopes = slopes is not None
        has_scales = k_scale is not None

        def local(q, kc, vc, tbl, sp, nt, *rest):
            i = 0
            sl = None
            if has_slopes:
                sl, i = rest[0], 1
            kw = ({"k_scale": rest[i], "v_scale": rest[i + 1]}
                  if has_scales else {})
            return attn(q, kc, vc, tbl, sp, nt, alibi_slopes=sl,
                        window=window, sm_scale=sm_scale, **kw)

        return shard_map(
            local, mesh=self.mesh, in_specs=tuple(in_specs),
            out_specs=q_spec, check_vma=False)(*operands)

    # ------------------------------------------------------------------
    def _forward(self, params, kv_cache, tokens, start_pos, n_tokens,
                 block_tables, verify_width: int = 0):
        """tokens [N, C]; start_pos/n_tokens [N]; block_tables [N, MB];
        kv_cache {k,v}: [L, NB, KH, bs, D] — plus {k_scale,v_scale}
        [L, NB, KH] when the pools are int8-quantized (kv_quant.py); the
        pytree structure selects the compiled program, so the
        unquantized trace is untouched.

        Returns (last_logits [N, V], new_kv_cache) — or, with static
        ``verify_width`` W > 0, (logits [N, W, V], new_kv_cache) holding
        each row's last W valid positions *right-aligned*: position
        ``W-1`` is the row's last valid token (what the default path
        gathers), ``W-1-j`` is j tokens earlier; rows shorter than W
        duplicate their first position in the left padding.
        """
        cfg = self.cfg
        N, C = tokens.shape
        bs = self.block_size
        NB = kv_cache["k"].shape[1]
        MB = block_tables.shape[1]
        dt = cfg.dtype

        x = params["embed"]["wte"][tokens].astype(dt)          # [N, C, H]
        if cfg.embedding_layernorm:
            x = _norm(x, params["embed"]["ln_w"],
                      params["embed"].get("ln_b"), cfg.norm, cfg.norm_eps)
        positions = start_pos[:, None] + jnp.arange(C)[None, :]  # [N, C]
        slopes = None
        if cfg.position == "rope":
            cos_full, sin_full = rope_table(cfg.max_seq_len, cfg.rot_dim,
                                            cfg.rope_theta)
            cos = cos_full[positions]                           # [N, C, R/2]
            sin = sin_full[positions]
        elif cfg.position == "alibi":
            # bias applied inside the paged kernel (slope · kv_position)
            slopes = alibi_slopes(cfg.num_heads)
            cos = sin = None
        else:
            x = x + params["embed"]["wpe"][positions].astype(dt)
            cos = sin = None

        valid = jnp.arange(C)[None, :] < n_tokens[:, None]      # [N, C]

        # scatter coordinates for KV writes: (pool block, slot-in-block)
        blk_idx = positions // bs                               # [N, C]
        blk_off = positions % bs
        blk_ids = jnp.take_along_axis(
            block_tables, jnp.clip(blk_idx, 0, MB - 1), axis=1)  # [N, C]
        # invalid tokens → sentinel NB: a *positive* out-of-range id, which
        # mode="drop" really drops (-1 would wrap to pool block NB-1 — JAX
        # normalizes negative scatter indices before the bounds check)
        write_blk = jnp.where(valid & (blk_ids >= 0), blk_ids, NB).reshape(-1)
        write_off = blk_off.reshape(-1)

        # int8 KV quantization (kv_quant.py, docs/SERVING.md "KV
        # quantization"): detected from the cache pytree so the disabled
        # path below is byte-for-byte the historical program. The touched-
        # block plan is layer-invariant — computed once, closed over by
        # every scanned layer body.
        quant = "k_scale" in kv_cache
        if quant:
            from .kv_quant import quantized_block_write, touched_block_plan

            kv_plan = touched_block_plan(block_tables, start_pos, n_tokens,
                                         C, bs, NB)

        def rope_q(q):
            if cfg.position != "rope":
                return q
            # per-(seq, pos) tables are exactly apply_rope's ndim-3 form
            # (rotate_half or GPT-J interleaved, partial rotary included)
            return apply_rope(q, cos, sin, cfg.rope_interleaved)

        def block_for(window):
            def block(x, xs):
                if quant:
                    lp, kc, vc, ks, vs = xs   # + scale planes [NB, KH]
                else:
                    lp, kc, vc = xs           # kc/vc [NB, KH, bs, D]
                    ks = vs = None
                h1 = _norm(x, lp["attn_norm_w"], lp.get("attn_norm_b"),
                           cfg.norm, cfg.norm_eps)
                nh, kvh, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
                q = rope_q(_linear(h1, lp["wq"], lp.get("wq_b"),
                                   dt).reshape(N, C, nh, hd))
                k = rope_q(_linear(h1, lp["wk"], lp.get("wk_b"),
                                   dt).reshape(N, C, kvh, hd))
                v = _linear(h1, lp["wv"], lp.get("wv_b"),
                            dt).reshape(N, C, kvh, hd)

                if quant:
                    # quantized paged KV write: read-modify-write of only
                    # the touched blocks — dequantize, merge the new
                    # tokens, re-quantize at the monotone per-block scale
                    kc, ks = quantized_block_write(kc, ks,
                                                   k.reshape(-1, kvh, hd),
                                                   kv_plan)
                    vc, vs = quantized_block_write(vc, vs,
                                                   v.reshape(-1, kvh, hd),
                                                   kv_plan)
                else:
                    # paged KV write (reference linear_blocked_kv_rotary
                    # kernel): token t lands at kc[block(t), :, slot(t), :]
                    kc = kc.at[write_blk, :, write_off, :].set(
                        k.reshape(-1, kvh, hd), mode="drop")
                    vc = vc.at[write_blk, :, write_off, :].set(
                        v.reshape(-1, kvh, hd), mode="drop")

                # paged read: Pallas block-table walk (reference
                # blocked_flash; Mistral sliding window clamps the walk to
                # the last W positions; TP shard_maps the walk over the
                # tensor axis; int8 pools dequantize in-kernel via the
                # scale operands)
                attn = self._attend(q, kc, vc, block_tables, start_pos,
                                    n_tokens, slopes, window=window,
                                    k_scale=ks, v_scale=vs)
                attn_out = _linear(attn.reshape(N, C, nh * hd), lp["wo"],
                                   lp.get("wo_b"), dt)
                x = self.model._attn_mlp_merge(x, attn_out, lp, h1)
                return x, ((kc, vc, ks, vs) if quant else (kc, vc))
            return block

        if quant:
            x, (new_k, new_v, new_ks, new_vs) = self.model._scan_layers(
                block_for, x, (params["layers"], kv_cache["k"],
                               kv_cache["v"], kv_cache["k_scale"],
                               kv_cache["v_scale"]))
            new_cache = {"k": new_k, "v": new_v,
                         "k_scale": new_ks, "v_scale": new_vs}
        else:
            x, (new_k, new_v) = self.model._scan_layers(
                block_for, x, (params["layers"], kv_cache["k"],
                               kv_cache["v"]))
            new_cache = {"k": new_k, "v": new_v}
        x = _norm(x, params["final_norm"]["w"], params["final_norm"].get("b"),
                  cfg.norm, cfg.norm_eps)
        if verify_width:
            # right-aligned trailing-positions gather: row i, slot j reads
            # chunk position n_tokens[i] - W + j (clipped) — slot W-1 is
            # exactly the default path's last-token gather
            W = verify_width
            idx = jnp.clip(n_tokens[:, None] - W + jnp.arange(W)[None, :],
                           0, C - 1)                              # [N, W]
            x_v = jnp.take_along_axis(x, idx[:, :, None], axis=1)  # [N,W,H]
            return self.model._unembed(params, x_v), new_cache
        # logits_gather: only the last valid token per sequence
        last_idx = jnp.clip(n_tokens - 1, 0, C - 1)
        x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
        logits = self.model._unembed(params, x_last[:, None, :])[:, 0]
        return logits, new_cache
