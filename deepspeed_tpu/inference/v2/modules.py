"""v2 module registry + implementation heuristics.

Counterpart of the reference's module registry / heuristics layer
(``inference/v2/modules/module_registry.py`` ``DSModuleRegistry`` +
``heuristics.py:179`` ``instantiate_attention`` et al.): every serving op
is a *module type* with one or more named implementations; a heuristic
picks the best implementation for the current config/hardware, and callers
may force one by name. The reference had exactly one implementation per
type ("currently a stub"); here each type registers the genuinely distinct
implementations the framework already ships:

- ``attention``: the Pallas block-table kernel (``ops/paged_attention``)
  vs the XLA gather formulation (off-TPU fallback / numeric reference).
- ``flash_attention``: the Pallas training kernel vs the grouped-einsum
  XLA reference (``ops/flash_attention``).
- ``moe``: dropless ``lax.ragged_dot`` grouped GEMM (``moe/grouped``) vs
  the capacity-factor einsum path (``moe/sharded_moe``).
- ``linear``: plain dense matmul vs weight-only-quantized int8/int4
  (``inference/quantization``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from ...ops.pallas_utils import HAS_PALLAS, on_tpu


@dataclasses.dataclass(frozen=True)
class ImplEntry:
    """One named implementation of a module type."""
    name: str
    factory: Callable[..., Callable]      # (**ctx) -> forward callable
    supports: Callable[..., bool]         # (**ctx) -> can run this config?
    priority: int = 0                     # higher wins among supported


class DSModuleRegistry:
    """Registry of module-type → named implementations (reference
    module_registry.py ``DSModuleRegistryBase`` collapsed into one table —
    the per-type ABC hierarchy is torch-module machinery jax doesn't
    need)."""

    _registry: Dict[str, Dict[str, ImplEntry]] = {}

    @classmethod
    def register(cls, module_type: str, name: str,
                 factory: Callable[..., Callable],
                 supports: Optional[Callable[..., bool]] = None,
                 priority: int = 0) -> None:
        entry = ImplEntry(name, factory, supports or (lambda **ctx: True),
                          priority)
        cls._registry.setdefault(module_type, {})[name] = entry

    @classmethod
    def implementations(cls, module_type: str) -> List[str]:
        return sorted(cls._registry.get(module_type, {}))

    @classmethod
    def instantiate(cls, module_type: str, name: Optional[str] = None,
                    **ctx) -> Callable:
        """Named lookup, or the highest-priority implementation whose
        ``supports(**ctx)`` accepts the context."""
        impls = cls._registry.get(module_type)
        if not impls:
            raise KeyError(f"no implementations registered for "
                           f"{module_type!r}")
        if name is not None:
            if name not in impls:
                raise KeyError(
                    f"{module_type!r} has no implementation {name!r}; "
                    f"known: {sorted(impls)}")
            return impls[name].factory(**ctx)
        viable = [e for e in impls.values() if e.supports(**ctx)]
        if not viable:
            raise RuntimeError(
                f"no {module_type!r} implementation supports the config "
                f"{ctx}; known: {sorted(impls)}")
        best = max(viable, key=lambda e: e.priority)
        return best.factory(**ctx)


# ------------------------------------------------------------ registrations

def _attn_pallas_supports(num_heads=0, kv_heads=0, head_dim=0,
                          force_interpret=False, **_):
    from ...ops.paged_attention import pallas_supported

    return pallas_supported(num_heads, kv_heads, head_dim, force_interpret)


def _attn_pallas_factory(force_interpret=False, **_):
    from ...ops import paged_attention as pa

    if force_interpret and not on_tpu():
        # selection must mean execution: run the kernel in interpreter
        # mode off-TPU instead of letting the runtime dispatch silently
        # fall back to the XLA gather
        def fn(q, kc, vc, tables, start_pos, n_tokens, alibi_slopes=None,
               window=0, sm_scale=None, k_scale=None, v_scale=None):
            return pa._paged_pallas(q, kc, vc, tables, start_pos, n_tokens,
                                    alibi_slopes=alibi_slopes,
                                    window=window, sm_scale=sm_scale,
                                    k_scale=k_scale, v_scale=v_scale,
                                    interpret=True)

        fn.__name__ = "paged_attention_interpret"
        return fn
    return pa.paged_attention


def _attn_xla_factory(**_):
    from ...ops.paged_attention import paged_attention_xla

    return paged_attention_xla


DSModuleRegistry.register("attention", "pallas_paged", _attn_pallas_factory,
                          supports=_attn_pallas_supports, priority=10)
DSModuleRegistry.register("attention", "xla_gather", _attn_xla_factory)


def _flash_pallas_supports(seq_len=0, head_dim=0, block_q=512, block_kv=512,
                           force_interpret=False, **_):
    from ...ops import flash_attention as fa

    return (HAS_PALLAS
            and fa._pallas_ok(seq_len, seq_len, head_dim, block_q, block_kv)
            and (on_tpu() or force_interpret or fa._FORCE_INTERPRET))


def _flash_pallas_factory(**_):
    from ...ops.flash_attention import flash_attention

    return flash_attention


def _flash_xla_factory(**_):
    from ...ops.flash_attention import _attention_xla

    return _attention_xla


DSModuleRegistry.register("flash_attention", "pallas_flash",
                          _flash_pallas_factory,
                          supports=_flash_pallas_supports, priority=10)
DSModuleRegistry.register("flash_attention", "xla_reference",
                          _flash_xla_factory)


def _moe_dropless_supports(moe_dropless=False, expert_parallel=1, **_):
    # r5: EP composes via the partial-manual expert-axis shard_map
    # (moe/grouped.py dropless_moe_mlp_ep)
    return bool(moe_dropless)


def _moe_dropless_factory(expert_parallel=1, mesh=None, **_):
    if expert_parallel > 1:
        from functools import partial

        from ...moe.grouped import dropless_moe_mlp_ep
        from ...parallel import topology as topo

        if mesh is None:
            mesh = topo.get_topology().mesh
        got = int(dict(zip(mesh.axis_names, mesh.devices.shape)
                       ).get("expert", 1))
        if got != expert_parallel:
            raise ValueError(
                f"expert_parallel={expert_parallel} but the mesh's expert "
                f"axis is {got} — set the topology (or pass mesh=) before "
                "instantiating the EP dropless MoE")
        return partial(dropless_moe_mlp_ep, mesh=mesh)
    from ...moe.grouped import dropless_moe_mlp

    return dropless_moe_mlp


def _moe_capacity_factory(**_):
    from ...moe.sharded_moe import moe_dispatch_combine

    return moe_dispatch_combine


DSModuleRegistry.register("moe", "dropless_ragged", _moe_dropless_factory,
                          supports=_moe_dropless_supports, priority=10)
DSModuleRegistry.register("moe", "capacity_einsum", _moe_capacity_factory)


def _linear_quant_supports(quant_bits=0, **_):
    return quant_bits in (4, 8)


def _linear_quant_factory(quant_bits=8, **_):
    from ..quantization import QuantTensor, quantize_array

    def prepare(w):
        """Quantize a weight once (int8/int4 resident in HBM); pass the
        result as ``w`` so the forward never re-quantizes."""
        return quantize_array(w, bits=quant_bits)

    def fn(x, w, b=None):
        # dequant fuses into the consumer matmul under jit
        if not isinstance(w, QuantTensor):
            w = prepare(w)
        y = x @ w.dequantize()
        return y if b is None else y + b

    fn.prepare = prepare
    return fn


def _linear_dense_factory(**_):
    def fn(x, w, b=None):
        y = x @ w
        return y if b is None else y + b

    return fn


DSModuleRegistry.register("linear", "weight_only_quant",
                          _linear_quant_factory,
                          supports=_linear_quant_supports, priority=10)
DSModuleRegistry.register("linear", "dense", _linear_dense_factory)


# --------------------------------------------------------------- heuristics

def instantiate_attn(model_cfg, name: Optional[str] = None,
                     force_interpret: bool = False) -> Callable:
    """Pick the serving attention implementation (reference
    heuristics.py:179 ``instantiate_attention``). Default policy: the
    Pallas block-table kernel whenever the hardware/shape contract holds,
    else the XLA gather."""
    return DSModuleRegistry.instantiate(
        "attention", name,
        num_heads=model_cfg.num_heads, kv_heads=model_cfg.kv_heads,
        head_dim=model_cfg.head_dim, force_interpret=force_interpret)


def instantiate_flash_attn(model_cfg, seq_len: int,
                           name: Optional[str] = None,
                           force_interpret: bool = False) -> Callable:
    return DSModuleRegistry.instantiate(
        "flash_attention", name,
        seq_len=seq_len, head_dim=model_cfg.head_dim,
        block_q=model_cfg.flash_block_q, block_kv=model_cfg.flash_block_kv,
        force_interpret=force_interpret)


def instantiate_moe(model_cfg, expert_parallel: int = 1,
                    name: Optional[str] = None) -> Callable:
    return DSModuleRegistry.instantiate(
        "moe", name, moe_dropless=model_cfg.moe_dropless,
        expert_parallel=expert_parallel)


def instantiate_linear(quant_bits: int = 0,
                       name: Optional[str] = None) -> Callable:
    return DSModuleRegistry.instantiate("linear", name,
                                        quant_bits=quant_bits)
