"""Tiered KV memory: host-RAM/disk spillover for the prefix cache.

HBM is the hard ceiling on serving scale: a production fleet's system
prompts do not fit in device KV, so before this module a cold prefix
block was simply *dropped* on LRU eviction (``DSStateManager._evict``)
and had to be re-prefilled from scratch on the next match. ZeRO-Infinity
and ZeRO-Offload (PAPERS.md: arxiv 2104.07857, 2101.06840) showed that a
slower-but-larger memory tier with overlapped async transfers turns a
capacity wall into a bandwidth problem; this module applies that
treatment to the prefix cache (docs/SERVING.md "KV tiering"):

- **Spill on eviction.** When the prefix cache evicts a cold indexed
  block, its pool slab bytes (K and V ``[L, KH, bs, D]``, plus the
  ``k_scale``/``v_scale`` plane entries ``[L, KH]`` under kv_quant — so
  the spill rides the int8 4x compression) are copied device→host into
  a bounded host-RAM tier, keyed by the block's original
  ``(parent_hash, tokens)`` index key. Only unreferenced *full* blocks
  are ever evicted, so only those are ever spilled — a referenced or
  partial block can never land in the tier.
- **Demote to disk.** When the host tier exceeds its byte bound, LRU
  entries demote to an optional disk tier through
  ``runtime/swap_tensor`` :class:`AsyncTensorSwapper` (one file per
  entry, CRC-checked — a corrupt or torn file reads back as a *miss*,
  never a crash). Past the disk bound, LRU entries are dropped for
  real.
- **Restore on match.** ``match_prefix`` consults the tier when the
  device index misses: a tier hit allocates a fresh block, starts the
  host→device scatter (dispatched asynchronously — JAX's async dispatch
  returns immediately and the forward that eventually consumes the pool
  orders itself after the copy, so the restore overlaps other
  requests' work instead of blocking the ragged batch), and re-enters
  the block in the index under its original key. The scheduler then
  prefills only the still-cold tail, exactly as for a device hit.

The tier is keyed by content (the index key), not by sequence — two
requests sharing a spilled prefix share the one restored block, and all
refcount/hash-chain invariants of ``ragged/manager.py`` are preserved.
Disabled (the default) the module is never constructed: the eviction
and match paths are byte-for-byte the historical prefix cache.
"""

from __future__ import annotations

import itertools
import os
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...utils.logging import logger

#: per-process store counter: disk files are namespaced
#: ``kvtier_<pid>_<store>_<n>.swp`` so replicas sharing one ``disk_path``
#: (the frontend applies a single config to every replica engine) can
#: never overwrite or delete each other's entries
_STORE_IDS = itertools.count()

#: stat keys every ``TieredKVStore.stats`` dict carries (and the zeroed
#: shape ``DSStateManager.tier_stats()`` reports with no tier built)
TIER_STAT_KEYS = ("spilled", "restored", "dropped", "demoted",
                  "hits", "misses", "corrupt")
#: occupancy keys (also surfaced through ``DSStateManager.occupancy()``
#: as ``kv_blocks_host_tier`` etc. — the bench phase stamps and the
#: serving gauges read those)
TIER_OCC_KEYS = ("host_blocks", "host_bytes", "disk_blocks", "disk_bytes")


def empty_tier_stats() -> Dict[str, int]:
    """The all-zero stats+occupancy dict a tier-less manager reports —
    one shape for consumers (replica delta publish, bench stamps)
    whether or not a tier exists."""
    out = {k: 0 for k in TIER_STAT_KEYS}
    out.update({k: 0 for k in TIER_OCC_KEYS})
    return out


class TieredKVStore:
    """Bounded host-RAM (and optional disk) store of spilled KV blocks.

    Entries are ``{slab_name: np.ndarray}`` dicts — one per-block slab
    per pool tensor (``k``/``v`` and, under kv_quant, the
    ``k_scale``/``v_scale`` plane rows) — keyed by the prefix-cache
    index key. Both tiers are LRU OrderedDicts bounded in *bytes*:
    host overflow demotes to disk (when configured), disk overflow
    drops. ``get`` pops (the device pool becomes the authority again;
    re-eviction re-spills), serving host hits from memory and disk hits
    through :class:`AsyncTensorSwapper` with a CRC integrity check —
    a corrupt entry is counted and treated as a miss.
    """

    def __init__(self, host_max_bytes: int,
                 disk_path: Optional[str] = None,
                 disk_max_bytes: int = 0):
        self.host_max_bytes = int(host_max_bytes)
        self.disk_max_bytes = int(disk_max_bytes)
        self._host: "OrderedDict[tuple, dict]" = OrderedDict()
        self._disk: "OrderedDict[tuple, dict]" = OrderedDict()
        self.host_bytes = 0
        self.disk_bytes = 0
        self._swapper = None
        self._disk_dir = None
        if disk_path and self.disk_max_bytes > 0:
            from ...runtime.swap_tensor.async_swapper import AsyncTensorSwapper

            self._swapper = AsyncTensorSwapper(disk_path)
            self._disk_dir = disk_path
        self._file_prefix = f"kvtier_{os.getpid()}_{next(_STORE_IDS)}"
        self._next_file = 0
        self.stats: Dict[str, int] = {k: 0 for k in TIER_STAT_KEYS}
        if self._disk_dir is not None:
            self._sweep_stale_files()

    def _sweep_stale_files(self) -> None:
        """Remove spill files whose owning PROCESS is gone — a crashed
        or restarted server must not grow a shared ``disk_path`` without
        bound (``disk_max_bytes`` only bounds the live store). Files of
        live processes — sibling replicas in this process included — are
        left strictly alone; when liveness can't be determined the file
        stays (leak-on-doubt beats deleting a live replica's entry)."""
        try:
            names = os.listdir(self._disk_dir)
        except OSError:
            return
        for f in names:
            if not (f.startswith("kvtier_") and f.endswith(".swp")):
                continue
            try:
                pid = int(f.split("_")[1])
            except (IndexError, ValueError):
                continue
            if pid == os.getpid():
                continue                    # this process: maybe live
            try:
                os.kill(pid, 0)
                continue                    # owner alive: not ours to touch
            except ProcessLookupError:
                pass                        # owner dead: stale
            except OSError:
                continue                    # can't tell: leave it
            try:
                os.remove(os.path.join(self._disk_dir, f))
            except OSError:
                pass

    def __del__(self):
        # a replaced engine's store (supervisor restart path) must not
        # orphan its spill files until process exit
        try:
            self.close()
        except Exception:
            pass

    # ------------------------------------------------------------ occupancy
    def occupancy(self) -> Dict[str, int]:
        return {"host_blocks": len(self._host),
                "host_bytes": int(self.host_bytes),
                "disk_blocks": len(self._disk),
                "disk_bytes": int(self.disk_bytes)}

    def __len__(self) -> int:
        return len(self._host) + len(self._disk)

    def __contains__(self, key) -> bool:
        return key in self._host or key in self._disk

    # --------------------------------------------------------------- spill
    def put(self, key: tuple, slabs: Dict[str, np.ndarray], *,
            _count_spill: bool = True) -> bool:
        """Admit one evicted block's slabs under its index key.

        Overwrites any prior entry for the key (same content by
        construction — the key hashes the block's token chain). Returns
        False (counted ``dropped``) when the entry cannot fit the host
        bound at all; otherwise True, demoting/dropping LRU entries as
        the byte bounds require. ``_count_spill=False`` is the
        :meth:`readmit` path — the published counters must stay
        monotonic, so a re-insert never increments-then-decrements."""
        entry = {name: np.ascontiguousarray(a) for name, a in slabs.items()}
        nbytes = sum(a.nbytes for a in entry.values())
        if nbytes > self.host_max_bytes:
            # an entry the host tier can never hold goes STRAIGHT to the
            # disk tier when one exists (a tiny host_max_bytes with a
            # large disk bound is the disk-heavy configuration, not a
            # mistake to silently drop on)
            self._forget(key)
            if self._swapper is not None and self._demote(
                    key, {"slabs": entry, "nbytes": nbytes}):
                if _count_spill:
                    self.stats["spilled"] += 1
                return True
            self.stats["dropped"] += 1
            return False
        self._forget(key)
        self._host[key] = {"slabs": entry, "nbytes": nbytes}
        self.host_bytes += nbytes
        if _count_spill:
            self.stats["spilled"] += 1
        while self.host_bytes > self.host_max_bytes:
            old_key, old = self._host.popitem(last=False)
            self.host_bytes -= old["nbytes"]
            if not self._demote(old_key, old):
                self.stats["dropped"] += 1
        return True

    def _forget(self, key: tuple) -> None:
        """Remove any existing entry for ``key`` from both tiers
        (overwrite path; not a drop — the caller re-inserts)."""
        old = self._host.pop(key, None)
        if old is not None:
            self.host_bytes -= old["nbytes"]
        meta = self._disk.pop(key, None)
        if meta is not None:
            self.disk_bytes -= meta["nbytes"]
            self._remove_file(meta["fkey"])

    # -------------------------------------------------------------- demote
    def _demote(self, key: tuple, entry: dict) -> bool:
        """Move one host entry to the disk tier; False = no disk tier or
        the write failed (the caller counts the block dropped)."""
        if self._swapper is None:
            return False
        names = sorted(entry["slabs"])
        parts = [entry["slabs"][n] for n in names]
        buf = np.concatenate([p.reshape(-1).view(np.uint8) for p in parts])
        fkey = f"{self._file_prefix}_{self._next_file}"
        self._next_file += 1
        try:
            self._swapper.swap_out(fkey, buf)
            self._swapper.wait()
        except Exception as e:
            logger.warning(f"KV tier: disk demotion failed ({e!r}); "
                           "dropping the block")
            # a dispatched-then-failed write may have left a partial
            # file at the final path — it is outside disk_bytes
            # accounting and a live process's sweep never touches it
            self._remove_file(fkey)
            return False
        self._disk[key] = {
            "fkey": fkey, "nbytes": buf.nbytes, "crc": zlib.crc32(buf),
            "parts": [(n, tuple(p.shape), str(p.dtype), p.nbytes)
                      for n, p in zip(names, parts)]}
        self.disk_bytes += buf.nbytes
        self.stats["demoted"] += 1
        while self.disk_bytes > self.disk_max_bytes:
            k2, m2 = self._disk.popitem(last=False)
            self.disk_bytes -= m2["nbytes"]
            self._remove_file(m2["fkey"])
            self.stats["dropped"] += 1
        return True

    def _remove_file(self, fkey: str) -> None:
        if self._disk_dir is None:
            return
        try:
            os.remove(os.path.join(self._disk_dir, f"{fkey}.swp"))
        except OSError:
            pass

    # -------------------------------------------------------------- restore
    def get(self, key: tuple) -> Optional[Dict[str, np.ndarray]]:
        """Pop one entry's slabs (host first, then disk). None = miss —
        including a disk entry whose file is torn, truncated, or fails
        its CRC (counted ``corrupt``): corruption degrades to a
        re-prefill, never an exception on the serving path."""
        entry = self._host.pop(key, None)
        if entry is not None:
            self.host_bytes -= entry["nbytes"]
            self.stats["hits"] += 1
            return entry["slabs"]
        meta = self._disk.pop(key, None)
        if meta is None:
            self.stats["misses"] += 1
            return None
        self.disk_bytes -= meta["nbytes"]
        buf = np.empty(meta["nbytes"], np.uint8)
        try:
            self._swapper.swap_in(meta["fkey"], buf)
            self._swapper.wait()
        except Exception as e:
            logger.warning(f"KV tier: disk read for spilled block failed "
                           f"({e!r}); treating as a miss")
            self.stats["corrupt"] += 1
            self._remove_file(meta["fkey"])
            return None
        if zlib.crc32(buf) != meta["crc"]:
            logger.warning("KV tier: spilled block failed its CRC check; "
                           "treating as a miss")
            self.stats["corrupt"] += 1
            self._remove_file(meta["fkey"])
            return None
        slabs: Dict[str, np.ndarray] = {}
        off = 0
        for name, shape, dt, nb in meta["parts"]:
            slabs[name] = buf[off:off + nb].view(np.dtype(dt)).reshape(shape)
            off += nb
        self.stats["hits"] += 1
        self._remove_file(meta["fkey"])
        return slabs

    def discard(self, key: tuple) -> None:
        """Drop an entry from both tiers (and its disk file) WITHOUT
        touching the hit/miss counters — the cancel path for parked
        preemption payloads, not a serving-path lookup."""
        self._forget(key)

    def readmit(self, key: tuple, slabs: Dict[str, np.ndarray]) -> None:
        """Put back an entry whose restore failed (no device block could
        be freed): the ``get`` that fetched it was not a real hit — the
        match degraded to a miss — and the re-insert is not a new spill
        (``_count_spill=False``: the ``spilled`` counter other threads
        sample for delta/reset math must never dip, or a transient read
        would masquerade as an engine swap). Keeps hit/miss/spill
        describing what the serving path actually experienced, so a
        pool wedged by live sequences can't report a 100%-hit tier."""
        self.stats["hits"] -= 1
        self.stats["misses"] += 1
        self.put(key, slabs, _count_spill=False)

    # ------------------------------------------------------------ lifecycle
    def lru_keys(self) -> Tuple[List[tuple], List[tuple]]:
        """(host keys, disk keys) oldest-first — test/introspection
        surface for the LRU ordering invariant."""
        return list(self._host), list(self._disk)

    def clear(self) -> None:
        for meta in self._disk.values():
            self._remove_file(meta["fkey"])
        self._host.clear()
        self._disk.clear()
        self.host_bytes = 0
        self.disk_bytes = 0

    def close(self) -> None:
        self.clear()
        if self._swapper is not None:
            self._swapper.close()
            self._swapper = None
