"""InferenceEngineV2 — FastGen-style ragged continuous-batching engine.

Counterpart of reference ``inference/v2/engine_v2.py:26``
(``InferenceEngineV2``: ``put`` :89 runs one forward over a ragged batch,
``query``/``can_schedule`` :161 for admission control, ``flush`` frees a
sequence's KV blocks). The serving loop on top (Dynamic SplitFuse) lives in
``scheduler.py`` — in the reference that loop is DeepSpeed-MII.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...models.transformer import CausalLM
from ...utils.logging import logger
from .paged_model import PagedCausalLM
from .ragged import BlockedAllocator, DSStateManager, RaggedBatchWrapper
from .scheduling_utils import SchedulingError, SchedulingResult


class RaggedInferenceEngineConfig:
    def __init__(self, max_ragged_batch_size: int = 768,
                 max_ragged_sequence_count: int = 32,
                 max_chunk_tokens: int = 256,
                 kv_blocks: int = 512, kv_block_size: int = 16,
                 max_tracked_sequences: int = 256,
                 enable_prefix_cache: bool = False,
                 prefix_cache_max_blocks: Optional[int] = None,
                 kv_quant_enabled: bool = False,
                 kv_quant_dtype: str = "int8",
                 kv_quant_scale_granularity: str = "block",
                 weight_quant_enabled: bool = False,
                 weight_quant_dtype: str = "int8",
                 weight_quant_block: int = 128,
                 weight_quant_skip: Optional[Sequence[str]] = None,
                 kv_tier_enabled: bool = False,
                 kv_tier_host_bytes: int = 64 * 1024 * 1024,
                 kv_tier_disk_path: Optional[str] = None,
                 kv_tier_disk_bytes: int = 0,
                 admission_reservation: bool = False,
                 admission_oversubscription_factor: float = 1.0,
                 admission_preemption_enabled: bool = False,
                 admission_victim_policy: str = "lowest_class",
                 admission_max_preemptions_per_seq: int = 2):
        self.max_ragged_batch_size = max_ragged_batch_size
        self.max_ragged_sequence_count = max_ragged_sequence_count
        self.max_chunk_tokens = max_chunk_tokens
        self.kv_blocks = kv_blocks
        self.kv_block_size = kv_block_size
        self.max_tracked_sequences = max_tracked_sequences
        # prefix cache (docs/SERVING.md "Prefix caching"): share full KV
        # blocks between sequences with identical leading tokens
        self.enable_prefix_cache = enable_prefix_cache
        self.prefix_cache_max_blocks = prefix_cache_max_blocks
        # int8 KV-cache quantization (docs/SERVING.md "KV quantization"):
        # pools stored int8 with per-(layer, block, kv-head) scales —
        # a fixed HBM byte budget buys ~2x the blocks (kv_quant.py)
        self.kv_quant_enabled = kv_quant_enabled
        self.kv_quant_dtype = kv_quant_dtype
        self.kv_quant_scale_granularity = kv_quant_scale_granularity
        # int8/fp8 weight serving (docs/SERVING.md "Weight
        # quantization"): the CausalLM param tree is quantized ONCE at
        # engine build (inference/v2/weight_quant.py) and every matmul
        # runs from the quantized tree — ~3.9x fewer resident param
        # bytes vs fp32 and the per-step HBM weight stream cut with it
        self.weight_quant_enabled = weight_quant_enabled
        self.weight_quant_dtype = weight_quant_dtype
        self.weight_quant_block = weight_quant_block
        self.weight_quant_skip = (list(weight_quant_skip)
                                  if weight_quant_skip is not None else [])
        # tiered KV memory (docs/SERVING.md "KV tiering"): spill evicted
        # prefix-cache blocks to a bounded host-RAM tier (optionally
        # overflowing to disk) and restore them on a later prefix match
        # instead of re-prefilling — requires enable_prefix_cache
        self.kv_tier_enabled = kv_tier_enabled
        self.kv_tier_host_bytes = kv_tier_host_bytes
        self.kv_tier_disk_path = kv_tier_disk_path
        self.kv_tier_disk_bytes = kv_tier_disk_bytes
        # admission overhaul (docs/SERVING.md "Admission and
        # preemption"): total-block reservation admission in the
        # scheduler — a sequence's whole projected KV need is reserved
        # before its first prefill chunk, so N concurrent partial
        # prefills can never exhaust the pool with none able to finish
        # — plus preemption that spills a victim's KV to the tier and
        # resumes it later via import + submit_prefilled. Off (the
        # default) keeps the chunk-by-chunk admission byte for byte.
        self.admission_reservation = admission_reservation
        self.admission_oversubscription_factor = \
            admission_oversubscription_factor
        self.admission_preemption_enabled = admission_preemption_enabled
        self.admission_victim_policy = admission_victim_policy
        self.admission_max_preemptions_per_seq = \
            admission_max_preemptions_per_seq


class InferenceEngineV2:
    def __init__(self, model: Optional[CausalLM] = None, params=None,
                 config: Optional[RaggedInferenceEngineConfig] = None,
                 checkpoint_path: Optional[str] = None, mesh=None):
        self.config = config or RaggedInferenceEngineConfig()
        if params is None and checkpoint_path is not None:
            # pretrained weights (reference engine_v2 builds its model from a
            # checkpoint via the layer-container DSL; here: models/convert.py)
            from ...models import convert

            model, params = convert.load_hf_checkpoint(checkpoint_path,
                                                       model=model)
        if model is None:
            raise ValueError("InferenceEngineV2 needs a model or checkpoint_path")
        self.model = model
        if params is None:
            params = model.init(jax.random.PRNGKey(0))

        # TP serving over a mesh with a tensor axis (reference
        # inference/v2/model_implementations/sharding/qkv.py:166): params
        # placed by the logical-axis TP rules, KV pool sharded over the
        # kv-head dim, attention shard_mapped inside PagedCausalLM.
        cache_sharding = None
        scale_sharding = None
        jmesh = None
        tp = 1
        if mesh is not None:
            from ...parallel import topology as topo_mod

            topo_obj = (mesh if isinstance(mesh, topo_mod.MeshTopology)
                        else topo_mod.MeshTopology(mesh))
            jmesh = topo_obj.mesh
            # raw meshes may lack a tensor axis entirely → unsharded serving
            tp = dict(jmesh.shape).get("tensor", 1)
            if tp <= 1:
                jmesh = None
                tp = 1
        # int8/fp8 weight serving (docs/SERVING.md "Weight
        # quantization"): quantize the param tree ONCE, before TP
        # placement — so the scale planes are computed from the full
        # weights and then shard with their weight shards (the per-leaf
        # block divides the per-shard width; weight_quant.py).
        self._weight_quant_stats = None
        if self.config.weight_quant_enabled:
            from .weight_quant import quantize_weights

            params, self._weight_quant_stats = quantize_weights(
                model.cfg, params, dtype=self.config.weight_quant_dtype,
                block=self.config.weight_quant_block,
                skip=self.config.weight_quant_skip, tp=tp)
        if jmesh is not None:
            from ...parallel.sharding import ZeroShardingPlan
            from .weight_quant import expand_spec_tree
            from jax.sharding import NamedSharding, PartitionSpec as P

            spec_tree = (model.param_specs()
                         if hasattr(model, "param_specs") else None)
            # quantized-weight nodes carry their spec onto both the
            # payload and the scale plane (the PR 6 KV scale-plane
            # treatment applied to weights)
            spec_tree = expand_spec_tree(spec_tree, params)
            plan = ZeroShardingPlan(topo_obj, 0, spec_tree)
            shardings = plan.params(jax.eval_shape(lambda: params))
            params = jax.tree.map(jax.device_put, params, shardings)
            cache_sharding = NamedSharding(
                jmesh, P(None, None, "tensor", None, None))
            # kv_quant scale planes [L, NB, KH] follow the pools'
            # kv-head split (paged_model extends the shard_map specs)
            scale_sharding = NamedSharding(jmesh, P(None, None, "tensor"))
        self.params = params

        cfg = model.cfg
        max_blocks_per_seq = -(-cfg.max_seq_len // self.config.kv_block_size)
        self._cache_sharding = cache_sharding
        self._scale_sharding = scale_sharding
        self.state_manager = self._build_state_manager()
        self.paged = PagedCausalLM(model, self.config.kv_block_size,
                                   max_blocks_per_seq, mesh=jmesh)
        self.batch = RaggedBatchWrapper(self.config.max_ragged_sequence_count,
                                        self.config.max_chunk_tokens,
                                        max_blocks_per_seq)

    def _build_state_manager(self) -> DSStateManager:
        """Fresh sequence registry + KV pools from the current config —
        the constructor path and ``configure_kv_quant``'s rebuild."""
        from .kv_quant import validate_kv_quant

        if self.config.kv_quant_enabled:
            validate_kv_quant(self.config.kv_quant_dtype,
                              self.config.kv_quant_scale_granularity)
        return DSStateManager(
            self.model.cfg, self.config.max_tracked_sequences,
            self.config.kv_blocks, self.config.kv_block_size,
            sharding=self._cache_sharding,
            enable_prefix_cache=self.config.enable_prefix_cache,
            prefix_cache_max_blocks=self.config.prefix_cache_max_blocks,
            kv_quant=self.config.kv_quant_enabled,
            kv_quant_dtype=self.config.kv_quant_dtype,
            scale_sharding=self._scale_sharding,
            kv_tier_enabled=self.config.kv_tier_enabled,
            kv_tier_host_bytes=self.config.kv_tier_host_bytes,
            kv_tier_disk_path=self.config.kv_tier_disk_path,
            kv_tier_disk_bytes=self.config.kv_tier_disk_bytes)

    # ----------------------------------------------------------- admission
    def can_schedule(self, uids: Sequence[int],
                     lengths: Sequence[int]) -> SchedulingResult:
        """Reference engine_v2.py:161: can this (uids, lengths) batch run?"""
        if len(uids) > self.config.max_ragged_sequence_count:
            return SchedulingResult.BatchSequenceLimitExceeded
        if sum(lengths) > self.config.max_ragged_batch_size:
            return SchedulingResult.BatchTokenLimitExceeded
        blocks_needed = 0
        for uid, n in zip(uids, lengths):
            if n > self.config.max_chunk_tokens:
                return SchedulingResult.SequenceTokenLimitExceeded
            seq = self.state_manager.get_sequence(uid)
            total = (seq.seen_tokens if seq else 0) + n
            if total > self.model.cfg.max_seq_len:
                return SchedulingResult.SequenceTokenLimitExceeded
            have = seq.cur_allocated_blocks if seq else 0
            need = -(-total // self.config.kv_block_size)
            blocks_needed += max(0, need - have)
        # available = free + LRU-evictable cached blocks (identical to the
        # free count when the prefix cache is disabled)
        if blocks_needed > self.state_manager.available_blocks:
            return SchedulingResult.KVCacheLimitExceeded
        return SchedulingResult.Success

    def query(self, uid: int) -> Tuple[int, int]:
        """(seen_tokens, allocated_blocks) for a sequence (reference query)."""
        seq = self.state_manager.get_sequence(uid)
        if seq is None:
            return (0, 0)
        return (seq.seen_tokens, seq.cur_allocated_blocks)

    # -------------------------------------------------------------- serving
    def put(self, uids: Sequence[int],
            tokens_list: Sequence[Sequence[int]], *,
            verify_width: int = 0,
            defer_commit: bool = False) -> jnp.ndarray:
        """Run one forward over the ragged batch; returns next-token logits
        [len(uids), vocab] (reference engine_v2.py:89).

        Speculative verification (spec/, docs/SERVING.md "Speculative
        decoding") uses two keyword extensions; the default call is
        byte-for-byte the historical path:

        - ``verify_width`` W > 0: return logits for each row's last W
          valid positions, right-aligned — [len(uids), W, vocab] with
          row i's last valid token at position W-1 — so the caller can
          read the target's greedy argmax at every draft offset without
          the engine materializing logits for the whole padded chunk. W
          is static per compiled program; callers should bucket it.
        - ``defer_commit``: advance ``seen_tokens`` (the KV was written)
          but do NOT advance the prefix-cache hash chain — the fed tokens
          may contain unverified drafts, and the index must never refer to
          content that a later ``trim_sequence`` rolls back. The caller
          commits the accepted prefix afterwards via :meth:`commit_tokens`.
        """
        status = self.can_schedule(uids, [len(t) for t in tokens_list])
        if status != SchedulingResult.Success:
            raise SchedulingError(status)

        self.batch.clear()
        staged = []
        for uid, toks in zip(uids, tokens_list):
            seq = self.state_manager.get_or_create_sequence(uid)
            self.state_manager.maybe_allocate_kv(seq, len(toks))
            self.batch.insert_sequence(uid, list(toks), seq.seen_tokens,
                                       seq.kv_blocks)
            staged.append((seq, toks))

        arrays = self.batch.finalize()
        args = (self.params, self.state_manager.kv_cache,
                jnp.asarray(arrays["tokens"]),
                jnp.asarray(arrays["start_pos"]),
                jnp.asarray(arrays["n_tokens"]),
                jnp.asarray(arrays["block_tables"]))
        if verify_width:
            logits, new_cache = self.paged.forward_verify(
                *args, verify_width=int(verify_width))
        else:
            logits, new_cache = self.paged.forward(*args)
        # commit sequence state only after the forward was dispatched: a
        # failed forward leaves seen_tokens unchanged (the step can be
        # retried) and — critically — never registers blocks whose KV was
        # never written in the prefix-cache index. Allocation above is safe
        # either way: the blocks belong to the sequence and return to the
        # pool at flush. (Assumes each uid appears at most once per batch,
        # which the scheduler guarantees.)
        self.state_manager.kv_cache = new_cache
        for seq, toks in staged:
            seq.seen_tokens += len(toks)
            if not defer_commit:
                self.state_manager.record_tokens(seq, toks)
        return logits[:len(uids)]

    def flush(self, uid: int) -> None:
        self.state_manager.flush_sequence(uid)

    # ------------------------------------------------------- speculative
    def trim_sequence(self, uid: int, n_tokens: int) -> int:
        """Drop a sequence's trailing ``n_tokens`` from the KV cache —
        speculative-decoding rollback of rejected draft tokens. Returns
        the number of KV blocks released (see
        :meth:`DSStateManager.trim_sequence` for the prefix-cache
        interaction contract)."""
        return self.state_manager.trim_sequence(uid, n_tokens)

    def commit_tokens(self, uid: int, tokens: Sequence[int]) -> None:
        """Advance the prefix-cache hash chain with verified tokens — the
        second half of a ``put(defer_commit=True)`` step, called after
        rejected drafts were trimmed. No-op when the cache is disabled."""
        seq = self.state_manager.get_sequence(uid)
        if seq is not None:
            self.state_manager.record_tokens(seq, tokens)

    # ----------------------------------------------------------- KV handoff
    def export_sequence(self, uid: int,
                        chunk_blocks: int = 0) -> Optional[Dict[str, object]]:
        """Host-RAM snapshot of a sequence's KV blocks (pool slabs +
        kv_quant scale planes + metadata) for disaggregated
        prefill→decode handoff — see
        :meth:`DSStateManager.export_sequence` (``chunk_blocks`` > 0 =
        the block-granularity streamed form). The sequence stays
        tracked; the caller :meth:`flush`\\ es once the payload is
        staged."""
        return self.state_manager.export_sequence(uid,
                                                  chunk_blocks=chunk_blocks)

    def import_sequence(self, uid: int, payload: Dict[str, object],
                        tokens: Sequence[int]) -> None:
        """Adopt an exported sequence's KV into this engine's pool and
        resume decoding from it byte-losslessly — see
        :meth:`DSStateManager.import_sequence`. Raises (leaving this
        engine untouched) on representation mismatch or KV pressure; the
        serving layer falls back to re-prefilling."""
        self.state_manager.import_sequence(uid, payload, tokens)

    # ---------------------------------------------- admission + preemption
    def configure_admission(self, reservation: bool,
                            oversubscription_factor: float = 1.0,
                            preemption_enabled: bool = False,
                            victim_policy: str = "lowest_class",
                            max_preemptions_per_seq: int = 2) -> None:
        """Stamp the admission-overhaul settings (docs/SERVING.md
        "Admission and preemption") onto a built engine — the serving
        layer's config-driven hook (``ServingConfig.admission``).
        Schedulers read these at construction, so call it before the
        replica (and its scheduler) is built — the ``ServingFrontend``
        replica-build path does."""
        if preemption_enabled and not reservation:
            raise ValueError(
                "admission preemption requires reservation admission "
                "(preemption is triggered by reservation shortfall)")
        self.config.admission_reservation = bool(reservation)
        self.config.admission_oversubscription_factor = \
            float(oversubscription_factor)
        self.config.admission_preemption_enabled = bool(preemption_enabled)
        self.config.admission_victim_policy = str(victim_policy)
        self.config.admission_max_preemptions_per_seq = \
            int(max_preemptions_per_seq)

    def try_reserve(self, uid: int, total_blocks: int) -> bool:
        """Reserve a sequence's total projected block need against the
        ledger — see :meth:`DSStateManager.try_reserve`."""
        return self.state_manager.try_reserve(uid, total_blocks)

    def force_reserve(self, uid: int, total_blocks: int) -> None:
        self.state_manager.force_reserve(uid, total_blocks)

    def release_reservation(self, uid: int) -> None:
        self.state_manager.release_reservation(uid)

    def reservation_headroom(self) -> int:
        """Blocks a new reservation can still claim — see
        :meth:`DSStateManager.reservation_headroom`."""
        return self.state_manager.reservation_headroom()

    def reserved_total_blocks(self) -> int:
        return self.state_manager.reserved_total_blocks()

    def freeable_blocks_of(self, uid: int) -> int:
        """Blocks a flush of this sequence would actually return to
        ``available_blocks`` — see
        :meth:`DSStateManager.freeable_blocks_of`."""
        return self.state_manager.freeable_blocks_of(uid)

    def preempt_stash(self, uid: int, payload: Dict[str, object]) -> None:
        """Park an exported sequence's KV for a later preemption resume
        — see :meth:`DSStateManager.preempt_stash`."""
        self.state_manager.preempt_stash(uid, payload)

    def preempt_restore_payload(self, uid: int) -> Optional[Dict[str, object]]:
        return self.state_manager.preempt_restore_payload(uid)

    def preempt_discard(self, uid: int) -> None:
        self.state_manager.preempt_discard(uid)

    def match_prefix(self, uid: int, prompt_tokens: Sequence[int]) -> int:
        """Prefix-cache lookup for a new sequence: share every cached
        leading full KV block of ``prompt_tokens`` and return the matched
        token count (the caller skips prefilling that many tokens).
        Returns 0 when the prefix cache is disabled — and, critically,
        creates no sequence state in that case."""
        return self.state_manager.match_prefix(uid, prompt_tokens)

    def prefix_stats(self) -> Dict[str, int]:
        """Monotonic prefix-cache counters: hits/misses (block lookups),
        evictions, tokens_saved, queries."""
        return self.state_manager.prefix_stats()

    def prefix_digest(self, max_entries: int = 512) -> List[int]:
        """Bounded chain-hash digest of the cached prefix content (device
        index + KV tier) — the fleet router's affinity input; see
        :meth:`DSStateManager.prefix_digest`."""
        return self.state_manager.prefix_digest(max_entries)

    def export_prefix_blocks(self, max_blocks: int = 64) -> List[tuple]:
        """Host copies of the hottest cached prefix blocks (the replica
        warm-up donor side) — see
        :meth:`DSStateManager.export_prefix_blocks`."""
        return self.state_manager.export_prefix_blocks(max_blocks)

    def import_prefix_blocks(self, entries: List[tuple]) -> int:
        """Seed the prefix cache with another replica's exported blocks
        (the warm-up receiver side) — see
        :meth:`DSStateManager.import_prefix_blocks`."""
        return self.state_manager.import_prefix_blocks(entries)

    def configure_prefix_cache(self, enabled: bool,
                               max_blocks: Optional[int] = None) -> None:
        """Toggle prefix caching on a built engine — the serving layer's
        config-driven hook (``ServingConfig.prefix_cache``). Enabling is
        safe at any time: matching/registration start from now (sequences
        already mid-flight are excluded from hashing by the chain-state
        consistency guard in ``record_tokens``). Disabling drops the whole
        index so retained blocks cannot strand outside the free pool."""
        self.config.enable_prefix_cache = bool(enabled)
        self.config.prefix_cache_max_blocks = max_blocks
        sm = self.state_manager
        if enabled:
            sm.prefix_cache_enabled = True
            sm.prefix_cache_max_blocks = max_blocks or 0
        else:
            sm.clear_prefix_cache()
            sm.prefix_cache_enabled = False
            if sm.kv_tier_enabled:
                # the tier cannot outlive the cache it spills for
                self.configure_kv_tier(False)

    # ------------------------------------------------------------- KV tier
    def configure_kv_tier(self, enabled: bool,
                          host_bytes: Optional[int] = None,
                          disk_path: Optional[str] = None,
                          disk_bytes: Optional[int] = None) -> None:
        """Toggle the tiered KV spillover on a built engine — the serving
        layer's config-driven hook (``ServingConfig.kv_tier``; see
        docs/SERVING.md "KV tiering"). Enabling requires the prefix
        cache (spill/restore ride its eviction/match paths) and is safe
        at any time — spilling starts with the next eviction. Disabling
        drops every spilled entry (host and disk). ``None`` arguments
        keep the config's current values — re-tuning the host bound
        must not silently destroy a configured disk tier; pass
        ``disk_bytes=0`` to explicitly drop one."""
        host = (int(host_bytes) if host_bytes is not None
                else self.config.kv_tier_host_bytes)
        dpath = (disk_path if disk_path is not None
                 else self.config.kv_tier_disk_path)
        dbytes = (int(disk_bytes) if disk_bytes is not None
                  else self.config.kv_tier_disk_bytes)
        # build first, commit config after: a rejected configuration
        # (prefix cache off) must not leave config claiming a tier the
        # manager never built
        self.state_manager.configure_kv_tier(
            enabled, host_bytes=host, disk_path=dpath, disk_bytes=dbytes)
        self.config.kv_tier_enabled = bool(enabled)
        self.config.kv_tier_host_bytes = host
        self.config.kv_tier_disk_path = dpath
        self.config.kv_tier_disk_bytes = dbytes

    def tier_stats(self) -> Dict[str, int]:
        """Monotonic KV-tier counters (spilled/restored/dropped/...)
        plus current host/disk residency; all zeros (same shape) when no
        tier is configured — see :meth:`DSStateManager.tier_stats`."""
        return self.state_manager.tier_stats()

    def drain_restore_times(self) -> List[float]:
        """Restore-dispatch wall times since the last drain — the
        serving layer observes them into the ``kv_tier_restore_s``
        histogram."""
        return self.state_manager.drain_restore_times()

    def occupancy(self) -> Dict[str, int]:
        """KV-pool occupancy snapshot (blocks + bytes + evictable/
        available) — the single source the serving gauges
        (``kv_blocks_in_use``/``kv_bytes_in_use``) and bench phase stamps
        read; see :meth:`DSStateManager.occupancy`."""
        return self.state_manager.occupancy()

    def configure_kv_quant(self, enabled: bool, dtype: str = "int8",
                           scale_granularity: str = "block") -> None:
        """Toggle int8 KV-cache quantization on a built engine — the
        serving layer's config-driven hook (``ServingConfig.kv_quant``).
        Unlike the prefix cache this re-allocates the KV pools (the
        representation changes), so it is only legal while no sequences
        are tracked: call it before traffic (the ``ServingFrontend``
        replica-build path) or after a drain."""
        if (bool(enabled) == self.state_manager.kv_quant
                and dtype == self.config.kv_quant_dtype
                and scale_granularity == self.config.kv_quant_scale_granularity):
            return
        if self.state_manager.tracked_sequences:
            raise RuntimeError(
                "cannot reconfigure kv_quant with "
                f"{len(self.state_manager.tracked_sequences)} sequences "
                "tracked — their KV blocks hold the old representation")
        if enabled:
            # validate BEFORE touching config: a rejected dtype must not
            # leave config claiming a representation the pools don't have
            from .kv_quant import validate_kv_quant

            validate_kv_quant(dtype, scale_granularity)
        self.config.kv_quant_enabled = bool(enabled)
        self.config.kv_quant_dtype = dtype
        self.config.kv_quant_scale_granularity = scale_granularity
        self.state_manager = self._build_state_manager()

    # ------------------------------------------------------- weight serving
    def configure_weight_quant(self, enabled: bool, dtype: str = "int8",
                               block: int = 128,
                               skip: Optional[Sequence[str]] = None) -> None:
        """Quantize this engine's weights in place — the serving layer's
        config-driven hook (``ServingConfig.weight_quant``; see
        docs/SERVING.md "Weight quantization"). Like ``configure_kv_quant``
        this is only legal before traffic (no tracked sequences): the
        compiled forward changes with the param pytree. Unlike KV pools,
        quantized weights cannot be un-quantized (the original values are
        gone — keeping a full-precision copy would defeat the byte cut),
        so disabling or re-coding an already-quantized engine raises:
        rebuild from the factory instead (what the frontend's replica
        paths do)."""
        skip_list = (list(skip) if skip is not None else [])
        already = self.config.weight_quant_enabled
        if already and enabled and dtype == self.config.weight_quant_dtype:
            # idempotent: an engine quantized at build meets the serving
            # config's apply with the same representation (block/skip
            # differences cannot be honored post-hoc — the full-precision
            # values are gone — and are advisory at this point)
            return
        if already:
            raise RuntimeError(
                "weights are already quantized "
                f"({self.config.weight_quant_dtype}) — quantization is "
                "lossy and cannot be reconfigured in place; rebuild the "
                "engine from its factory")
        if not enabled:
            return                      # off -> off: nothing to do
        if self.state_manager.tracked_sequences:
            raise RuntimeError(
                "cannot quantize weights with "
                f"{len(self.state_manager.tracked_sequences)} sequences "
                "tracked — mid-stream logits would shift under the "
                "requests' feet")
        from .weight_quant import quantize_weights

        self.params, self._weight_quant_stats = quantize_weights(
            self.model.cfg, self.params, dtype=dtype, block=int(block),
            skip=skip_list, tp=self.paged.tp)
        self.config.weight_quant_enabled = True
        self.config.weight_quant_dtype = dtype
        self.config.weight_quant_block = int(block)
        self.config.weight_quant_skip = skip_list

    def param_stats(self) -> Dict[str, object]:
        """Resident param-byte accounting (total + quantized share) — the
        single source the ``param_bytes_total``/``param_bytes_quantized``
        serving gauges and the bench phase stamps read; cheap (pure
        shape/dtype metadata, computed lazily once per param tree)."""
        if self._weight_quant_stats is None:
            from .weight_quant import param_stats

            self._weight_quant_stats = param_stats(
                self.params,
                dtype=(self.config.weight_quant_dtype
                       if self.config.weight_quant_enabled else ""),
                block=(self.config.weight_quant_block
                       if self.config.weight_quant_enabled else 0))
        return dict(self._weight_quant_stats)

    @property
    def free_blocks(self) -> int:
        return self.state_manager.free_blocks
