from .engine_v2 import InferenceEngineV2, RaggedInferenceEngineConfig  # noqa: F401
from .scheduler import ContinuousBatchingScheduler, Request  # noqa: F401
from .scheduling_utils import SchedulingResult, SchedulingError  # noqa: F401
