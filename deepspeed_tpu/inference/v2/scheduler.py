"""Continuous-batching serving loop with Dynamic SplitFuse scheduling.

The reference keeps this loop in DeepSpeed-MII (external repo; in-repo
support is ``scheduling_utils.py`` — SURVEY §2 "DeepSpeed-MII / FastGen
scheduler"). Shipping it in-tree makes the TPU engine self-contained:
requests enter a queue; each step the scheduler packs (a) one decode token
for every running sequence and (b) prompt *chunks* from pending requests,
splitting long prompts so every forward has near-constant token count — the
Dynamic SplitFuse property that keeps TTFT low while decode throughput
stays flat.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

import numpy as np

from .engine_v2 import InferenceEngineV2
from .scheduling_utils import SchedulingResult


@dataclasses.dataclass
class Request:
    uid: int
    prompt_tokens: List[int]
    max_new_tokens: int = 64
    eos_token_id: Optional[int] = None
    # serving hooks (serving/replica.py): per-token delivery and a terminal
    # notification with a finish reason ("eos" | "length" | "cancelled")
    on_token: Optional[Callable[[int, int], None]] = None
    on_finish: Optional[Callable[["Request", str], None]] = None
    # state
    prompt_fed: int = 0
    prefix_matched: int = -1     # tokens served from the prefix cache
    #                              (-1 = lookup not yet performed)
    generated: List[int] = dataclasses.field(default_factory=list)
    last_logits: Optional[np.ndarray] = None
    done: bool = False
    finish_reason: Optional[str] = None

    @property
    def prompt_remaining(self) -> int:
        return len(self.prompt_tokens) - self.prompt_fed


class ContinuousBatchingScheduler:
    def __init__(self, engine: InferenceEngineV2,
                 sample_fn: Optional[Callable] = None):
        self.engine = engine
        self.pending: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}
        self.finished: Dict[int, Request] = {}
        self.sample_fn = sample_fn or (lambda logits: int(np.argmax(logits)))
        self._budget = engine.config.max_ragged_batch_size
        self._max_seqs = engine.config.max_ragged_sequence_count
        self._chunk = engine.config.max_chunk_tokens

    def submit(self, uid: int, prompt_tokens: List[int],
               max_new_tokens: int = 64, eos_token_id: Optional[int] = None,
               on_token: Optional[Callable[[int, int], None]] = None,
               on_finish: Optional[Callable[[Request, str], None]] = None):
        self.pending.append(Request(uid, list(prompt_tokens), max_new_tokens,
                                    eos_token_id, on_token, on_finish))

    def cancel(self, uid: int) -> bool:
        """Abort a request wherever it is; frees its KV blocks immediately
        (serving's cancel path — the blocks go back to the pool this step,
        not when the sequence would have finished). Returns False for
        unknown/already-finished uids."""
        req = self.running.pop(uid, None)
        if req is None:
            for r in self.pending:
                if r.uid == uid:
                    req = r
                    self.pending.remove(r)
                    break
        if req is None or req.done:
            return False
        self.engine.flush(uid)
        req.done = True
        req.finish_reason = "cancelled"
        self.finished[uid] = req
        if req.on_finish is not None:
            req.on_finish(req, "cancelled")
        return True

    @property
    def has_work(self) -> bool:
        return bool(self.pending or self.running)

    def _pack(self):
        """Dynamic SplitFuse packing: decodes first, then prompt chunks.

        Pure planning — no request state is mutated here (so a failed
        forward can be retried); admission is checked incrementally for
        decodes AND prompt chunks, deferring what doesn't fit to the next
        step."""
        uids: List[int] = []
        chunks: List[List[int]] = []
        plan: List[tuple] = []        # (req, chunk, is_decode)
        budget = self._budget

        # prompt candidates (running-but-prefilling, then pending) are
        # pulled and prefix-matched up front, BEFORE any admission check:
        # match_prefix pins shared blocks (refcounts), which moves them
        # out of the evictable count admission reads — matching after an
        # admit() could invalidate that admission and turn the engine's
        # re-check in put() into a SchedulingError. One-time per request;
        # a no-op returning 0 when the cache is disabled. Matched blocks
        # stay shared across deferral/retry until finish/cancel flushes.
        candidates: List[Request] = [r for r in self.running.values()
                                     if r.prompt_remaining > 0]
        new_candidates: List[Request] = []
        while self.pending and len(self.running) + len(new_candidates) < self._max_seqs:
            new_candidates.append(self.pending.popleft())
        for req in candidates + new_candidates:
            if req.prefix_matched < 0:
                req.prefix_matched = self.engine.match_prefix(
                    req.uid, req.prompt_tokens)
                if req.prefix_matched > 0:
                    req.prompt_fed = req.prefix_matched

        def admit(req, chunk) -> bool:
            ok = self.engine.can_schedule(uids + [req.uid],
                                          [len(c) for c in chunks] + [len(chunk)])
            if ok != SchedulingResult.Success:
                return False
            uids.append(req.uid)
            chunks.append(chunk)
            return True

        # (a) one token for every running (decode) sequence that fits
        for uid, req in list(self.running.items()):
            if req.prompt_remaining > 0 or budget <= 0:
                continue  # still prefilling (below) / out of budget (defer)
            tok = self.sample_fn(req.last_logits)
            if admit(req, [tok]):
                plan.append((req, [tok], True))
                budget -= 1
        # (b) prompt chunks: running-but-prefilling first, then pending
        for req in candidates + new_candidates:
            scheduled = False
            if budget > 0 and len(uids) < self._max_seqs:
                take = min(req.prompt_remaining, budget, self._chunk)
                chunk = req.prompt_tokens[req.prompt_fed:req.prompt_fed + take]
                if admit(req, chunk):
                    plan.append((req, chunk, False))
                    budget -= take
                    scheduled = True
            if not scheduled and req.uid not in self.running:
                self.pending.appendleft(req)   # new request deferred
        return uids, chunks, plan

    def step(self) -> List[int]:
        """One engine forward; returns uids of requests finished this step."""
        uids, chunks, plan = self._pack()
        if not uids:
            return []
        logits = np.asarray(self.engine.put(uids, chunks))
        done_now = []
        # commit state only after the forward succeeded
        for i, (req, chunk, is_decode) in enumerate(plan):
            req.last_logits = logits[i]
            if is_decode:
                req.generated.append(chunk[0])
                if req.on_token is not None:
                    req.on_token(req.uid, chunk[0])
            else:
                req.prompt_fed += len(chunk)
                self.running[req.uid] = req
            if req.prompt_remaining > 0:
                continue  # mid-prefill: sample only once the prompt is done
            ended = (req.eos_token_id is not None and req.generated
                     and req.generated[-1] == req.eos_token_id)
            if len(req.generated) >= req.max_new_tokens or ended:
                req.done = True
                req.finish_reason = "eos" if ended else "length"
                self.finished[req.uid] = req
                self.running.pop(req.uid, None)
                self.engine.flush(req.uid)
                done_now.append(req.uid)
                if req.on_finish is not None:
                    req.on_finish(req, req.finish_reason)
        return done_now

    def run_to_completion(self, max_steps: int = 10000) -> Dict[int, Request]:
        steps = 0
        while self.has_work and steps < max_steps:
            self.step()
            steps += 1
        return self.finished
