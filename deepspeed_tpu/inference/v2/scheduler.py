"""Continuous-batching serving loop with Dynamic SplitFuse scheduling.

The reference keeps this loop in DeepSpeed-MII (external repo; in-repo
support is ``scheduling_utils.py`` — SURVEY §2 "DeepSpeed-MII / FastGen
scheduler"). Shipping it in-tree makes the TPU engine self-contained:
requests enter a queue; each step the scheduler packs (a) one decode token
for every running sequence and (b) prompt *chunks* from pending requests,
splitting long prompts so every forward has near-constant token count — the
Dynamic SplitFuse property that keeps TTFT low while decode throughput
stays flat.

Speculative decoding (``proposer`` + greedy sampling; spec/,
docs/SERVING.md "Speculative decoding") rides the same packing: a decode
row carries ``[certain_token, draft_1..draft_K]`` instead of one token —
structurally a K+1-token prefill chunk — the forward returns per-position
logits, ``verify_greedy`` accepts the longest draft prefix the target's
argmax agrees with, and rejected tokens are rolled back with
``engine.trim_sequence``. The emitted stream is byte-identical to
speculation off; with no proposer the scheduler is byte-for-byte the
historical one.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict, deque
from typing import Callable, Deque, Dict, List, Optional

import numpy as np

from ...telemetry import NOOP_TRACER
from ...utils.logging import logger
from .engine_v2 import InferenceEngineV2
from .scheduling_utils import SchedulingResult
from .spec import DraftProposer, verify_greedy


@dataclasses.dataclass
class Request:
    uid: int
    prompt_tokens: List[int]
    max_new_tokens: int = 64
    eos_token_id: Optional[int] = None
    # serving hooks (serving/replica.py): per-token delivery and a terminal
    # notification with a finish reason ("eos" | "length" | "cancelled")
    on_token: Optional[Callable[[int, int], None]] = None
    on_finish: Optional[Callable[["Request", str], None]] = None
    # admission overhaul (docs/SERVING.md "Admission and preemption"):
    # shed_rank orders preemption victim selection (higher = lower
    # urgency class, preempted first — the serving layer passes its
    # class shed rank); preempt_count caps how often one sequence may
    # be spilled (the starvation guard); total_blocks is the reserved
    # total projected KV need recorded at admission
    shed_rank: int = 0
    preempt_count: int = 0
    total_blocks: int = 0
    # state
    prompt_fed: int = 0
    prefix_matched: int = -1     # tokens served from the prefix cache
    #                              (-1 = lookup not yet performed)
    generated: List[int] = dataclasses.field(default_factory=list)
    last_logits: Optional[np.ndarray] = None
    done: bool = False
    finish_reason: Optional[str] = None
    # telemetry (docs/OBSERVABILITY.md): set by submit() when the
    # scheduler's tracer is enabled and the caller passed a trace id;
    # spans holds the open prefill/decode stage spans
    trace_id: Optional[str] = None
    spans: Optional[Dict[str, object]] = None

    @property
    def prompt_remaining(self) -> int:
        return len(self.prompt_tokens) - self.prompt_fed


class ContinuousBatchingScheduler:
    def __init__(self, engine: InferenceEngineV2,
                 sample_fn: Optional[Callable] = None,
                 proposer: Optional[DraftProposer] = None,
                 max_draft_tokens: int = 4,
                 tracer=None, trace_label: str = "scheduler",
                 prefill_only: bool = False,
                 decode_reserve_tokens: int = 0):
        self.engine = engine
        # disaggregated serving roles (docs/SERVING.md "Disaggregated
        # serving"): a prefill-only scheduler never decodes — a request
        # whose prompt completes is finished with reason "prefilled" and
        # its KV left RESIDENT for the handoff export; a decode-role
        # scheduler reserves part of each step's token budget so queued
        # prompt chunks can never blow up the forward a decode rides in.
        # Defaults (False / 0) keep the historical scheduler byte for
        # byte.
        self.prefill_only = bool(prefill_only)
        self.decode_reserve_tokens = int(decode_reserve_tokens)
        # telemetry: per-forward spans under ``trace_label``'s trace and
        # per-request prefill/decode stage spans (docs/OBSERVABILITY.md).
        # The default NOOP tracer keeps the historical hot path: one
        # ``enabled`` attribute check per step.
        self.tracer = tracer if tracer is not None else NOOP_TRACER
        self.trace_label = trace_label
        self.pending: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}
        self.finished: Dict[int, Request] = {}
        self.sample_fn = sample_fn or (lambda logits: int(np.argmax(logits)))
        self._budget = engine.config.max_ragged_batch_size
        self._max_seqs = engine.config.max_ragged_sequence_count
        self._chunk = engine.config.max_chunk_tokens
        # speculative decoding: only lossless under greedy sampling — a
        # custom sample_fn silently wins over the proposer (documented)
        self.max_draft_tokens = max_draft_tokens
        self.proposer = proposer
        if proposer is not None and sample_fn is not None:
            logger.warning(
                "speculative decoding requires greedy sampling; custom "
                "sample_fn given — proposer disabled for this scheduler")
            self.proposer = None
        self._spec_stats = {"proposed": 0, "accepted": 0, "emitted": 0,
                            "decode_rows": 0}
        self._proposer_warned = False
        # admission overhaul (docs/SERVING.md "Admission and
        # preemption"), read from the ENGINE config so bare schedulers
        # (bench, tests) and the serving stack share one wiring point
        # (``ServingFrontend`` stamps ``ServingConfig.admission`` onto
        # each replica engine via ``engine.configure_admission`` before
        # building the replica's scheduler). All-default = the
        # historical chunk-by-chunk admission byte for byte.
        ecfg = engine.config
        self.reservation = bool(getattr(ecfg, "admission_reservation",
                                        False))
        self.oversubscription_factor = float(getattr(
            ecfg, "admission_oversubscription_factor", 1.0))
        self.preempt_enabled = bool(getattr(
            ecfg, "admission_preemption_enabled", False))
        self.victim_policy = str(getattr(
            ecfg, "admission_victim_policy", "lowest_class"))
        self.max_preemptions_per_seq = int(getattr(
            ecfg, "admission_max_preemptions_per_seq", 2))
        # parked (preempted) sequences, resume order = preemption order:
        # uid -> {"req", "tokens", "stashed", "last_logits", "fed",
        #         "n_blocks", "total_blocks"}
        self.preempted: "OrderedDict[int, dict]" = OrderedDict()
        self._preempt_stats = {"preempted": 0, "resumed": 0}
        self._parked_blocks = 0           # device blocks parked seqs held
        self._last_shortfall = 0          # blocks the pending head is short
        self._preempt_events: List[dict] = []   # drained by the replica
        self._spill_times: List[float] = []     # → preempt_spill_s
        self._resume_times: List[float] = []    # → preempt_resume_s

    @property
    def spec_enabled(self) -> bool:
        return self.proposer is not None

    def spec_stats(self) -> Dict[str, int]:
        """Monotonic speculative-decoding counters: ``proposed``/
        ``accepted`` draft tokens, ``emitted`` decode tokens, and
        ``decode_rows`` (decode row-forwards — each would have emitted
        exactly one token without speculation, so tokens-per-forward =
        emitted / decode_rows). ``proposed`` counts drafts that reached
        verification — drafts discarded by the admission degrade path
        were never judged and don't count; ``accepted`` counts only
        *delivered* drafts (a draft verified beyond an EOS is trimmed,
        not delivered), so acceptance_rate describes the streams the
        requests actually received."""
        return dict(self._spec_stats)

    def submit(self, uid: int, prompt_tokens: List[int],
               max_new_tokens: int = 64, eos_token_id: Optional[int] = None,
               on_token: Optional[Callable[[int, int], None]] = None,
               on_finish: Optional[Callable[[Request, str], None]] = None,
               trace_id: Optional[str] = None, shed_rank: int = 0):
        req = Request(uid, list(prompt_tokens), max_new_tokens,
                      eos_token_id, on_token, on_finish,
                      shed_rank=int(shed_rank))
        if trace_id is not None and self.tracer.enabled:
            # the prefill stage starts at scheduler submission so the
            # request's span chain stays gap-free: any wait for a packing
            # slot is prefill time from the request's point of view
            req.trace_id = trace_id
            req.spans = {"prefill": self.tracer.begin(
                "prefill", trace_id=trace_id,
                attrs={"prompt_tokens": len(req.prompt_tokens)})}
        self.pending.append(req)

    def submit_prefilled(self, uid: int, prompt_tokens: List[int],
                         last_logits, max_new_tokens: int = 64,
                         eos_token_id: Optional[int] = None,
                         on_token: Optional[Callable[[int, int], None]] = None,
                         on_finish: Optional[Callable[["Request", str],
                                                      None]] = None,
                         trace_id: Optional[str] = None,
                         shed_rank: int = 0) -> Request:
        """Resume a sequence whose prompt KV was imported from a
        prefill-role replica (``engine.import_sequence`` must have run
        first): the request enters ``running`` directly with the prompt
        marked fed and the source's final-position logits, so the first
        decode step samples exactly the token the source would have —
        byte-lossless under greedy decoding (docs/SERVING.md
        "Disaggregated serving")."""
        req = Request(uid, list(prompt_tokens), max_new_tokens,
                      eos_token_id, on_token, on_finish,
                      shed_rank=int(shed_rank))
        req.prompt_fed = len(req.prompt_tokens)
        req.prefix_matched = 0       # no lookup: the KV arrived whole
        req.last_logits = np.asarray(last_logits)
        if self.reservation:
            # the imported blocks are already resident; reserve the
            # remaining decode need. A shortfall here is repaired by the
            # preemption pass (or, with preemption off, was prevented by
            # the replica's pre-import headroom check) — the import
            # cannot be un-done from here, so the ledger records it
            # unconditionally rather than lying by omission.
            req.total_blocks = self._total_blocks(req)
            if not self.engine.try_reserve(uid, req.total_blocks):
                self.engine.force_reserve(uid, req.total_blocks)
        if trace_id is not None and self.tracer.enabled:
            # no prefill stage here (it ran on the source replica); the
            # decode span opens at the first emitted token as usual
            req.trace_id = trace_id
            req.spans = {}
        self.running[uid] = req
        return req

    def cancel(self, uid: int) -> bool:
        """Abort a request wherever it is; frees its KV blocks immediately
        (serving's cancel path — the blocks go back to the pool this step,
        not when the sequence would have finished). Returns False for
        unknown/already-finished uids."""
        req = self.running.pop(uid, None)
        if req is None:
            # a preempted (parked) sequence holds no device blocks —
            # drop its spilled payload and settle terminally
            entry = self.preempted.pop(uid, None)
            if entry is not None:
                req = entry["req"]
                self._parked_blocks -= entry["n_blocks"]
                self.engine.preempt_discard(uid)
        if req is None:
            for r in self.pending:
                if r.uid == uid:
                    req = r
                    self.pending.remove(r)
                    break
        if req is None or req.done:
            return False
        self.engine.flush(uid)
        if self.proposer is not None:       # drop draft state mid-speculation
            self.proposer.release(uid)
        self._end_request_spans(req, "cancelled")
        req.done = True
        req.finish_reason = "cancelled"
        self.finished[uid] = req
        if req.on_finish is not None:
            req.on_finish(req, "cancelled")
        return True

    def evacuate(self, uid: int) -> Optional[Dict[str, object]]:
        """Detach a sequence for migration to ANOTHER replica
        (docs/SERVING.md "Elastic autoscaling"): remove it from this
        scheduler's structures and free its device blocks WITHOUT
        settling it — no ``done`` mark, no ``on_finish`` callback; the
        serving layer re-queues the request and its stream continues
        elsewhere. For a fully-prefilled running sequence the resident
        KV is exported first (the PR 11 spill representation) and
        returned as a staged-handoff payload (``last_logits`` included)
        so the destination replica imports instead of re-prefilling;
        anything else — pending, mid-prefill, parked — returns ``None``
        and the caller re-prefills from prompt + delivered tokens (the
        failover resume semantics, lossless under greedy decoding).
        Returns ``None`` also for unknown/finished uids (nothing to
        move)."""
        payload = None
        req = self.running.pop(uid, None)
        if req is not None:
            if (req.prompt_remaining == 0 and not req.done
                    and req.last_logits is not None):
                try:
                    payload = self.engine.export_sequence(uid)
                except Exception as e:
                    logger.warning(f"evacuation KV export for sequence "
                                   f"{uid} failed ({e!r}); falling back "
                                   "to re-prefill")
                    payload = None
                if payload is not None:
                    payload["last_logits"] = req.last_logits
        else:
            # parked sequence: its device blocks are already free and
            # its payload sits in the preempt stash — drop the stash
            # (the re-prefill path is simpler than re-plumbing a parked
            # import across replicas) and hand the request back
            entry = self.preempted.pop(uid, None)
            if entry is not None:
                req = entry["req"]
                self._parked_blocks -= entry["n_blocks"]
                self.engine.preempt_discard(uid)
        if req is None:
            for r in self.pending:
                if r.uid == uid:
                    req = r
                    self.pending.remove(r)
                    break
        if req is None or req.done:
            return None
        try:
            self.engine.flush(uid)     # frees blocks + releases reservation
        except Exception:
            pass
        if self.proposer is not None:   # drop draft state mid-speculation
            self.proposer.release(uid)
        self._end_request_spans(req, "evacuated")
        return payload

    @property
    def has_work(self) -> bool:
        return bool(self.pending or self.running or self.preempted)

    def _pack(self):
        """Dynamic SplitFuse packing: decodes first, then prompt chunks.

        Pure planning — no request state is mutated here (so a failed
        forward can be retried); admission is checked incrementally for
        decodes AND prompt chunks, deferring what doesn't fit to the next
        step."""
        uids: List[int] = []
        chunks: List[List[int]] = []
        plan: List[tuple] = []        # (req, chunk, is_decode)
        budget = self._budget

        # prompt candidates (running-but-prefilling, then pending) are
        # pulled and prefix-matched up front, BEFORE any admission check:
        # match_prefix pins shared blocks (refcounts), which moves them
        # out of the evictable count admission reads — matching after an
        # admit() could invalidate that admission and turn the engine's
        # re-check in put() into a SchedulingError. One-time per request;
        # a no-op returning 0 when the cache is disabled. Matched blocks
        # stay shared across deferral/retry until finish/cancel flushes.
        if self.reservation:
            # admission overhaul (docs/SERVING.md "Admission and
            # preemption"): repair any force-reserve over-commitment,
            # resume parked sequences oldest-first while seats and
            # headroom allow, then admit pending work under total-block
            # reservation — a request that cannot reserve its whole
            # projected need WAITS instead of part-prefilling the pool
            # into a wedge.
            self._maybe_restore_headroom()
            self._resume_preempted()
            new_candidates = self._admit_pending_reserved()
        else:
            new_candidates = []
            while (self.pending
                   and len(self.running) + len(new_candidates) < self._max_seqs):
                new_candidates.append(self.pending.popleft())
        candidates: List[Request] = [r for r in self.running.values()
                                     if r.prompt_remaining > 0]
        for req in candidates + new_candidates:
            self._match_prefix_for(req)

        def admit(req, chunk) -> bool:
            ok = self.engine.can_schedule(uids + [req.uid],
                                          [len(c) for c in chunks] + [len(chunk)])
            if ok != SchedulingResult.Success:
                return False
            uids.append(req.uid)
            chunks.append(chunk)
            return True

        # (a) one token for every running (decode) sequence that fits —
        # plus up to max_draft_tokens proposer drafts when speculating
        # (the chunk is then verified like a K+1-token prefill chunk)
        for uid, req in list(self.running.items()):
            if self.prefill_only:
                break     # prefill-role: decode rows never pack here
            if req.prompt_remaining > 0 or budget <= 0:
                continue  # still prefilling (below) / out of budget (defer)
            tok = self.sample_fn(req.last_logits)
            chunk = [tok]
            if self.proposer is not None:
                # cap drafts so the chunk fits every static budget; the
                # last draft slot is pointless when the request can emit
                # at most one more token anyway
                k = min(self.max_draft_tokens, budget - 1, self._chunk - 1,
                        req.max_new_tokens - len(req.generated) - 1)
                if k > 0:
                    drafts = self._propose(req, tok, k)
                    if drafts:
                        chunk = [tok] + [int(d) for d in drafts[:k]]
            if admit(req, chunk):
                plan.append((req, chunk, True))
                budget -= len(chunk)
            elif len(chunk) > 1 and admit(req, [tok]):
                # speculative chunk didn't fit (KV pressure / seq-len
                # ceiling) — degrade to plain decode rather than defer
                plan.append((req, [tok], True))
                budget -= 1
        # (b) prompt chunks: running-but-prefilling first, then pending.
        # A decode-role scheduler holds back the UNUSED part of its
        # decode reservation from prompt chunks — the forward a decode
        # row rides in stays small even under a queued-prompt burst.
        # Clamped so at least one prompt token can always be scheduled
        # (an over-sized reservation must degrade prefill, not wedge it).
        reserve = 0
        if self.decode_reserve_tokens > 0:
            decode_used = self._budget - budget
            reserve = max(0, self.decode_reserve_tokens - decode_used)
            reserve = min(reserve, max(0, budget - 1))
        prompt_budget = budget - reserve
        for req in candidates + new_candidates:
            scheduled = False
            if prompt_budget > 0 and len(uids) < self._max_seqs:
                take = min(req.prompt_remaining, prompt_budget, self._chunk)
                chunk = req.prompt_tokens[req.prompt_fed:req.prompt_fed + take]
                if admit(req, chunk):
                    plan.append((req, chunk, False))
                    budget -= take
                    prompt_budget -= take
                    scheduled = True
            if not scheduled and req.uid not in self.running:
                self.pending.appendleft(req)   # new request deferred
        return uids, chunks, plan

    def _match_prefix_for(self, req: Request) -> None:
        """One-time prefix-cache lookup for a candidate (no-op once
        done, or when the cache is disabled — returns 0, creates
        nothing). Matched blocks stay shared across deferral/retry
        until finish/cancel flushes."""
        if req.prefix_matched >= 0:
            return
        # tiered KV memory (docs/SERVING.md "KV tiering"): count how
        # many of this request's matched blocks came back from the
        # host/disk tier — only when tracing, the extra stats read is
        # off the default hot path
        tier_fn = (getattr(self.engine, "tier_stats", None)
                   if req.spans is not None else None)
        restored0 = tier_fn()["restored"] if tier_fn else 0
        req.prefix_matched = self.engine.match_prefix(
            req.uid, req.prompt_tokens)
        if req.prefix_matched > 0:
            req.prompt_fed = req.prefix_matched
        if req.spans is not None:
            # cache outcome as a span attribute — the "where did
            # this TTFT go" answer includes what was skipped
            req.spans["prefill"].set("prefix_matched_tokens",
                                     req.prefix_matched)
            if tier_fn:
                req.spans["prefill"].set(
                    "kv_tier_restored_blocks",
                    tier_fn()["restored"] - restored0)

    # ------------------- reservation admission + preemption (tentpole;
    # docs/SERVING.md "Admission and preemption") -------------------------
    def _total_blocks(self, req: Request) -> int:
        """A request's TOTAL projected KV block need: every token that
        will ever sit in the cache — prompt plus the generation budget
        still owed (``generated`` stays populated across a preemption
        re-prefill, where the delivered tokens were folded into the
        prompt). Clamped to the pool size: a request the pool can never
        hold whole is admitted best-effort and defers at the tail
        exactly as the historical path did, instead of blocking the
        queue forever behind an unsatisfiable reservation."""
        bs = self.engine.config.kv_block_size
        total = (len(req.prompt_tokens)
                 + max(0, req.max_new_tokens - len(req.generated)))
        return min(-(-total // bs), self.engine.config.kv_blocks)

    def _admit_pending_reserved(self) -> List[Request]:
        """Pull pending requests under total-block reservation. FIFO
        within an urgency class (skipping a blocked peer would starve
        large requests), but a blocked head does NOT hold back
        strictly-more-urgent work behind it — that work may be able to
        reserve (or preempt) where the head could not. The unmet need
        is published as the reservation shortfall."""
        out: List[Request] = []
        self._last_shortfall = 0
        blocked_rank: Optional[int] = None    # most urgent rank blocked
        i = 0
        while (i < len(self.pending)
               and len(self.running) + len(out) < self._max_seqs):
            req = self.pending[i]
            if blocked_rank is not None and req.shed_rank >= blocked_rank:
                i += 1
                continue
            if self._try_admit(req):
                del self.pending[i]
                out.append(req)
            else:
                blocked_rank = (req.shed_rank if blocked_rank is None
                                else min(blocked_rank, req.shed_rank))
                i += 1
        return out

    def _try_admit(self, req: Request) -> bool:
        """Reservation admission for one request: prefix-match first
        (cached blocks credit against the need), then reserve the total
        projected block count. On shortfall, preemption (when enabled)
        may spill strictly-lower-urgency victims to the KV tier; a
        request that still cannot reserve is rolled back — its matched
        blocks released back to the cache — and waits."""
        total = self._total_blocks(req)
        self._match_prefix_for(req)
        if self.engine.try_reserve(req.uid, total):
            req.total_blocks = total
            return True
        if self.preempt_enabled and self._preempt_for(req, total):
            if self.engine.try_reserve(req.uid, total):
                req.total_blocks = total
                return True
        # rollback: the sequence keeps nothing while it waits (pinned
        # shared blocks would shrink everyone else's headroom); the
        # match re-runs on the next attempt
        self.engine.flush(req.uid)
        req.prefix_matched = -1
        req.prompt_fed = 0
        self._last_shortfall = max(
            self._last_shortfall,
            total - max(0, self.engine.reservation_headroom()))
        return False

    def _victim_order(self, req: Request, blocks: int):
        """Sort key for victim selection, LARGEST preempted first.
        ``lowest_class`` (default): lowest urgency class first (highest
        shed_rank), then most blocks (frees the most memory), then
        least progress (wastes the least work). ``most_blocks`` /
        ``least_progress`` re-order the tie-breakers for workloads that
        care more about one axis."""
        progress = req.prompt_fed + len(req.generated)
        if self.victim_policy == "most_blocks":
            return (blocks, req.shed_rank, -progress)
        if self.victim_policy == "least_progress":
            return (-progress, req.shed_rank, blocks)
        return (req.shed_rank, blocks, -progress)

    def _eligible_victims(self, min_rank: Optional[int] = None) -> List[tuple]:
        """(req, blocks) preemption candidates, best victim first.
        ``min_rank`` (admission-driven preemption) requires a victim of
        STRICTLY lower urgency than the newcomer — preempting peer work
        to admit identical work is pure churn, so same-class overload
        waits instead. ``max_preemptions_per_seq`` makes a sequence
        immune after that many spills (the starvation cap)."""
        out = []
        for uid, req in self.running.items():
            if req.preempt_count >= self.max_preemptions_per_seq:
                continue
            if min_rank is not None and req.shed_rank <= min_rank:
                continue
            # count only blocks a flush would actually return to the
            # available pool — prefix blocks other sequences share free
            # nothing, and spilling a victim for headroom that never
            # materializes is pure churn
            blocks = self.engine.freeable_blocks_of(uid)
            if blocks <= 0:
                continue         # nothing reclaimable to spill
            out.append((req, blocks))
        out.sort(key=lambda t: self._victim_order(*t), reverse=True)
        return out

    def _preempt_for(self, req: Request, total: int) -> bool:
        """Admission-driven preemption: spill strictly-lower-urgency
        victims until ``req`` can reserve, bounded by the
        oversubscription cap (total committed blocks — resident
        reservations plus parked sequences — may not exceed
        ``oversubscription_factor x kv_blocks``; at the default 1.0
        parking a victim to admit new work would always overflow the
        cap, so a factor > 1 is what turns preemptive admission on).
        Returns False without touching anything when the eligible
        victims cannot cover the shortfall — pointless churn."""
        committed = (self.engine.reserved_total_blocks()
                     + sum(e["total_blocks"] for e in self.preempted.values()))
        cap = self.oversubscription_factor * self.engine.config.kv_blocks
        if committed + total > cap:
            return False
        victims = self._eligible_victims(min_rank=req.shed_rank)
        have = self.engine.query(req.uid)[1]     # prefix-matched credit
        shortfall = (max(0, total - have)
                     - max(0, self.engine.reservation_headroom()))
        freeable = sum(b for _, b in victims)
        if freeable < shortfall:
            return False
        freed = 0
        for victim, blocks in victims:
            if freed >= shortfall:
                break
            self._preempt(victim)
            freed += blocks      # the FREEABLE count, not the export size
        return True

    def _maybe_restore_headroom(self) -> None:
        """Repair a negative reservation headroom (a ``force_reserve``
        over-commitment from a KV-handoff import) by spilling victims —
        any urgency class; the import already happened, so the only
        alternative is exactly the deferred-forever wedge this overhaul
        removes."""
        if not self.preempt_enabled:
            return
        while self.engine.reservation_headroom() < 0:
            victims = self._eligible_victims()
            if not victims:
                return
            self._preempt(victims[0][0])

    def _preempt(self, req: Request) -> int:
        """Spill one running sequence: export its KV (pool slabs +
        kv_quant scales) into the preemption store — the ``TieredKVStore``
        when a tier is configured — free its device blocks, and park it
        for a later byte-lossless resume. Returns the blocks freed."""
        t0 = time.perf_counter()
        uid = req.uid
        payload = self.engine.export_sequence(uid)
        n_blocks = int(payload["n_blocks"]) if payload else 0
        if payload is not None:
            self.engine.preempt_stash(uid, payload)
        # the tokens the exported KV encodes: fed prompt + committed
        # generation — what import_sequence replays into the prefix index
        tokens = req.prompt_tokens[:req.prompt_fed] + list(req.generated)
        self.engine.flush(uid)        # frees blocks + releases reservation
        self.running.pop(uid, None)
        if self.proposer is not None:
            self.proposer.release(uid)
        req.preempt_count += 1
        self.preempted[uid] = {
            "req": req, "tokens": tokens, "stashed": payload is not None,
            "last_logits": req.last_logits, "fed": req.prompt_fed,
            "n_blocks": n_blocks,
            "total_blocks": req.total_blocks or self._total_blocks(req)}
        self._parked_blocks += n_blocks
        self._preempt_stats["preempted"] += 1
        self._preempt_events.append({"uid": uid, "blocks": n_blocks})
        self._spill_times.append(time.perf_counter() - t0)
        if len(self._spill_times) > 4096:        # bounded when undrained
            del self._spill_times[:2048]
        return n_blocks

    def _resume_preempted(self) -> None:
        """Bring parked sequences back, oldest first, while a seat and
        full-reservation headroom exist (strict FIFO: resuming younger,
        smaller sequences over the head would starve it). The spilled
        payload imports byte-losslessly — the resumed sequence decodes
        from the exact logits it was parked with; a payload the tier
        dropped (byte bounds, disk corruption) degrades to a greedy
        re-prefill of prompt + delivered tokens, the failover resume
        semantics."""
        for uid in list(self.preempted):
            if len(self.running) >= self._max_seqs:
                return
            entry = self.preempted[uid]
            total = entry["total_blocks"]
            if total > self.engine.reservation_headroom():
                return
            t0 = time.perf_counter()
            req: Request = entry["req"]
            payload = (self.engine.preempt_restore_payload(uid)
                       if entry["stashed"] else None)
            if payload is not None:
                try:
                    self.engine.import_sequence(uid, payload,
                                                tokens=entry["tokens"])
                except Exception as e:
                    logger.warning(
                        f"preemption resume import for sequence {uid} "
                        f"failed ({e!r}); re-prefilling")
                    payload = None
            if payload is not None:
                req.prompt_fed = entry["fed"]
                req.last_logits = entry["last_logits"]
            else:
                # lost payload: re-prefill everything the KV held. The
                # delivered tokens fold into the prompt (KV order is
                # prompt-then-generation) while ``generated`` keeps the
                # budget accounting; greedy decoding of this prefix
                # continues the stream byte-identically.
                req.prompt_tokens = list(entry["tokens"]) + \
                    req.prompt_tokens[entry["fed"]:]
                req.prompt_fed = 0
                req.prefix_matched = -1
                req.last_logits = None
            self.engine.force_reserve(uid, total)
            req.total_blocks = total
            del self.preempted[uid]
            self._parked_blocks -= entry["n_blocks"]
            self.running[uid] = req
            self._preempt_stats["resumed"] += 1
            self._resume_times.append(time.perf_counter() - t0)
            if len(self._resume_times) > 4096:
                del self._resume_times[:2048]

    # ---------------------------------------------- preemption observability
    def preempt_stats(self) -> Dict[str, int]:
        """Monotonic counters: sequences ``preempted`` (spilled to the
        tier) and ``resumed`` (brought back) — the serving layer
        delta-publishes them as ``sequences_preempted`` /
        ``sequences_resumed``."""
        return dict(self._preempt_stats)

    def preempted_resident_blocks(self) -> int:
        """Device blocks the currently-parked sequences held when they
        were spilled — the footprint preemption is keeping off the pool
        (the ``preempted_resident_blocks`` gauge)."""
        return self._parked_blocks

    def reserve_shortfall_blocks(self) -> int:
        """Blocks the pending head is short of reserving, as of the
        last packing pass (the ``queue_wait_blocks`` gauge; 0 with
        reservation off or nothing waiting)."""
        return self._last_shortfall

    def drain_preempt_times(self):
        """(spill wall times, resume wall times) since the last drain —
        the serving layer observes them into ``preempt_spill_s`` /
        ``preempt_resume_s``."""
        spills, self._spill_times = self._spill_times, []
        resumes, self._resume_times = self._resume_times, []
        return spills, resumes

    def drain_preempt_events(self) -> List[dict]:
        """Per-preemption records since the last drain — the replica
        journals each as a ``sequence_preempted`` ops event."""
        out, self._preempt_events = self._preempt_events, []
        return out

    def _propose(self, req: Request, tok: int, k: int) -> List[int]:
        """Fetch drafts, isolating the scheduler from proposer faults —
        proposers are advisory, so any exception degrades to "no drafts"
        (warned once) instead of killing the serving step loop. Proposers
        with a bounded lookback (``context_window``) get only that tail,
        saving a full-history list rebuild per decode row per step."""
        win = getattr(self.proposer, "context_window", None)
        if win is None:
            ctx = req.prompt_tokens + req.generated + [tok]
        else:
            need = max(win - 1, 0)
            gen = req.generated
            if len(gen) >= need:
                ctx = gen[len(gen) - need:] + [tok]
            else:
                ctx = (req.prompt_tokens[max(0, len(req.prompt_tokens)
                                             - (need - len(gen))):]
                       + gen + [tok])
        try:
            return self.proposer.propose(req.uid, ctx, k)
        except Exception as e:
            if not self._proposer_warned:
                self._proposer_warned = True
                logger.warning(f"draft proposer failed ({e!r}); "
                               "continuing without speculation for the "
                               "affected steps")
            return []

    # ----------------------------------------------------------- telemetry
    def _note_first_token(self, req: Request) -> None:
        """Request-trace stage transition at the first emitted token:
        prefill ends (this instant IS the TTFT endpoint) and the decode
        stage opens."""
        if req.spans is None:
            return
        sp = req.spans.pop("prefill", None)
        if sp is not None:
            sp.end()
        req.spans["decode"] = self.tracer.begin("decode",
                                                trace_id=req.trace_id)

    def _end_request_spans(self, req: Request, reason: str) -> None:
        if req.spans is None:
            return
        dec = req.spans.get("decode")
        if dec is not None:
            dec.set("generated", len(req.generated))
            dec.set("finish_reason", reason)
        for sp in req.spans.values():
            sp.end()
        req.spans = None

    def step(self) -> List[int]:
        """One engine forward; returns uids of requests finished this step."""
        uids, chunks, plan = self._pack()
        if not uids:
            return []
        # verification width: the widest speculative decode chunk this
        # step, bucketed (pow2) to bound compiled-program variants. Steps
        # with no drafts in flight — pure prefill, draft-less decode —
        # take the exact historical path.
        spec_w = max((len(c) for _, c, d in plan if d and len(c) > 1),
                     default=0)
        # per-forward telemetry span (replica-level trace): brackets the
        # device call including host materialization of the logits
        traced = self.tracer.enabled
        fspan = self.tracer.begin(
            "forward", trace_id=self.trace_label,
            attrs={"n_seqs": len(uids),
                   "n_tokens": int(sum(len(c) for c in chunks))}) \
            if traced else None
        if self.proposer is None or spec_w == 0:
            logits = np.asarray(self.engine.put(uids, chunks))
            vspan = None
        else:
            W = self.engine.batch._bucket(spec_w, self._chunk)
            if traced:
                fspan.set("verify_width", W)
            # speculative step: right-aligned trailing-position logits for
            # verification; the prefix-cache hash chain is committed
            # per-row below, once rejected drafts have been trimmed (the
            # index must never see tokens a trim can roll back)
            logits = np.asarray(self.engine.put(uids, chunks,
                                                verify_width=W,
                                                defer_commit=True))
            # host-side verify/trim/commit of this step, as its own span
            vspan = self.tracer.begin("spec_verify",
                                      trace_id=self.trace_label,
                                      attrs={"verify_width": W}) \
                if traced else None
        if traced:
            fspan.end()
        done_now = []
        # commit state only after the forward succeeded
        for i, (req, chunk, is_decode) in enumerate(plan):
            if self.proposer is None or spec_w == 0:
                req.last_logits = logits[i]
                if is_decode:
                    if not req.generated:
                        self._note_first_token(req)
                    req.generated.append(chunk[0])
                    self._spec_stats["decode_rows"] += 1
                    self._spec_stats["emitted"] += 1
                    if req.on_token is not None:
                        req.on_token(req.uid, chunk[0])
                else:
                    req.prompt_fed += len(chunk)
                    self.running[req.uid] = req
            elif is_decode:
                # row i's valid positions are right-aligned: the last
                # len(chunk) slots
                self._apply_verified(req, chunk,
                                     logits[i, logits.shape[1] - len(chunk):])
            else:
                req.last_logits = logits[i, -1]   # slot W-1 = last valid
                self.engine.commit_tokens(req.uid, chunk)
                req.prompt_fed += len(chunk)
                self.running[req.uid] = req
            if req.prompt_remaining > 0:
                continue  # mid-prefill: sample only once the prompt is done
            if self.prefill_only:
                # prompt complete on a prefill-role scheduler: stop here.
                # The KV is deliberately NOT flushed — the serving layer
                # exports it for the decode-role handoff and flushes once
                # the payload is staged (docs/SERVING.md "Disaggregated
                # serving"); last_logits carries the final-position
                # logits the destination samples its first token from.
                req.done = True
                req.finish_reason = "prefilled"
                self._end_request_spans(req, "prefilled")
                self.finished[req.uid] = req
                self.running.pop(req.uid, None)
                if self.proposer is not None:
                    self.proposer.release(req.uid)
                done_now.append(req.uid)
                if req.on_finish is not None:
                    req.on_finish(req, "prefilled")
                continue
            ended = (req.eos_token_id is not None and req.generated
                     and req.generated[-1] == req.eos_token_id)
            if len(req.generated) >= req.max_new_tokens or ended:
                req.done = True
                req.finish_reason = "eos" if ended else "length"
                self._end_request_spans(req, req.finish_reason)
                self.finished[req.uid] = req
                self.running.pop(req.uid, None)
                self.engine.flush(req.uid)
                if self.proposer is not None:
                    self.proposer.release(req.uid)
                done_now.append(req.uid)
                if req.on_finish is not None:
                    req.on_finish(req, req.finish_reason)
        if vspan is not None:
            vspan.end()
        return done_now

    def _apply_verified(self, req: Request, chunk: List[int],
                        rows: np.ndarray) -> None:
        """Verify one speculative decode row and commit the outcome:
        accept the longest target-agreeing draft prefix, trim the rejected
        tail out of the KV cache, advance the prefix-cache chain with the
        surviving tokens only, and stream the emitted tokens (stopping at
        EOS — exactly where plain greedy decoding would have stopped)."""
        emitted, last = verify_greedy(chunk, rows)
        if req.eos_token_id is not None and req.eos_token_id in emitted:
            # tokens the target accepted beyond EOS are never delivered —
            # truncate BEFORE trim/commit/stats so the KV state, the
            # prefix chain, and the counters all describe exactly the
            # stream the request receives
            cut = emitted.index(req.eos_token_id) + 1
            emitted, last = emitted[:cut], cut - 1
        rejected = len(chunk) - len(emitted)
        if rejected:
            self.engine.trim_sequence(req.uid, rejected)
        self.engine.commit_tokens(req.uid, emitted)
        req.last_logits = rows[last]
        self._spec_stats["decode_rows"] += 1
        self._spec_stats["proposed"] += len(chunk) - 1
        self._spec_stats["accepted"] += len(emitted) - 1
        if not req.generated and emitted:
            self._note_first_token(req)
        if req.spans is not None:
            # accumulate this request's speculation outcome on its decode
            # span — "how many of MY tokens came from accepted drafts"
            dec = req.spans.get("decode")
            if dec is not None:
                a = dec.attrs
                a["spec_proposed"] = a.get("spec_proposed", 0) + len(chunk) - 1
                a["spec_accepted"] = a.get("spec_accepted", 0) + len(emitted) - 1
        for t in emitted:
            req.generated.append(t)
            self._spec_stats["emitted"] += 1
            if req.on_token is not None:
                req.on_token(req.uid, t)

    def run_to_completion(self, max_steps: int = 10000) -> Dict[int, Request]:
        steps = 0
        while self.has_work and steps < max_steps:
            self.step()
            steps += 1
        return self.finished
