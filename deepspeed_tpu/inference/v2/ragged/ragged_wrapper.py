"""Ragged batch descriptor: host-side assembly of the padded device batch.

Counterpart of reference ``inference/v2/ragged/ragged_wrapper.py``
(``RaggedBatchWrapper`` :267 — token concatenation + inflight descriptors
uploaded via the pinned fast_host_buffer). The TPU program wants *static*
shapes, so the wrapper pads to (max_seqs, max_chunk) and carries per-seq
metadata arrays; XLA masks do the ragged part. One wrapper instance is
reused across steps (buffers re-filled, no allocation per step).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


class RaggedBatchWrapper:
    def __init__(self, max_seqs: int, max_chunk: int, max_blocks_per_seq: int):
        self.max_seqs = max_seqs
        self.max_chunk = max_chunk
        self.max_blocks_per_seq = max_blocks_per_seq
        self.clear()

    def clear(self):
        ms, mc, mb = self.max_seqs, self.max_chunk, self.max_blocks_per_seq
        self.tokens = np.zeros((ms, mc), np.int32)
        self.start_pos = np.zeros((ms,), np.int32)     # tokens already cached
        self.n_tokens = np.zeros((ms,), np.int32)      # new tokens this step
        self.block_tables = np.full((ms, mb), -1, np.int32)
        self.uids: List[int] = []

    @property
    def current_sequences(self) -> int:
        return len(self.uids)

    @property
    def current_tokens(self) -> int:
        return int(self.n_tokens.sum())

    def insert_sequence(self, uid: int, tokens: Sequence[int], start_pos: int,
                        kv_blocks: Sequence[int]) -> int:
        """Add one sequence's chunk; returns its row index."""
        i = len(self.uids)
        if i >= self.max_seqs:
            raise ValueError("ragged batch full (max_seqs)")
        n = len(tokens)
        if n > self.max_chunk:
            raise ValueError(f"chunk {n} > max_chunk {self.max_chunk}")
        if len(kv_blocks) > self.max_blocks_per_seq:
            raise ValueError("sequence exceeds max_blocks_per_seq")
        self.tokens[i, :n] = np.asarray(tokens, np.int32)
        self.start_pos[i] = start_pos
        self.n_tokens[i] = n
        self.block_tables[i, :len(kv_blocks)] = np.asarray(kv_blocks, np.int32)
        self.uids.append(uid)
        return i

    @staticmethod
    def _bucket(n: int, cap: int) -> int:
        """Smallest power of two >= n, capped. Bounds the number of compiled
        program variants to O(log² cap) while letting a decode step run a
        [S, 1] batch instead of the full [max_seqs, max_chunk] pad."""
        b = 1
        while b < n:
            b *= 2
        return min(b, cap)

    def finalize(self, bucketed: bool = True) -> Dict[str, np.ndarray]:
        """Device-ready arrays (the reference's pinned-buffer upload).

        With ``bucketed`` (default), the batch is trimmed to
        (bucket(num_seqs), bucket(max chunk width)) — rows beyond the real
        sequences carry n_tokens=0 / table=-1 and are fully masked."""
        if not bucketed:
            return {
                "tokens": self.tokens,
                "start_pos": self.start_pos,
                "n_tokens": self.n_tokens,
                "block_tables": self.block_tables,
            }
        S = self._bucket(max(len(self.uids), 1), self.max_seqs)
        C = self._bucket(max(int(self.n_tokens.max()), 1), self.max_chunk)
        return {
            "tokens": self.tokens[:S, :C],
            "start_pos": self.start_pos[:S],
            "n_tokens": self.n_tokens[:S],
            "block_tables": self.block_tables[:S],
        }
