from .blocked_allocator import BlockedAllocator  # noqa: F401
from .manager import DSSequenceDescriptor, DSStateManager  # noqa: F401
from .ragged_wrapper import RaggedBatchWrapper  # noqa: F401
