"""Ragged sequence state: descriptors, block tables, paged KV cache.

Counterpart of reference ``inference/v2/ragged/ragged_manager.py``
(``DSStateManager``), ``sequence_descriptor.py`` (``DSSequenceDescriptor``)
and ``kv_cache.py`` (``BlockedKVCache``): tracks per-sequence seen-token
counts and KV block ownership, allocates blocks on demand, and owns the
device-side paged cache tensors [L, num_blocks, KH, block_size, D] (the
per-(block, kv-head) slab is the trailing [block_size, D] — the layout the
Pallas paged-attention index maps depend on, ops/paged_attention.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from .blocked_allocator import BlockedAllocator


@dataclass
class DSSequenceDescriptor:
    uid: int
    seen_tokens: int = 0                   # tokens already in the KV cache
    kv_blocks: List[int] = field(default_factory=list)
    input_tokens: List[int] = field(default_factory=list)  # pending prompt

    @property
    def cur_allocated_blocks(self) -> int:
        return len(self.kv_blocks)


class DSStateManager:
    """Sequence registry + paged KV cache (reference ragged_manager.py:204)."""

    def __init__(self, model_cfg, max_tracked_sequences: int = 256,
                 num_blocks: int = 256, block_size: int = 16,
                 dtype=None, sharding=None):
        self.cfg = model_cfg
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.max_tracked_sequences = max_tracked_sequences
        self.allocator = BlockedAllocator(num_blocks)
        self._seqs: Dict[int, DSSequenceDescriptor] = {}
        dt = dtype or model_cfg.dtype
        # [L, NB, KH, bs, D]: the per-(block, kv-head) slab is the trailing
        # [bs, D] — one tileable VMEM block, DMA'd directly by the Pallas
        # paged-attention index maps (ops/paged_attention.py).
        # ``sharding``: optional NamedSharding placing KH over the tensor
        # axis (TP serving — reference v2 sharding/qkv.py:166 head split).
        shape = (model_cfg.num_layers, num_blocks, model_cfg.kv_heads,
                 block_size, model_cfg.head_dim)
        if sharding is None:
            zeros = jnp.zeros(shape, dt)
        else:
            # allocate each device's shard directly — a full pool on one
            # device before resharding could OOM exactly when TP matters
            zeros = jax.jit(lambda: jnp.zeros(shape, dt),
                            out_shardings=sharding)()
        self.kv_cache = {"k": zeros, "v": zeros}

    # -- sequence registry -------------------------------------------------
    def get_or_create_sequence(self, uid: int) -> DSSequenceDescriptor:
        if uid not in self._seqs:
            if len(self._seqs) >= self.max_tracked_sequences:
                raise RuntimeError("max tracked sequences exceeded")
            self._seqs[uid] = DSSequenceDescriptor(uid=uid)
        return self._seqs[uid]

    def get_sequence(self, uid: int) -> Optional[DSSequenceDescriptor]:
        return self._seqs.get(uid)

    def flush_sequence(self, uid: int) -> None:
        """Release a finished sequence's blocks (reference engine_v2.flush)."""
        seq = self._seqs.pop(uid, None)
        if seq is not None and seq.kv_blocks:
            self.allocator.free(seq.kv_blocks)

    @property
    def tracked_sequences(self) -> List[int]:
        return list(self._seqs)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    # -- block math ---------------------------------------------------------
    def blocks_needed(self, seq: DSSequenceDescriptor, new_tokens: int) -> int:
        total = seq.seen_tokens + new_tokens
        need = -(-total // self.block_size)   # ceil
        return max(0, need - len(seq.kv_blocks))

    def maybe_allocate_kv(self, seq: DSSequenceDescriptor, new_tokens: int):
        need = self.blocks_needed(seq, new_tokens)
        if need > 0:
            seq.kv_blocks.extend(self.allocator.allocate(need))
