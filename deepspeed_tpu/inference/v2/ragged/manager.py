"""Ragged sequence state: descriptors, block tables, paged KV cache.

Counterpart of reference ``inference/v2/ragged/ragged_manager.py``
(``DSStateManager``), ``sequence_descriptor.py`` (``DSSequenceDescriptor``)
and ``kv_cache.py`` (``BlockedKVCache``): tracks per-sequence seen-token
counts and KV block ownership, allocates blocks on demand, and owns the
device-side paged cache tensors [L, num_blocks, KH, block_size, D] (the
per-(block, kv-head) slab is the trailing [block_size, D] — the layout the
Pallas paged-attention index maps depend on, ops/paged_attention.py).

Prefix cache (docs/SERVING.md "Prefix caching"): every *full* KV block a
sequence fills is registered in a hash index keyed by the chain hash of
its token content — ``h_i = hash((h_{i-1}, tokens_i))`` — so a later
sequence whose prompt starts with the same tokens at the same positions
shares those device blocks instead of re-prefilling them
(:meth:`DSStateManager.match_prefix`). Shared blocks are immutable: a
sequence only ever writes KV at positions ≥ its matched length, which land
in blocks it allocated itself; the last, partially-filled block of a
prompt is never matched (the walk stops at the last full-block boundary
strictly below ``len(prompt)``), so the tail is re-prefilled — the
copy-on-write of this design. The cache holds one reference of its own on
each indexed block; blocks whose only reference is the cache's are
*unreferenced* and evicted in LRU order when ``allocate`` would otherwise
fail (or when ``max_cached_blocks`` is exceeded).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .blocked_allocator import BlockedAllocator


@dataclass
class DSSequenceDescriptor:
    uid: int
    seen_tokens: int = 0                   # tokens already in the KV cache
    kv_blocks: List[int] = field(default_factory=list)
    input_tokens: List[int] = field(default_factory=list)  # pending prompt
    # prefix-cache chain state: hash through the last full block, how many
    # leading blocks have been hashed, and the tokens of the partial block
    chain_hash: int = 0
    hashed_blocks: int = 0
    pending_tokens: List[int] = field(default_factory=list)

    @property
    def cur_allocated_blocks(self) -> int:
        return len(self.kv_blocks)


class DSStateManager:
    """Sequence registry + paged KV cache (reference ragged_manager.py:204)."""

    def __init__(self, model_cfg, max_tracked_sequences: int = 256,
                 num_blocks: int = 256, block_size: int = 16,
                 dtype=None, sharding=None,
                 enable_prefix_cache: bool = False,
                 prefix_cache_max_blocks: Optional[int] = None,
                 kv_quant: bool = False, kv_quant_dtype: str = "int8",
                 scale_sharding=None,
                 kv_tier_enabled: bool = False,
                 kv_tier_host_bytes: int = 64 * 1024 * 1024,
                 kv_tier_disk_path: Optional[str] = None,
                 kv_tier_disk_bytes: int = 0):
        from ..kv_quant import kv_bytes_per_block

        self.cfg = model_cfg
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.max_tracked_sequences = max_tracked_sequences
        # quantized KV (docs/SERVING.md "KV quantization"): pools stored
        # as symmetric int8 or float8_e4m3fn (``kv_quant_dtype``) with
        # per-(layer, block, kv-head) f32 scale planes — half the HBM
        # bytes per block vs bf16, so a fixed byte budget buys ~2x the
        # blocks (inference/v2/kv_quant.py)
        self.kv_quant = bool(kv_quant)
        self.kv_quant_dtype = str(kv_quant_dtype)
        self.allocator = BlockedAllocator(
            num_blocks,
            bytes_per_block=kv_bytes_per_block(model_cfg, block_size,
                                               self.kv_quant, dtype))
        self._seqs: Dict[int, DSSequenceDescriptor] = {}
        # -- reservation ledger (docs/SERVING.md "Admission and
        # preemption"): per-sequence TOTAL projected block need, recorded
        # at admission. The scheduler's reservation admission keeps
        # ``sum(unfilled) <= available_blocks`` — every admitted sequence
        # can always obtain the blocks it still needs, so chunk-by-chunk
        # prefill can never wedge the pool. Passive when nobody reserves
        # (the ledger is empty → headroom == available_blocks).
        self._reserved: Dict[int, int] = {}
        # -- preemption spill store: whole-sequence KV exports parked
        # under pressure. Slab bytes live in the KV tier when one is
        # configured (byte-bounded LRU + disk demotion + CRC — dropped
        # entries degrade to a lossless greedy re-prefill), else in a
        # plain host-RAM dict bounded by the parked-sequence count.
        self._preempt_store: Dict[int, dict] = {}
        # -- prefix cache ---------------------------------------------------
        self.prefix_cache_enabled = bool(enable_prefix_cache)
        self.prefix_cache_max_blocks = (prefix_cache_max_blocks
                                        if prefix_cache_max_blocks else 0)
        # index key = (parent_chain_hash, block_tokens_tuple): the block's
        # own tokens are compared EXACTLY on lookup (dict equality), so a
        # builtin-hash collision cannot alias two different blocks; only
        # the parent linkage is compressed to its 64-bit chain hash.
        self._index: "OrderedDict[tuple, int]" = OrderedDict()  # key -> block
        self._block_hash: Dict[int, tuple] = {}                 # block -> key
        self._evictable = 0       # indexed blocks whose only ref is the
        #                           cache's own (kept incrementally — the
        #                           admission path reads it per candidate)
        self._stats = {"hits": 0, "misses": 0, "evictions": 0,
                       "tokens_saved": 0, "queries": 0}
        # tiered KV memory (docs/SERVING.md "KV tiering"): host-RAM/disk
        # spillover for evicted prefix-cache blocks with restore on
        # match. None = the historical drop-on-evict path byte for byte.
        self._tier = None
        self._restore_times: List[float] = []   # drained by the serving
        #                                         layer into kv_tier_restore_s
        if kv_tier_enabled:
            self.configure_kv_tier(True, host_bytes=kv_tier_host_bytes,
                                   disk_path=kv_tier_disk_path,
                                   disk_bytes=kv_tier_disk_bytes)
        dt = dtype or model_cfg.dtype
        # [L, NB, KH, bs, D]: the per-(block, kv-head) slab is the trailing
        # [bs, D] — one tileable VMEM block, DMA'd directly by the Pallas
        # paged-attention index maps (ops/paged_attention.py).
        # ``sharding``: optional NamedSharding placing KH over the tensor
        # axis (TP serving — reference v2 sharding/qkv.py:166 head split).
        shape = (model_cfg.num_layers, num_blocks, model_cfg.kv_heads,
                 block_size, model_cfg.head_dim)
        from ..kv_quant import pool_dtype as _pool_dtype

        pool_dt = _pool_dtype(self.kv_quant_dtype) if self.kv_quant else dt

        def _alloc(shp, adt, shard):
            if shard is None:
                return jnp.zeros(shp, adt)
            # allocate each device's shard directly — a full pool on one
            # device before resharding could OOM exactly when TP matters
            return jax.jit(lambda: jnp.zeros(shp, adt),
                           out_shardings=shard)()

        zeros = _alloc(shape, pool_dt, sharding)
        self.kv_cache = {"k": zeros, "v": zeros}
        if self.kv_quant:
            # symmetric per-(layer, block, kv-head) scales, indexed by
            # pool block id — a prefix-shared block shares its scale for
            # free; freed blocks' stale entries are ignored (not reset) by
            # the fresh-block write rule in kv_quant.quantized_block_write
            sshape = shape[:3]
            szeros = _alloc(sshape, jnp.float32, scale_sharding)
            self.kv_cache["k_scale"] = szeros
            self.kv_cache["v_scale"] = szeros

    # -- sequence registry -------------------------------------------------
    def get_or_create_sequence(self, uid: int) -> DSSequenceDescriptor:
        if uid not in self._seqs:
            if len(self._seqs) >= self.max_tracked_sequences:
                raise RuntimeError("max tracked sequences exceeded")
            self._seqs[uid] = DSSequenceDescriptor(uid=uid)
        return self._seqs[uid]

    def get_sequence(self, uid: int) -> Optional[DSSequenceDescriptor]:
        return self._seqs.get(uid)

    def flush_sequence(self, uid: int) -> None:
        """Release a finished sequence's blocks (reference engine_v2.flush).
        Blocks held by the prefix cache stay resident (the cache's own
        reference keeps them) and become evictable once no sequence refers
        to them."""
        seq = self._seqs.pop(uid, None)
        self._reserved.pop(uid, None)     # reservation dies with the state
        if seq is not None and seq.kv_blocks:
            self._release_blocks(seq.kv_blocks)

    def _release_blocks(self, blocks: List[int]) -> None:
        """Drop one reference per block and keep the incremental
        evictable count honest: an indexed block whose only remaining
        reference is the cache's own just became reclaimable. The single
        home for this transition — flush and trim both go through it."""
        self.allocator.release(blocks)
        if self.prefix_cache_enabled:
            for b in blocks:
                if (b in self._block_hash
                        and self.allocator.ref_count(b) == 1):
                    self._evictable += 1

    def trim_sequence(self, uid: int, n_tokens: int) -> int:
        """KV rollback: drop the trailing ``n_tokens`` from a sequence —
        the speculative-decoding rejection path (spec/: drafts the target
        model refuted must vanish from the cache before the next step).

        Trailing blocks that become empty are ``release``d through the
        refcount machinery: a private block returns to the free list; a
        block the prefix cache also holds stays resident (the cache's own
        reference keeps it) and becomes evictable. Blocks *below* the new
        length — including prefix-shared ones — are untouched: no refcount
        changes, no index changes.

        Interaction with the prefix-cache index: draft tokens are never
        chain-registered (the scheduler defers ``record_tokens`` until
        after verification — ``put(defer_commit=True)``), so a trim of
        speculative tokens can never cut into hashed coverage. Trimming
        *into* an already-indexed block is refused with ``ValueError``:
        the retained prefix of such a block would later be overwritten in
        place while the index (and possibly other sequences) still
        reference the old content. Callers that need that must flush and
        re-prefill instead.

        Returns the number of blocks released.
        """
        seq = self._seqs.get(uid)
        if seq is None or n_tokens <= 0:
            return 0
        if n_tokens > seq.seen_tokens:
            raise ValueError(
                f"cannot trim {n_tokens} tokens from sequence {uid} "
                f"({seq.seen_tokens} seen)")
        new_seen = seq.seen_tokens - n_tokens
        if new_seen < seq.hashed_blocks * self.block_size:
            raise ValueError(
                f"cannot trim sequence {uid} into prefix-indexed blocks "
                f"({seq.hashed_blocks} blocks hashed, want "
                f"{new_seen} tokens)")
        keep = -(-new_seen // self.block_size)       # ceil; 0 when new_seen=0
        dropped = seq.kv_blocks[keep:]
        # sharing happens only through the prefix index, and indexed
        # blocks sit inside hashed coverage (guarded above) — a dropped
        # block that is shared yet unindexed means some other sequence
        # reads KV this trim is rolling back: corruption, refuse loudly
        for b in dropped:
            if self.allocator.is_shared(b) and b not in self._block_hash:
                raise ValueError(
                    f"cannot trim block {b} of sequence {uid}: shared "
                    "outside the prefix index (sharing invariant violated)")
        del seq.kv_blocks[keep:]
        seq.seen_tokens = new_seen
        # chain state: un-blocked pending tokens past the new end are gone
        over = (seq.hashed_blocks * self.block_size
                + len(seq.pending_tokens)) - new_seen
        if over > 0:
            del seq.pending_tokens[len(seq.pending_tokens) - over:]
        if dropped:
            self._release_blocks(dropped)
        return len(dropped)

    # -- KV handoff (disaggregated prefill/decode) --------------------------
    def export_sequence(self, uid: int,
                        chunk_blocks: int = 0) -> Optional[Dict[str, object]]:
        """Host-RAM snapshot of a sequence's KV state for cross-engine
        handoff (docs/SERVING.md "Disaggregated serving"): every pool
        slab the sequence's block table references — K and V, plus the
        ``k_scale``/``v_scale`` planes under kv_quant — copied
        device→host (async transfer started for all slabs before any is
        materialized, so the copies overlap), with the metadata
        :meth:`import_sequence` validates against. Whole blocks are
        copied verbatim (stale slots past ``seen_tokens`` included), so
        an import reproduces the pool content byte-for-byte — attention
        masks those positions on both sides. Shared prefix blocks export
        like private ones (content copy; the source's refcounts are
        untouched). Returns ``None`` for unknown/empty sequences. The
        source sequence keeps its state — the caller flushes after the
        payload is staged.

        ``chunk_blocks`` > 0 switches to the block-granularity streamed
        form (docs/SERVING.md "Multi-host serving"): the payload carries
        ``"chunks"`` — a list of per-chunk slab dicts covering at most
        ``chunk_blocks`` blocks each. Every chunk's device→host copy is
        dispatched BEFORE any chunk materializes (so the copies
        overlap), and the payload holds host numpy arrays — staged
        payloads pin host RAM only, never device HBM — in units a
        consumer (the wire codec, the import scatter) can stream one at
        a time, overlapping a long-context handoff's transfer with
        ongoing decode. Byte content is identical to the whole-slab
        form (tests assert)."""
        seq = self._seqs.get(uid)
        if seq is None or not seq.kv_blocks:
            return None
        meta = {"seen_tokens": seq.seen_tokens,
                "block_size": self.block_size,
                "kv_quant": self.kv_quant,
                "kv_quant_dtype": self.kv_quant_dtype,
                "n_blocks": len(seq.kv_blocks)}
        if chunk_blocks and chunk_blocks > 0:
            device_chunks = []
            for s in range(0, len(seq.kv_blocks), int(chunk_blocks)):
                ids = jnp.asarray(seq.kv_blocks[s:s + int(chunk_blocks)],
                                  dtype=jnp.int32)
                arrs = {name: jnp.take(pool, ids, axis=1)
                        for name, pool in self.kv_cache.items()}
                for a in arrs.values():
                    try:
                        a.copy_to_host_async()
                    except Exception:   # backend without async host copy
                        pass
                device_chunks.append(arrs)
            # materialize AFTER every copy was dispatched (each asarray
            # waits only for its own chunk's transfer) — the device
            # buffers are released here, so a staged payload pins host
            # RAM, not HBM
            meta["chunk_blocks"] = int(chunk_blocks)
            meta["chunks"] = [{name: np.asarray(a)
                               for name, a in c.items()}
                              for c in device_chunks]
            return meta
        ids = jnp.asarray(seq.kv_blocks, dtype=jnp.int32)
        arrs = {name: jnp.take(pool, ids, axis=1)
                for name, pool in self.kv_cache.items()}
        for a in arrs.values():
            try:
                a.copy_to_host_async()
            except Exception:   # backend without async host copy
                pass
        meta["slabs"] = {name: np.asarray(a) for name, a in arrs.items()}
        return meta

    def import_sequence(self, uid: int, payload: Dict[str, object],
                        tokens: Sequence[int]) -> None:
        """Adopt an exported sequence: allocate fresh blocks, scatter the
        payload's slabs (and scale planes) into this pool at the new
        ids, and seed the descriptor at the source's ``seen_tokens`` —
        the destination decodes from here exactly as the source would
        have (byte-lossless: int8/f32/bf16 slabs round-trip host copies
        exactly).

        ``tokens`` are the actual tokens the imported KV encodes (length
        must equal ``seen_tokens``): they replay ``record_tokens`` so
        the destination's prefix-cache hash chain covers the imported
        blocks — full blocks register in the index and later prompts
        sharing the prefix hit, exactly as if the prefill had run here.

        Raises on representation mismatch (block size / kv_quant — a
        heterogeneous fleet must recompute instead), on a uid that
        already has state, and on insufficient capacity (after LRU
        prefix-cache eviction). Failure leaves the manager untouched —
        the caller falls back to re-prefilling.

        Accepts BOTH payload forms: whole-slab (``"slabs"``) and the
        block-granularity streamed form (``"chunks"`` — see
        :meth:`export_sequence`); chunked payloads scatter one chunk at
        a time, so the first chunks land while later ones are still
        materializing/arriving."""
        chunks = payload.get("chunks")
        slabs = (payload["slabs"] if chunks is None
                 else {k: None for k in chunks[0]} if chunks
                 else {k: None for k in self.kv_cache})
        if int(payload["block_size"]) != self.block_size:
            raise ValueError(
                f"KV import block_size mismatch: payload "
                f"{payload['block_size']} vs pool {self.block_size}")
        if bool(payload["kv_quant"]) != self.kv_quant:
            raise ValueError(
                f"KV import representation mismatch: payload kv_quant="
                f"{payload['kv_quant']} vs pool kv_quant={self.kv_quant}")
        # dtype axis of the representation check (int8 vs fp8_e4m3):
        # pre-dtype payloads default to int8, the only representation
        # that existed when they were written
        pay_dt = str(payload.get("kv_quant_dtype", "int8"))
        if self.kv_quant and pay_dt != self.kv_quant_dtype:
            raise ValueError(
                f"KV import representation mismatch: payload "
                f"kv_quant_dtype={pay_dt!r} vs pool "
                f"{self.kv_quant_dtype!r}")
        if set(slabs) != set(self.kv_cache):
            raise ValueError(f"KV import slab keys {sorted(slabs)} != "
                             f"pool keys {sorted(self.kv_cache)}")
        seen = int(payload["seen_tokens"])
        if len(tokens) != seen:
            raise ValueError(f"KV import needs the {seen} tokens the KV "
                             f"encodes, got {len(tokens)}")
        existing = self._seqs.get(uid)
        if existing is not None and (existing.seen_tokens
                                     or existing.kv_blocks):
            raise ValueError(f"cannot import into sequence {uid}: it "
                             "already has KV state")
        n = int(payload["n_blocks"])
        if chunks is not None:
            got = sum(int(np.shape(next(iter(c.values())))[1])
                      for c in chunks)
            if got != n:
                raise ValueError(f"KV import chunks cover {got} blocks, "
                                 f"payload claims {n}")
        short = n - self.allocator.free_blocks
        if short > 0 and self.prefix_cache_enabled:
            self._evict(short)
        if n > self.allocator.free_blocks:
            raise RuntimeError(
                f"cannot import {n} KV blocks "
                f"({self.allocator.free_blocks} free)")
        seq = self.get_or_create_sequence(uid)
        blocks = self.allocator.allocate(n)
        try:
            if chunks is not None:
                # streamed form: glue the chunks per slab and scatter
                # ONCE per pool tensor — a per-chunk `.at[].set` would
                # copy the whole pool per chunk (O(chunks x pool
                # bytes)), the exact long-context case chunking exists
                # to help. The streaming benefit already happened
                # upstream (per-chunk host copies / wire frames).
                ids = jnp.asarray(blocks, dtype=jnp.int32)
                for name, pool in self.kv_cache.items():
                    glued = np.concatenate(
                        [np.asarray(c[name]) for c in chunks], axis=1)
                    self.kv_cache[name] = pool.at[:, ids].set(
                        jnp.asarray(glued, dtype=pool.dtype))
            else:
                ids = jnp.asarray(blocks, dtype=jnp.int32)
                for name, pool in self.kv_cache.items():
                    self.kv_cache[name] = pool.at[:, ids].set(
                        jnp.asarray(slabs[name], dtype=pool.dtype))
            seq.kv_blocks.extend(blocks)
            seq.seen_tokens = seen
            # prefix-index coherence: rebuild the hash chain over the
            # imported tokens (no-op when the cache is disabled)
            self.record_tokens(seq, tokens)
        except Exception:
            self._seqs.pop(uid, None)
            self.allocator.release(blocks)
            raise

    @property
    def tracked_sequences(self) -> List[int]:
        return list(self._seqs)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    # -- block math ---------------------------------------------------------
    def blocks_needed(self, seq: DSSequenceDescriptor, new_tokens: int) -> int:
        total = seq.seen_tokens + new_tokens
        need = -(-total // self.block_size)   # ceil
        return max(0, need - len(seq.kv_blocks))

    def maybe_allocate_kv(self, seq: DSSequenceDescriptor, new_tokens: int):
        need = self.blocks_needed(seq, new_tokens)
        if need > 0:
            short = need - self.allocator.free_blocks
            if short > 0 and self.prefix_cache_enabled:
                self._evict(short)           # LRU unreferenced cached blocks
            seq.kv_blocks.extend(self.allocator.allocate(need))

    # -- reservation ledger (docs/SERVING.md "Admission and preemption") ----
    def _unfilled(self, uid: int, total: int) -> int:
        seq = self._seqs.get(uid)
        have = len(seq.kv_blocks) if seq is not None else 0
        return max(0, total - have)

    def reserved_unfilled(self) -> int:
        """Blocks the reserved sequences are still entitled to allocate —
        the ledger's claim against ``available_blocks``. Recomputed per
        read: the ledger only ever holds admitted + parked sequences
        (bounded by the ragged seat count, dozens), so the walk is noise
        next to the forward each scheduler step runs."""
        return sum(self._unfilled(uid, total)
                   for uid, total in self._reserved.items())

    def freeable_blocks_of(self, uid: int) -> int:
        """Blocks that would actually return to ``available_blocks`` if
        this sequence were flushed right now: private blocks (the
        sequence holds the only reference) plus cache-indexed blocks
        whose only OTHER reference is the cache's own (they become
        evictable). Prefix blocks other live sequences still share free
        NOTHING on flush — preemption victim selection must not count
        them, or a victim gets spilled for headroom that never
        materializes."""
        seq = self._seqs.get(uid)
        if seq is None:
            return 0
        n = 0
        for b in seq.kv_blocks:
            rc = self.allocator.ref_count(b)
            if rc == 1 or (rc == 2 and b in self._block_hash):
                n += 1
        return n

    def reservation_headroom(self) -> int:
        """``available_blocks`` minus the outstanding reservation claims:
        what a NEW reservation (or a preempted sequence's resume) can
        take without endangering an admitted sequence's future
        allocations. Negative only after a ``force_reserve``
        over-commitment (KV handoff imports) — the scheduler's
        preemption path restores it."""
        return self.available_blocks - self.reserved_unfilled()

    def try_reserve(self, uid: int, total_blocks: int) -> bool:
        """Reserve a sequence's total projected block need (prompt +
        generation budget, blocks it already holds — prefix-cache hits
        included — credited). False = shortfall: the caller defers the
        sequence instead of part-prefilling it into a wedge."""
        prior = self._reserved.pop(uid, None)
        need = self._unfilled(uid, int(total_blocks))
        if need > self.reservation_headroom():
            if prior is not None:
                self._reserved[uid] = prior
            return False
        self._reserved[uid] = int(total_blocks)
        return True

    def force_reserve(self, uid: int, total_blocks: int) -> None:
        """Record a reservation unconditionally — the KV-handoff import
        path, whose blocks are already resident when the ledger first
        hears of the sequence. May push headroom negative; the
        scheduler's preemption pass repairs that."""
        self._reserved[uid] = int(total_blocks)

    def release_reservation(self, uid: int) -> None:
        self._reserved.pop(uid, None)

    def reserved_total_blocks(self) -> int:
        """Sum of the reserved sequences' total projected needs — the
        resident half of the oversubscription-cap accounting."""
        return sum(self._reserved.values())

    @property
    def reserved_sequences(self) -> int:
        return len(self._reserved)

    # -- preemption spill store (docs/SERVING.md "Admission and preemption")
    def preempt_stash(self, uid: int, payload: Dict[str, object]) -> None:
        """Park an exported sequence's KV (``export_sequence`` payload)
        for a later resume. Slab bytes go through the KV tier when one
        is configured — int8 slabs under kv_quant ride the 4x
        compression, host overflow demotes to disk, and a dropped or
        corrupt entry degrades the resume to a greedy re-prefill — else
        they stay in host RAM on this store."""
        meta = {k: payload[k] for k in ("seen_tokens", "block_size",
                                        "kv_quant", "n_blocks")}
        # representation dtype axis (int8/fp8_e4m3) — absent only in
        # pre-dtype payloads, which were int8 by construction
        meta["kv_quant_dtype"] = payload.get("kv_quant_dtype", "int8")
        if self._tier is not None:
            # not a prefix-cache spill: keep the per-block tier counters
            # honest (sequences_preempted counts these instead)
            self._tier.put(("__preempt__", uid), payload["slabs"],
                           _count_spill=False)
            meta["in_tier"] = True
        else:
            meta["slabs"] = payload["slabs"]
        self._preempt_store[uid] = meta

    def preempt_restore_payload(self, uid: int) -> Optional[Dict[str, object]]:
        """Take a parked sequence's export payload back (one-shot).
        ``None`` = nothing parked, or the tier dropped/corrupted the
        entry — the caller re-prefills (byte-lossless under greedy)."""
        meta = self._preempt_store.pop(uid, None)
        if meta is None:
            return None
        meta = dict(meta)
        if meta.pop("in_tier", False):
            slabs = (self._tier.get(("__preempt__", uid))
                     if self._tier is not None else None)
            if slabs is None:
                return None
            meta["slabs"] = slabs
        return meta

    def preempt_discard(self, uid: int) -> None:
        """Drop a parked payload (cancel/deadline/shutdown of a
        preempted sequence)."""
        meta = self._preempt_store.pop(uid, None)
        if meta is not None and meta.get("in_tier") and self._tier is not None:
            self._tier.discard(("__preempt__", uid))

    @property
    def preempted_parked(self) -> int:
        return len(self._preempt_store)

    # -- prefix cache --------------------------------------------------------
    @property
    def evictable_blocks(self) -> int:
        """Cached blocks whose only reference is the cache's own.
        Maintained incrementally (share on match / release on flush /
        eviction are the only transitions) — the admission path reads
        this once per candidate per step."""
        if not self.prefix_cache_enabled:
            return 0
        return self._evictable

    @property
    def available_blocks(self) -> int:
        """Blocks an allocate can obtain: free + evictable (admission
        control must count reclaimable cache residency, or a warm cache
        would wedge the scheduler on KVCacheLimitExceeded forever)."""
        return self.allocator.free_blocks + self.evictable_blocks

    def occupancy(self) -> Dict[str, int]:
        """One snapshot of KV-pool occupancy: the allocator's block/byte
        counts plus the prefix-cache view (evictable = reclaimable cached
        blocks, available = what an allocate can actually obtain). The
        serving layer publishes this as ``kv_blocks_in_use`` /
        ``kv_bytes_in_use`` gauges and every bench phase stamps it."""
        occ = self.allocator.occupancy()
        occ["evictable_blocks"] = self.evictable_blocks
        occ["available_blocks"] = occ["free_blocks"] + occ["evictable_blocks"]
        # per-tier residency (docs/SERVING.md "KV tiering"): zeros when
        # no tier is configured, so the serving gauges and bench stamps
        # have one schema either way
        tier = (self._tier.occupancy() if self._tier is not None
                else {"host_blocks": 0, "host_bytes": 0,
                      "disk_blocks": 0, "disk_bytes": 0})
        occ["kv_blocks_host_tier"] = tier["host_blocks"]
        occ["kv_bytes_host_tier"] = tier["host_bytes"]
        occ["kv_blocks_disk_tier"] = tier["disk_blocks"]
        occ["kv_bytes_disk_tier"] = tier["disk_bytes"]
        return occ

    def prefix_stats(self) -> Dict[str, int]:
        return dict(self._stats)

    def match_prefix(self, uid: int,
                     prompt_tokens: Sequence[int]) -> int:
        """Match a new sequence's prompt against the cache block-by-block.

        Shares every leading full block whose chain hash is indexed, seeds
        the sequence's ``seen_tokens`` at the matched length, and returns
        it. The walk is capped at ``len(prompt) - 1`` so at least one
        token is always left to prefill — the forward that produces the
        first logits. No-op (returns 0, creates nothing) when the cache is
        disabled or the sequence already has state.
        """
        if not self.prefix_cache_enabled:
            return 0
        seq = self.get_or_create_sequence(uid)
        if seq.seen_tokens > 0 or seq.kv_blocks:
            return seq.seen_tokens
        self._stats["queries"] += 1
        limit = len(prompt_tokens) - 1
        matched: List[int] = []
        h = 0
        n = 0
        while n + self.block_size <= limit:
            key = (h, tuple(prompt_tokens[n:n + self.block_size]))
            b = self._index.get(key)
            if b is None and self._tier is not None:
                # tiered KV memory (docs/SERVING.md "KV tiering"): a
                # device miss may be a spilled run — chain keys are
                # computable from the prompt alone, so the whole
                # contiguous spilled run restores in ONE batched
                # scatter per pool tensor, then the walk re-reads the
                # index and continues as if it had hit
                if self._restore_chain(key, prompt_tokens, n, limit):
                    b = self._index.get(key)
            if b is None:
                self._stats["misses"] += 1
                break
            self._index.move_to_end(key)     # LRU touch
            if self.allocator.ref_count(b) == 1:
                self._evictable -= 1         # about to gain a sequence ref
            # share NOW (not batched at the end): a tier restore later in
            # this walk may trigger eviction, and an already-matched
            # block held only by the cache's ref would be reclaimable —
            # the sequence ref pins it for the rest of the walk
            self.allocator.share([b])
            matched.append(b)
            h = hash(key)
            n += self.block_size
            self._stats["hits"] += 1
        if matched:
            seq.kv_blocks.extend(matched)
            seq.seen_tokens = n
            seq.chain_hash = h
            seq.hashed_blocks = len(matched)
            self._stats["tokens_saved"] += n
        return n

    def record_tokens(self, seq: DSSequenceDescriptor,
                      tokens: Sequence[int]) -> None:
        """Advance the sequence's hash chain with tokens just written to
        its KV blocks; each block that becomes full is registered in the
        index (prompt and generated tokens alike — a later request whose
        prompt extends this conversation reuses both)."""
        if not self.prefix_cache_enabled:
            return
        # chain-state consistency guard: hashing is only valid when the
        # chain covers the sequence from position 0 (a sequence that was
        # mid-flight when the cache got enabled would otherwise register
        # its content under wrong positions). An inconsistent sequence
        # skips without extending state, so it stays skipped.
        if (seq.hashed_blocks * self.block_size + len(seq.pending_tokens)
                != seq.seen_tokens - len(tokens)):
            return
        seq.pending_tokens.extend(int(t) for t in tokens)
        while len(seq.pending_tokens) >= self.block_size:
            chunk = tuple(seq.pending_tokens[:self.block_size])
            del seq.pending_tokens[:self.block_size]
            key = (seq.chain_hash, chunk)
            seq.chain_hash = hash(key)
            block = seq.kv_blocks[seq.hashed_blocks]
            seq.hashed_blocks += 1
            self._register(key, block)

    def _register(self, key: tuple, block: int) -> None:
        if key in self._index or block in self._block_hash:
            return          # content already cached / block already indexed
        if (self.prefix_cache_max_blocks
                and len(self._index) >= self.prefix_cache_max_blocks
                and not self._evict(1)):
            return          # cache full of in-use blocks: skip registration
        self.allocator.share([block])        # the cache's own reference
        self._index[key] = block
        self._block_hash[block] = key
        # the registering sequence still holds its reference, so the block
        # enters the index referenced (not evictable) — it becomes
        # evictable in flush_sequence when the last sequence ref drops

    def _evict(self, n: int) -> int:
        """Drop up to ``n`` LRU unreferenced cached blocks; returns how
        many were evicted (their cache reference released → free list).

        With a KV tier configured (docs/SERVING.md "KV tiering") each
        evicted block's slab bytes spill to the host tier under its
        index key before the id returns to the free pool — safe even
        though release precedes the copy, because JAX arrays are
        immutable: the batched ``jnp.take`` below snapshots the pool
        content as of this call, and nothing rewrites the pool until a
        later forward. Only unreferenced full indexed blocks ever reach
        this path, so a referenced or partial block can never spill."""
        evicted = 0
        spill: List[tuple] = []         # (index key, block id)
        for key in list(self._index):
            if evicted >= n:
                break
            b = self._index[key]
            if self.allocator.ref_count(b) == 1:
                del self._index[key]
                del self._block_hash[b]
                if self._tier is not None:
                    spill.append((key, b))
                self.allocator.release([b])
                self._evictable -= 1
                self._stats["evictions"] += 1
                evicted += 1
        if spill:
            self._spill_blocks(spill)
        return evicted

    # -- tiered KV memory (docs/SERVING.md "KV tiering") ---------------------
    def configure_kv_tier(self, enabled: bool, host_bytes: int = 64 << 20,
                          disk_path: Optional[str] = None,
                          disk_bytes: int = 0) -> None:
        """Build (or tear down) the host-RAM/disk spill tier behind the
        prefix cache. Enabling requires the prefix cache — spill happens
        at cache eviction and restore at match, so a tier without the
        cache could never see a block. Disabling drops every spilled
        entry (and its disk files); re-enabling starts empty."""
        if self._tier is not None:
            self._tier.close()
            self._tier = None
        self._restore_times.clear()
        if not enabled:
            return
        if not self.prefix_cache_enabled:
            raise ValueError(
                "kv_tier requires the prefix cache: spill/restore happen "
                "at prefix-cache eviction/match (enable prefix_cache "
                "first)")
        from ..kv_tier import TieredKVStore

        self._tier = TieredKVStore(host_bytes, disk_path=disk_path,
                                   disk_max_bytes=disk_bytes)

    @property
    def kv_tier_enabled(self) -> bool:
        return self._tier is not None

    def _spill_blocks(self, spill: List[tuple]) -> None:
        """Copy evicted blocks' slabs device→host into the tier. One
        batched gather per pool tensor with the host copies started
        async for all slabs before any is materialized (the
        export_sequence idiom), then one tier entry per block."""
        ids = jnp.asarray([b for _, b in spill], dtype=jnp.int32)
        arrs = {name: jnp.take(pool, ids, axis=1)
                for name, pool in self.kv_cache.items()}
        for a in arrs.values():
            try:
                a.copy_to_host_async()
            except Exception:       # backend without async host copy
                pass
        host = {name: np.asarray(a) for name, a in arrs.items()}
        for i, (key, _) in enumerate(spill):
            self._tier.put(key, {name: host[name][:, i] for name in host})

    def _restore_chain(self, first_key: tuple, prompt_tokens: Sequence[int],
                       n: int, limit: int) -> int:
        """Restore the contiguous spilled run starting at ``first_key``:
        look the chain ahead (key ``i+1`` is ``hash(key_i)`` + the next
        token block — computable from the prompt alone, no device data
        needed), pop every consecutive tier entry, and scatter them all
        back in ONE batched ``.at[:, ids].set`` per pool tensor — the
        per-block dispatch overhead is what would otherwise eat the
        saved prefill at small block sizes. The scatters are dispatched
        asynchronously (JAX async dispatch): the call returns with the
        copies in flight and the forward that later reads the pool
        orders itself after them, so other requests' work overlaps the
        restore. Each restored block re-registers under its original
        key; blocks the pool has no room for are readmitted to the tier
        (the match then degrades to a re-prefill from that point,
        exactly the tier-less behavior). Returns how many blocks were
        restored."""
        bs = self.block_size
        h, pos = first_key[0], n
        # cap the lookahead at what the pool could possibly hold BEFORE
        # popping anything: a chain longer than free+evictable would
        # otherwise pop (and disk-read, CRC-check, then readmit and
        # disk-REWRITE) a tail that can never fit — O(chain) disk churn
        # per repeat request in exactly the pool-smaller-than-working-set
        # regime the tier exists for
        budget = self.allocator.free_blocks + self.evictable_blocks
        if self.prefix_cache_max_blocks:
            budget = min(budget,
                         max(0, self.prefix_cache_max_blocks
                             - len(self._index)) + self.evictable_blocks)
        if budget <= 0:
            if first_key in self._tier:
                # the tier HAS the block but the pool can't take it:
                # that is a miss the serving path experienced, even
                # though nothing was popped
                self._tier.stats["misses"] += 1
            return 0
        keys: List[tuple] = []
        entries: List[Dict[str, np.ndarray]] = []
        while pos + bs <= limit and len(entries) < budget:
            key = (h, tuple(prompt_tokens[pos:pos + bs]))
            if key in self._index:
                break               # back in device: the walk takes over
            entry = self._tier.get(key)
            if entry is None:
                break
            keys.append(key)
            entries.append(entry)
            h = hash(key)
            pos += bs
        if not entries:
            return 0
        t0 = time.perf_counter()
        m = len(entries)
        short = m - self.allocator.free_blocks
        if short > 0:
            self._evict(short)      # colder residents spill to make room
        m = min(m, self.allocator.free_blocks)
        if self.prefix_cache_max_blocks:
            allowed = self.prefix_cache_max_blocks - len(self._index)
            if allowed < m:
                self._evict(m - allowed)
                allowed = self.prefix_cache_max_blocks - len(self._index)
            m = min(m, max(0, allowed), self.allocator.free_blocks)
        for key, entry in zip(keys[m:], entries[m:]):
            # no room: keep them for a calmer moment (readmit keeps the
            # tier's hit/miss/spill counters describing what happened)
            self._tier.readmit(key, entry)
        if m <= 0:
            return 0
        blocks = self.allocator.allocate(m)
        ids = jnp.asarray(blocks, dtype=jnp.int32)
        for name, pool in self.kv_cache.items():
            stacked = np.stack([entries[i][name] for i in range(m)], axis=1)
            self.kv_cache[name] = pool.at[:, ids].set(
                jnp.asarray(stacked, dtype=pool.dtype))
        for key, b in zip(keys[:m], blocks):
            self._index[key] = b
            self._block_hash[b] = key
            self._evictable += 1    # only the cache's ref so far; the
            #                         match hit path shares + decrements
        self._tier.stats["restored"] += m
        self._restore_times.append(time.perf_counter() - t0)
        if len(self._restore_times) > 4096:     # bounded when undrained
            del self._restore_times[:2048]
        return m

    def tier_stats(self) -> Dict[str, int]:
        """Monotonic spill/restore/drop counters plus current host/disk
        occupancy — all zeros (same shape) without a tier, so consumers
        (replica delta publish, bench stamps) need no feature check."""
        from ..kv_tier import empty_tier_stats

        if self._tier is None:
            return empty_tier_stats()
        out = dict(self._tier.stats)
        out.update(self._tier.occupancy())
        return out

    def drain_restore_times(self) -> List[float]:
        """Wall-clock restore-batch dispatch durations (one per
        contiguous restored run) since the last drain — the serving
        layer observes them into ``kv_tier_restore_s``."""
        out, self._restore_times = self._restore_times, []
        return out

    # -- fleet KV locality (docs/SERVING.md "Fleet KV locality") -------------
    def prefix_digest(self, max_entries: int = 512) -> List[int]:
        """A bounded digest of the cached prefix content this replica
        could serve without prefilling: the chain hashes of the device
        index (MRU first — the entries most likely to survive until the
        routed request arrives) plus the host/disk tier's keys (newest
        first). The digest is advisory routing input: truncation or a
        raced eviction only costs a router credit its match walk would
        have earned, never correctness. Empty when the cache is off.

        The list() snapshots below are single C-level calls, the same
        cross-thread tolerance the serving layer's ``tier_stats`` reads
        already rely on — the router tick reads this while the replica
        worker mutates the index."""
        if not self.prefix_cache_enabled or max_entries <= 0:
            return []
        out: List[int] = []
        for key in reversed(list(self._index)):
            if len(out) >= max_entries:
                return out
            out.append(hash(key))
        if self._tier is not None:
            host_keys, disk_keys = self._tier.lru_keys()
            for keys in (host_keys, disk_keys):
                for key in reversed(keys):
                    if len(out) >= max_entries:
                        return out
                    if key and key[0] == "__preempt__":
                        continue    # parked sequences aren't prefix content
                    out.append(hash(key))
        return out

    def export_prefix_blocks(self, max_blocks: int = 64) -> List[tuple]:
        """Device→host copies of the hottest cached prefix blocks, MRU
        first, as ``(index_key, {pool_name: per-block ndarray})`` pairs
        in tier-entry format — the donor side of replica warm-up. One
        batched ``jnp.take`` gather per pool tensor (the
        ``_spill_blocks`` idiom); the donor's own index is untouched.
        Empty when the cache is off or empty."""
        if not self.prefix_cache_enabled or max_blocks <= 0:
            return []
        pairs = [(key, b) for key, b
                 in reversed(list(self._index.items()))][:max_blocks]
        if not pairs:
            return []
        ids = jnp.asarray([b for _, b in pairs], dtype=jnp.int32)
        arrs = {name: jnp.take(pool, ids, axis=1)
                for name, pool in self.kv_cache.items()}
        for a in arrs.values():
            try:
                a.copy_to_host_async()
            except Exception:       # backend without async host copy
                pass
        host = {name: np.asarray(a) for name, a in arrs.items()}
        return [(key, {name: host[name][:, i] for name in host})
                for i, (key, _) in enumerate(pairs)]

    def import_prefix_blocks(self, entries: List[tuple]) -> int:
        """Seed the prefix cache with exported blocks (the grown-replica
        side of warm-up): allocate, scatter every slab back in ONE
        batched ``.at[:, ids].set`` per pool tensor (the
        ``_restore_chain`` idiom), and register each block under its
        original chain key as cache-referenced-only (evictable — warmed
        content yields to real traffic on pressure). Entries already
        indexed or beyond the free-block / ``prefix_cache_max_blocks``
        budget are skipped. Returns how many blocks landed."""
        if not self.prefix_cache_enabled or not entries:
            return 0
        budget = self.allocator.free_blocks
        if self.prefix_cache_max_blocks:
            budget = min(budget, max(0, self.prefix_cache_max_blocks
                                     - len(self._index)))
        take: List[tuple] = []
        for key, entry in entries:
            if len(take) >= budget:
                break
            if key in self._index:
                continue
            take.append((key, entry))
        if not take:
            return 0
        m = len(take)
        blocks = self.allocator.allocate(m)
        ids = jnp.asarray(blocks, dtype=jnp.int32)
        for name, pool in self.kv_cache.items():
            stacked = np.stack([take[i][1][name] for i in range(m)], axis=1)
            self.kv_cache[name] = pool.at[:, ids].set(
                jnp.asarray(stacked, dtype=pool.dtype))
        for (key, _), b in zip(take, blocks):
            self._index[key] = b
            self._block_hash[b] = key
            self._evictable += 1    # the allocate ref is the cache's ref,
            #                         exactly as in _restore_chain
        return m

    def clear_prefix_cache(self) -> None:
        """Drop every index entry, releasing the cache's references.
        Blocks still shared by live sequences stay allocated until those
        sequences flush; unreferenced ones return to the free list. A
        configured KV tier is emptied too (its entries are keyed by the
        chain hashes this wipe invalidates only in spirit — content keys
        stay valid — but a cleared cache should not keep shadow
        residency in host RAM)."""
        for key, b in list(self._index.items()):
            self.allocator.release([b])
        self._index.clear()
        self._block_hash.clear()
        self._evictable = 0
        if self._tier is not None:
            self._tier.clear()
