"""Paged-KV block allocator (reference inference/v2/ragged/blocked_allocator.py).

Free-list allocator over a fixed pool of KV blocks; the reference implements
this as a linked list in a torch tensor — host-side Python is equally fast
at this scale and keeps the device program pure.

Blocks carry a reference count so the prefix cache (``manager.py``) can
share one immutable KV block between many sequences: ``allocate`` hands out
blocks at refcount 1, ``share`` adds a reference, ``release`` drops one and
returns the block to the free list only when the count reaches zero.
``free`` is the historical name for ``release`` and keeps the old
double-free ``ValueError``; the allocated-set (the refcount dict) makes
that check O(1) per block instead of a rebuild of the whole free list.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Sequence


class BlockedAllocator:
    def __init__(self, num_blocks: int, bytes_per_block: int = 0):
        if num_blocks < 1:
            raise ValueError(f"need at least one block, got {num_blocks}")
        self._num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks))
        self._refs: Dict[int, int] = {}      # allocated block -> refcount
        # HBM bytes one block costs across layers (K+V slabs + scale
        # entries under kv_quant — inference/v2/kv_quant.py); 0 = unknown.
        # Lets occupancy() speak bytes, the unit admission budgets and
        # dashboards actually care about.
        self.bytes_per_block = int(bytes_per_block)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def total_blocks(self) -> int:
        return self._num_blocks

    def occupancy(self) -> Dict[str, int]:
        """One consistent snapshot of pool occupancy — the single home
        for the counts admission control, the prefix cache, serving
        metrics (``kv_blocks_in_use``/``kv_bytes_in_use`` gauges) and the
        bench phases previously derived ad hoc."""
        in_use = self._num_blocks - len(self._free)
        bpb = self.bytes_per_block
        return {"total_blocks": self._num_blocks,
                "free_blocks": len(self._free),
                "in_use_blocks": in_use,
                "bytes_per_block": bpb,
                "bytes_in_use": in_use * bpb,
                "bytes_total": self._num_blocks * bpb}

    def ref_count(self, block: int) -> int:
        """Current refcount (0 for free/unknown blocks)."""
        return self._refs.get(block, 0)

    def is_shared(self, block: int) -> bool:
        """More than one holder (prefix cache and/or other sequences) —
        the owner must not mutate the block's KV in place."""
        return self._refs.get(block, 0) > 1

    def allocate(self, num_blocks: int) -> List[int]:
        if num_blocks > len(self._free):
            raise ValueError(
                f"cannot allocate {num_blocks} blocks ({len(self._free)} free)")
        out, self._free = self._free[:num_blocks], self._free[num_blocks:]
        for b in out:
            self._refs[b] = 1
        return out

    def share(self, blocks: Sequence[int]) -> None:
        """Add one reference to each (already-allocated) block."""
        for b in blocks:
            if b not in self._refs:
                raise ValueError(f"cannot share unallocated block {b}")
        for b in blocks:
            self._refs[b] += 1

    def release(self, blocks: Sequence[int]) -> List[int]:
        """Drop one reference per block; blocks reaching refcount 0 go back
        to the free list. Returns the blocks actually freed. Validates the
        whole call before mutating, so an invalid/double release leaves the
        allocator untouched."""
        counts = Counter(blocks)
        for b, n in counts.items():
            if b < 0 or b >= self._num_blocks or n > self._refs.get(b, 0):
                raise ValueError(f"invalid or double free of block {b}")
        freed: List[int] = []
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                freed.append(b)
        self._free.extend(freed)
        return freed

    def free(self, blocks: Sequence[int]) -> None:
        """Historical single-owner API: identical to :meth:`release`."""
        self.release(blocks)
