"""Paged-KV block allocator (reference inference/v2/ragged/blocked_allocator.py).

Free-list allocator over a fixed pool of KV blocks; the reference implements
this as a linked list in a torch tensor — host-side Python is equally fast
at this scale and keeps the device program pure.
"""

from __future__ import annotations

from typing import List


class BlockedAllocator:
    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"need at least one block, got {num_blocks}")
        self._num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def total_blocks(self) -> int:
        return self._num_blocks

    def allocate(self, num_blocks: int) -> List[int]:
        if num_blocks > len(self._free):
            raise ValueError(
                f"cannot allocate {num_blocks} blocks ({len(self._free)} free)")
        out, self._free = self._free[:num_blocks], self._free[num_blocks:]
        return out

    def free(self, blocks: List[int]) -> None:
        seen = set(self._free)
        for b in blocks:
            if b < 0 or b >= self._num_blocks or b in seen:
                raise ValueError(f"invalid or double free of block {b}")
            seen.add(b)
        self._free.extend(blocks)
