"""Greedy-lossless verification of a speculative chunk.

The scheduler packs ``chunk = [t0, d1, ..., dK]`` into one ragged step:
``t0`` is the *certain* token (the target's own greedy sample from the
previous step's logits), ``d1..dK`` the proposer's drafts. The target
forward returns per-position logits for the whole chunk; position ``j``'s
argmax is the target's greedy choice for the token *after* ``chunk[j]``.
A draft ``d_{j+1}`` is accepted iff it equals that argmax — i.e. iff plain
greedy decoding would have produced exactly it. Acceptance stops at the
first disagreement, so the emitted stream is byte-identical to greedy
decoding with speculation off; only the number of forwards changes.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def verify_greedy(chunk: Sequence[int],
                  logits_rows: np.ndarray) -> Tuple[List[int], int]:
    """Verify one speculative chunk against the target's logits.

    ``chunk``: the ``1 + K`` tokens fed this step (certain token + drafts).
    ``logits_rows``: ``[>= len(chunk), vocab]`` per-position target logits
    for the chunk (extra padded rows are ignored).

    Returns ``(emitted, last_idx)``: the tokens proven correct this step —
    ``chunk[0]`` plus the longest agreeing draft prefix — and the index of
    the logits row holding the distribution *after* the last emitted token
    (its argmax is the next certain token; the scheduler stores it as
    ``last_logits``, which is also where the "+1 bonus token" of
    speculative decoding comes from: one extra token is always known after
    a fully-accepted chunk).
    """
    n = len(chunk)
    emitted = [int(chunk[0])]
    # one argmax over the chunk's rows; row j answers "what follows
    # chunk[:j+1]?"
    greedy = np.argmax(np.asarray(logits_rows[:n]), axis=-1)
    for j in range(1, n):
        if int(chunk[j]) != int(greedy[j - 1]):
            break
        emitted.append(int(chunk[j]))
    return emitted, len(emitted) - 1
