"""Speculative decoding for the v2 ragged engine (docs/SERVING.md
"Speculative decoding").

Greedy-lossless: a :class:`DraftProposer` guesses the next K tokens, the
scheduler packs them into one ragged step (structurally a K-token prefill
chunk), the target model's per-position argmax verifies them, and the
longest agreeing prefix is accepted — rejected tokens are rolled back via
``DSStateManager.trim_sequence``. The emitted stream is byte-identical to
plain greedy decoding; speculation only changes how many forwards it takes.

The reference DeepSpeed (0.12.3) has no speculative path — see
docs/DIVERGENCES.md.
"""

from .proposer import (DraftModelProposer, DraftProposer,  # noqa: F401
                       NGramProposer)
from .verify import verify_greedy  # noqa: F401

__all__ = ["DraftProposer", "NGramProposer", "DraftModelProposer",
           "verify_greedy"]
