"""Draft proposers for speculative decoding.

Two implementations of the :class:`DraftProposer` protocol:

- :class:`NGramProposer` — prompt-lookup self-drafting: the sequence's own
  token history is the draft model (match the current suffix n-gram against
  an earlier occurrence and propose what followed it). No second model, no
  device work, deterministic — repetition-heavy workloads (code, extraction,
  multi-turn chat quoting context) accept most drafts for free.
- :class:`DraftModelProposer` — a small same-family model runs greedily K
  steps ahead on its own :class:`InferenceEngineV2`. Rollback of rejected
  drafts reuses the same ``trim_sequence`` machinery as the target engine.

Proposers are *advisory*: any (possibly empty) token list is correct —
verification never trusts them. They may keep per-uid state; the scheduler
calls :meth:`release` when a sequence finishes or is cancelled.
"""

from __future__ import annotations

from typing import Dict, List, Protocol, Sequence, runtime_checkable

import numpy as np


@runtime_checkable
class DraftProposer(Protocol):
    def propose(self, uid: int, context: Sequence[int],
                k: int) -> List[int]:
        """Up to ``k`` draft tokens predicted to follow ``context``
        (the sequence's full token history: prompt + emitted tokens,
        including the just-sampled one). Fewer (or none) is always legal."""
        ...

    def release(self, uid: int) -> None:
        """Drop any per-sequence state (finish/cancel/expiry)."""
        ...


class NGramProposer:
    """Prompt-lookup decoding (self-speculation).

    Finds the longest suffix of the context (``ngram_min..ngram_max``
    tokens) that also occurs earlier in the context and proposes the
    tokens that followed that occurrence. Longer suffixes win; within a
    suffix length, the occurrence with the longest continuation runway
    (up to the k requested drafts) wins, most recent on ties — a match
    one cycle period from the end would otherwise cap every proposal at
    one period. ``max_history`` bounds the scan (O(max_history) integer
    compares per call) regardless of context length.
    """

    def __init__(self, ngram_max: int = 3, ngram_min: int = 1,
                 max_history: int = 4096):
        if ngram_min < 1 or ngram_max < ngram_min:
            raise ValueError(f"need 1 <= ngram_min <= ngram_max, got "
                             f"{ngram_min}..{ngram_max}")
        self.ngram_max = ngram_max
        self.ngram_min = ngram_min
        self.max_history = max_history

    @property
    def context_window(self):
        """Lookback bound — the scheduler passes only this many trailing
        context tokens, skipping the full-history rebuild per call."""
        return self.max_history

    def propose(self, uid: int, context: Sequence[int], k: int) -> List[int]:
        ctx = list(context[-self.max_history:])
        L = len(ctx)
        if k <= 0 or L < self.ngram_min + 1:
            return []
        # one backward pass: at each earlier occurrence of the last token,
        # extend the suffix match leftward. Longest suffix wins; within a
        # length, the most recent occurrence with a full k-token
        # continuation (a near-the-end match — one cycle period back in a
        # repetition loop — may leave fewer than k tokens of runway, in
        # which case an older occurrence drafts deeper). The miss path is
        # O(L) integer compares — no per-candidate slice allocations —
        # so non-repetitive traffic pays near nothing per decode row.
        last = ctx[-1]
        n_cap = min(self.ngram_max, L - 1)
        best_n, best_cont = 0, []
        for j in range(L - 2, -1, -1):        # j: candidate match of `last`
            if ctx[j] != last:
                continue
            n = 1
            while n < n_cap and n <= j and ctx[j - n] == ctx[L - 1 - n]:
                n += 1
            if n < self.ngram_min:
                continue
            cont = ctx[j + 1:j + 1 + k]
            if n > best_n or (n == best_n and len(cont) > len(best_cont)):
                best_n, best_cont = n, cont
            if best_n == n_cap and len(best_cont) >= k:
                break                         # nothing can beat this
        return best_cont

    def release(self, uid: int) -> None:  # stateless
        pass


class DraftModelProposer:
    """Greedy K-step lookahead with a small draft model.

    ``engine`` is an :class:`InferenceEngineV2` over the draft model (same
    tokenizer family as the target — token ids must mean the same thing).
    The proposer mirrors each sequence's context into the draft engine
    incrementally: on every call it trims the draft KV back to the longest
    common prefix of what it fed and the (authoritative) target context —
    this is where rejected drafts from the previous round roll back, via
    the same ``trim_sequence`` path the target engine uses — then feeds the
    missing context tokens and decodes ``k`` tokens greedily.

    Cost model: the per-uid ``propose`` hook runs k serial single-token
    draft forwards per decode row per step — S·k draft dispatches for S
    running sequences, *not* one batched draft forward. That is the right
    trade for latency-sensitive, low-concurrency serving with a much
    cheaper draft; at high batch sizes the dispatch overhead erodes the
    saved target forwards, and the n-gram proposer (zero device work) or
    no speculation wins. Batched draft proposal needs a batch-level
    proposer hook — future work (docs/SERVING.md).
    """

    # needs the FULL context from position 0 (the incremental mirror diffs
    # against it) — no bounded lookback
    context_window = None

    def __init__(self, engine):
        self.engine = engine
        self._fed: Dict[int, List[int]] = {}      # uid -> tokens in draft KV
        self._last: Dict[int, np.ndarray] = {}    # uid -> last logits row

    def propose(self, uid: int, context: Sequence[int], k: int) -> List[int]:
        ctx = list(context)
        if k <= 0 or not ctx:
            return []
        # roll rejected drafts back FIRST — even when the horizon check
        # below skips proposing, stale refuted tokens must not keep
        # occupying draft-engine KV blocks (or desync ``fed``)
        fed = self._fed.setdefault(uid, [])
        p = 0
        while p < len(fed) and p < len(ctx) and fed[p] == ctx[p]:
            p += 1
        if p < len(fed):
            self.engine.trim_sequence(uid, len(fed) - p)
            del fed[p:]
        # the draft model cannot see past its own horizon (it must be able
        # to run ctx + k tokens); give up rather than overflow it
        if len(ctx) + k > self.engine.model.cfg.max_seq_len:
            return []
        # every draft-engine put defers the prefix-cache chain commit: the
        # fed tokens include drafts that the next call may trim back, and
        # trim_sequence refuses to cut into chain-indexed blocks — with a
        # prefix-cache-enabled draft engine the chain must simply never
        # advance (the draft KV is scratch space, not reusable prefill)
        chunk_cap = self.engine.config.max_chunk_tokens
        pos = len(fed)
        while pos < len(ctx):
            take = min(chunk_cap, len(ctx) - pos)
            self._last[uid] = np.asarray(
                self.engine.put([uid], [ctx[pos:pos + take]],
                                defer_commit=True))[0]
            fed.extend(ctx[pos:pos + take])
            pos += take
        if uid not in self._last:                 # ctx fully cached, no
            return []                             # logits to draft from
        drafts: List[int] = []
        for _ in range(k):
            t = int(np.argmax(self._last[uid]))
            drafts.append(t)
            self._last[uid] = np.asarray(
                self.engine.put([uid], [[t]], defer_commit=True))[0]
            fed.append(t)
        return drafts

    def release(self, uid: int) -> None:
        self._fed.pop(uid, None)
        self._last.pop(uid, None)
        if self.engine.state_manager.get_sequence(uid) is not None:
            self.engine.flush(uid)
