from .sharded_moe import TopKGate, top1gating, top2gating, moe_dispatch_combine  # noqa: F401
from .layer import MoE  # noqa: F401
