"""Dropless grouped-GEMM MoE (megablox-style).

Counterpart of reference ``inference/v2/kernels/cutlass_ops/moe_gemm``
(CUTLASS grouped GEMM over per-expert token groups) and the capacity-free
execution style of modern MoE serving. The GShard capacity path
(``sharded_moe.py``) pads every expert to a fixed capacity — simple to
shard, but wastes FLOPs on padding and drops overflow tokens. This path
sorts tokens by their routed expert and runs ``jax.lax.ragged_dot``
(TPU-native grouped matmul — the same op Pallas megablox kernels back)
over the true group sizes: no padding FLOPs, no dropped tokens.

Two formulations:

- ``dropless_moe_mlp`` — single-shard (no expert mesh axis): one sort +
  three ``ragged_dot`` calls.
- ``dropless_moe_mlp_ep`` — expert-parallel (round 5): a *partial-manual*
  ``shard_map`` over just the ``expert`` axis (every other mesh axis stays
  under GSPMD). Activations are replicated over the expert axis, so each
  shard already holds every token row: it sorts the tokens routed to ITS
  local experts to the front (everything else lands in a trailing dummy
  group backed by zero weights), runs the per-shard ``ragged_dot``
  grouped matmul, and a ``psum`` over the expert axis combines each
  token's single live contribution — no capacity padding, no dropped
  tokens, and the only collective is the combine. A ``ragged_all_to_all``
  dispatch over expert-sharded activations would cut per-shard compute
  from O(N) to O(N/ep) rows, but XLA:CPU cannot execute it yet, which
  would leave the path untestable on the CI mesh.

Reference counterpart: ``moe/sharded_moe.py:477`` (EP all-to-all around
expert compute) + ``inference/v2/kernels/cutlass_ops/moe_gemm/moe_gemm.cu``
(per-rank grouped GEMM). The reference cannot express the fused
gather-sort-ragged-scatter program at all — its dispatch is fixed-capacity.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def dropless_moe_mlp(tokens: jax.Array, router_logits: jax.Array,
                     w_in: jax.Array, w_out: jax.Array,
                     w_gate: Optional[jax.Array] = None,
                     activation: str = "gelu",
                     dtype=None) -> Tuple[jax.Array, jax.Array]:
    """Top-1 dropless MoE FFN.

    tokens [N, H]; router_logits [N, E] (fp32); w_in [E, H, M];
    w_out [E, M, H]; w_gate [E, H, M] for SwiGLU. Returns
    (out [N, H], aux_loss) — aux is the GShard load-balancing loss
    (E · Σ_e fraction_tokens_e · fraction_probs_e), same as top1gating.
    """
    N, H = tokens.shape
    E = router_logits.shape[-1]
    dtype = dtype or tokens.dtype
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(router_logits, axis=-1)          # [N]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    # load-balance aux (reference sharded_moe.py top1gating l_aux)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(expert, E, dtype=jnp.float32), axis=0)
    l_aux = jnp.sum(me * ce) * E

    # sort tokens by expert; group sizes are the per-expert counts
    order = jnp.argsort(expert)                          # stable
    sorted_tokens = tokens[order].astype(dtype)
    group_sizes = jnp.zeros((E,), jnp.int32).at[expert].add(1)

    out_sorted = _ragged_expert_ffn(sorted_tokens, group_sizes, w_in,
                                    w_out, w_gate, activation, dtype)

    # unsort + gate scale
    out = jnp.zeros_like(out_sorted).at[order].set(out_sorted)
    return out * gate[:, None].astype(dtype), l_aux


def _ragged_expert_ffn(st, gs, w_in, w_out, w_gate, activation, dtype):
    """Grouped FFN over expert-sorted tokens ``st`` with group sizes
    ``gs`` (one trailing dummy group allowed when the weights carry an
    extra zero expert)."""
    h = lax.ragged_dot(st, w_in.astype(dtype), gs)
    if w_gate is not None and activation == "silu":
        g = lax.ragged_dot(st, w_gate.astype(dtype), gs)
        h = jax.nn.silu(g) * h
    elif activation == "relu":
        h = jax.nn.relu(h)
    else:
        h = jax.nn.gelu(h, approximate=activation != "gelu_exact")
    return lax.ragged_dot(h, w_out.astype(dtype), gs)


def dropless_moe_mlp_ep(tokens: jax.Array, router_logits: jax.Array,
                        w_in: jax.Array, w_out: jax.Array,
                        w_gate: Optional[jax.Array] = None,
                        *, mesh, axis_name: str = "expert",
                        activation: str = "gelu",
                        dtype=None) -> Tuple[jax.Array, jax.Array]:
    """Expert-parallel top-1 dropless MoE FFN (module docstring).

    tokens [N, H] and router_logits [N, E] are ordinary GSPMD arrays
    (sharded over data axes); w_in/w_out/w_gate [E, ...] carry the
    ``expert`` mesh axis on dim 0. Returns (out [N, H], aux_loss).
    """
    from ..compat import shard_map
    from jax.sharding import PartitionSpec as P

    dtype = dtype or tokens.dtype
    E = router_logits.shape[-1]
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(router_logits, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    # load-balance aux from the global routing stats (same formula as the
    # single-shard path; computed under GSPMD, not inside the shard_map)
    me_frac = jnp.mean(probs, axis=0)
    ce_frac = jnp.mean(jax.nn.one_hot(expert, E, dtype=jnp.float32), axis=0)
    l_aux = jnp.sum(me_frac * ce_frac) * E

    def ep_core(tok, exp, w_in, w_out, w_gate):
        # Activations are REPLICATED over the expert axis (the engine's
        # batch sharding spans data/fsdp only), so every shard already
        # holds all N token rows — no dispatch gather needed. Each shard
        # sorts the tokens routed to ITS experts to the front, runs the
        # grouped GEMM over N rows (non-local rows land in a zero-weight
        # dummy group), and a psum over the expert axis combines each
        # token's single live contribution. Per-shard compute is O(N)
        # rows; the ideal O(N/ep) would need dynamic shapes (or a
        # ragged_all_to_all dispatch with expert-sharded activations).
        shard = lax.axis_index(axis_name)
        el = w_in.shape[0]                       # local experts E // ep
        e0 = shard * el
        local = (exp >= e0) & (exp < e0 + el)
        key = jnp.where(local, exp - e0, el)     # el = dummy group
        order = jnp.argsort(key)                 # stable: keeps token order
        st = tok[order].astype(dtype)
        gs = jnp.zeros((el + 1,), jnp.int32).at[key].add(1)
        # dummy expert el carries zero weights → exact zero output for
        # tokens owned by other shards (gelu/silu·0/relu all fix 0)
        pad = lambda w: (None if w is None else                # noqa: E731
                         jnp.concatenate([w, jnp.zeros_like(w[:1])], 0))
        o = _ragged_expert_ffn(st, gs, pad(w_in), pad(w_out), pad(w_gate),
                               activation, dtype)
        full = jnp.zeros_like(o).at[order].set(o)
        # combine: sum over expert shards (exactly one is nonzero per
        # token) — the EP combine collective; output stays replicated
        return lax.psum(full, axis_name)

    wspec = P(axis_name)
    out = shard_map(ep_core, mesh=mesh, axis_names={axis_name},
                    in_specs=(P(), P(), wspec, wspec,
                              P() if w_gate is None else wspec),
                    out_specs=P(), check_vma=False)(
        tokens, expert, w_in, w_out, w_gate)
    return out * gate[:, None].astype(dtype), l_aux
