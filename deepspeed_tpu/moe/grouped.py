"""Dropless grouped-GEMM MoE (megablox-style).

Counterpart of reference ``inference/v2/kernels/cutlass_ops/moe_gemm``
(CUTLASS grouped GEMM over per-expert token groups) and the capacity-free
execution style of modern MoE serving. The GShard capacity path
(``sharded_moe.py``) pads every expert to a fixed capacity — simple to
shard, but wastes FLOPs on padding and drops overflow tokens. This path
sorts tokens by their routed expert and runs ``jax.lax.ragged_dot``
(TPU-native grouped matmul — the same op Pallas megablox kernels back)
over the true group sizes: no padding FLOPs, no dropped tokens.

Single-device (per-shard) formulation: with expert parallelism the
capacity-einsum path remains the sharded implementation (its all-to-all is
the EP collective); ``ragged_dot``'s group dimension cannot span an
``expert`` mesh axis. That mirrors the reference, where the cutlass
grouped GEMM also runs per-rank after dispatch.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def dropless_moe_mlp(tokens: jax.Array, router_logits: jax.Array,
                     w_in: jax.Array, w_out: jax.Array,
                     w_gate: Optional[jax.Array] = None,
                     activation: str = "gelu",
                     dtype=None) -> Tuple[jax.Array, jax.Array]:
    """Top-1 dropless MoE FFN.

    tokens [N, H]; router_logits [N, E] (fp32); w_in [E, H, M];
    w_out [E, M, H]; w_gate [E, H, M] for SwiGLU. Returns
    (out [N, H], aux_loss) — aux is the GShard load-balancing loss
    (E · Σ_e fraction_tokens_e · fraction_probs_e), same as top1gating.
    """
    N, H = tokens.shape
    E = router_logits.shape[-1]
    dtype = dtype or tokens.dtype
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(router_logits, axis=-1)          # [N]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    # load-balance aux (reference sharded_moe.py top1gating l_aux)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(expert, E, dtype=jnp.float32), axis=0)
    l_aux = jnp.sum(me * ce) * E

    # sort tokens by expert; group sizes are the per-expert counts
    order = jnp.argsort(expert)                          # stable
    sorted_tokens = tokens[order].astype(dtype)
    group_sizes = jnp.zeros((E,), jnp.int32).at[expert].add(1)

    h = lax.ragged_dot(sorted_tokens, w_in.astype(dtype), group_sizes)
    if w_gate is not None and activation == "silu":
        g = lax.ragged_dot(sorted_tokens, w_gate.astype(dtype), group_sizes)
        h = jax.nn.silu(g) * h
    elif activation == "relu":
        h = jax.nn.relu(h)
    else:
        h = jax.nn.gelu(h, approximate=activation != "gelu_exact")
    out_sorted = lax.ragged_dot(h, w_out.astype(dtype), group_sizes)

    # unsort + gate scale
    out = jnp.zeros_like(out_sorted).at[order].set(out_sorted)
    return out * gate[:, None].astype(dtype), l_aux
