"""MoE layer: gate + experts + dispatch, with expert-parallel sharding.

Counterpart of reference ``deepspeed/moe/layer.py:16`` (``MoE``) and
``moe/experts.py:10`` (``Experts``). Experts are a stacked parameter tree
with leading dim = num_experts, sharded over the ``expert`` mesh axis by
``parallel/sharding.py`` (logical axis "expert") — the reference's expert
process groups (utils/groups.py:113,161) become that axis.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..parallel.sharding import spec
from .sharded_moe import TopKGate, moe_dispatch_combine


class MoE:
    """Functional MoE FFN block.

    ``init(rng) -> params``; ``apply(params, x, rng, train) ->
    (y, l_aux, exp_counts)`` with x [..., M] (leading dims flattened to the
    token dim internally).
    """

    def __init__(self, hidden_size: int, intermediate_size: int,
                 num_experts: int, k: int = 1, capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0, min_capacity: int = 4,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True, activation: str = "gelu",
                 dtype=jnp.float32):
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.activation = activation
        self.dtype = dtype
        self.gate = TopKGate(hidden_size, num_experts, k, capacity_factor,
                             eval_capacity_factor, min_capacity,
                             noisy_gate_policy, drop_tokens)

    def init(self, rng):
        E, M, F = self.num_experts, self.hidden_size, self.intermediate_size
        k1, k2, k3, kg = jax.random.split(rng, 4)
        std = 0.02
        p = {
            "gate": self.gate.init(kg),
            "w_in": std * jax.random.normal(k1, (E, M, F), jnp.float32),
            "w_out": std * jax.random.normal(k2, (E, F, M), jnp.float32),
        }
        if self.activation == "silu":
            p["w_gate"] = std * jax.random.normal(k3, (E, M, F), jnp.float32)
        return p

    def param_specs(self):
        s = {
            "gate": {"wg": spec("embed", None)},
            "w_in": spec("expert", "embed", "mlp"),
            "w_out": spec("expert", "mlp", "embed"),
        }
        if self.activation == "silu":
            s["w_gate"] = spec("expert", "embed", "mlp")
        return s

    def _expert_fn(self, params):
        from .sharded_moe import expert_mlp

        def fn(expert_in):  # [E, C, M]
            return expert_mlp(expert_in, params["w_in"], params["w_out"],
                              params.get("w_gate"), self.activation, self.dtype)

        return fn

    def apply(self, params, x, rng=None, train: bool = True):
        orig_shape = x.shape
        M = orig_shape[-1]
        tokens = x.reshape(-1, M)
        l_aux, combine, dispatch, exp_counts = self.gate(
            params["gate"], tokens, rng, train)
        y = moe_dispatch_combine(tokens.astype(self.dtype),
                                 combine, dispatch,
                                 self._expert_fn(params))
        return y.reshape(orig_shape), l_aux, exp_counts
