"""Sharded MoE: top-k gating + capacity-based dispatch/combine.

Counterpart of reference ``deepspeed/moe/sharded_moe.py`` (``top1gating``
:184, ``top2gating`` :282, ``TopKGate`` :348, ``MOELayer.forward`` :477 with
its two ``_AllToAll.apply`` :95 around expert compute). The TPU-native
design is the original GShard formulation the reference itself derives from:
dispatch and combine are einsums against a [tokens, experts, capacity]
one-hot; with the expert dim of the expert parameters sharded over the
``expert`` mesh axis and tokens sharded over data axes, XLA lowers the two
einsums to exactly the reference's all-to-all pair — no hand-written
dispatch code.

Aux (load-balancing) loss follows the reference: ``l_aux = E · Σ_e me·ce``
where ``me`` is mean gate prob and ``ce`` the fraction of tokens routed to
expert e (sharded_moe.py:249).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _one_hot(idx, n):
    return jax.nn.one_hot(idx.astype(jnp.int32), n, dtype=jnp.float32)


def top1gating(logits, capacity_factor: float = 1.0, min_capacity: int = 4,
               rng: Optional[jax.Array] = None, noisy_gate_policy: Optional[str] = None,
               drop_tokens: bool = True):
    """Top-1 gating (reference sharded_moe.py:184).

    logits [S, E] → (l_aux, combine [S,E,C], dispatch [S,E,C] bool, exp_counts).
    """
    S, E = logits.shape
    if noisy_gate_policy == "RSample" and rng is not None:
        # Gumbel-argmax = sampling from softmax(logits) (reference
        # sharded_moe.py:194 gumbel_rsample)
        logits_w_noise = logits + jax.random.gumbel(rng, logits.shape)
    else:
        logits_w_noise = logits
    gates = jax.nn.softmax(logits, axis=-1)
    idx1 = jnp.argmax(logits_w_noise, axis=-1)                 # [S]
    mask1 = _one_hot(idx1, E)                                  # [S, E]
    C = _capacity(S, E, capacity_factor, min_capacity) if drop_tokens else S

    # position of each token within its expert's queue
    locations1 = jnp.cumsum(mask1, axis=0) - mask1             # [S, E]
    loc1 = jnp.sum(locations1 * mask1, axis=-1)                # [S]

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    keep = (loc1 < C) & (mask1.sum(-1) > 0)
    gate1 = jnp.sum(gates * mask1, axis=-1)                    # [S]
    combine = (gate1 * keep)[:, None, None] * mask1[:, :, None] \
        * _one_hot(loc1, C)[:, None, :]                        # [S, E, C]
    dispatch = combine > 0
    exp_counts = jnp.sum(mask1, axis=0)
    return l_aux, combine, dispatch, exp_counts


def top2gating(logits, capacity_factor: float = 1.0, min_capacity: int = 4,
               rng: Optional[jax.Array] = None):
    """Top-2 gating (reference sharded_moe.py:282): second expert chosen from
    masked logits; gates renormalized over the chosen pair."""
    S, E = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)
    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(idx1, E)
    logits_no1 = jnp.where(mask1 > 0, -jnp.inf, logits)
    if rng is not None:
        # Gumbel-noise second-expert sampling (reference sharded_moe.py:297)
        logits_no1 = logits_no1 + jax.random.gumbel(rng, logits.shape)
    idx2 = jnp.argmax(logits_no1, axis=-1)
    mask2 = _one_hot(idx2, E)

    C = _capacity(S, E, capacity_factor * 2, min_capacity)

    locations1 = jnp.cumsum(mask1, axis=0) - mask1
    locations2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0, keepdims=True)
    loc1 = jnp.sum(locations1 * mask1, axis=-1)
    loc2 = jnp.sum(locations2 * mask2, axis=-1)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    keep1 = loc1 < C
    keep2 = loc2 < C
    g1 = jnp.sum(gates * mask1, axis=-1) * keep1
    g2 = jnp.sum(gates * mask2, axis=-1) * keep2
    denom = jnp.clip(g1 + g2, 1e-9, None)
    g1, g2 = g1 / denom, g2 / denom

    combine = g1[:, None, None] * mask1[:, :, None] * _one_hot(loc1, C)[:, None, :] \
        + g2[:, None, None] * mask2[:, :, None] * _one_hot(loc2, C)[:, None, :]
    dispatch = combine > 0
    exp_counts = jnp.sum(mask1 + mask2, axis=0)
    return l_aux, combine, dispatch, exp_counts


class TopKGate:
    """Gate module (reference sharded_moe.py:348): linear router + top-k."""

    def __init__(self, hidden_size: int, num_experts: int, k: int = 1,
                 capacity_factor: float = 1.0, eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4, noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True):
        if k not in (1, 2):
            raise ValueError("Only top-1 and top-2 gating supported (reference parity)")
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens

    def init(self, rng):
        scale = 1.0 / math.sqrt(self.hidden_size)
        return {"wg": scale * jax.random.normal(
            rng, (self.hidden_size, self.num_experts), jnp.float32)}

    def __call__(self, params, x, rng=None, train: bool = True):
        """x [S, M] → (l_aux, combine [S,E,C], dispatch, exp_counts)."""
        logits = x.astype(jnp.float32) @ params["wg"]
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(logits, cf, self.min_capacity, rng,
                              self.noisy_gate_policy if train else None,
                              self.drop_tokens)
        return top2gating(logits, cf, self.min_capacity, rng)


def expert_mlp(expert_in, w_in, w_out, w_gate=None, activation: str = "gelu",
               dtype=None):
    """Per-expert FFN over dispatched tokens [E, C, M] → [E, C, M] (the
    expert compute of reference moe/experts.py:10). Shared by the MoE layer
    and the in-model MoE path."""
    if dtype is None:
        dtype = expert_in.dtype
    w_in = w_in.astype(dtype)
    h = jnp.einsum("ecm,emf->ecf", expert_in, w_in)
    if activation == "silu":
        g = jnp.einsum("ecm,emf->ecf", expert_in, w_gate.astype(dtype))
        h = jax.nn.silu(g) * h
    else:
        h = jax.nn.gelu(h, approximate=True)
    return jnp.einsum("ecf,efm->ecm", h, w_out.astype(dtype))


def moe_dispatch_combine(x, combine, dispatch, expert_fn):
    """The GShard einsum pair (reference MOELayer.forward sharded_moe.py:477).

    x [S, M]; combine/dispatch [S, E, C]; expert_fn: [E, C, M] → [E, C, M]
    (expert dim sharded over the ``expert`` mesh axis ⇒ XLA inserts the
    all-to-alls here).
    """
    expert_in = jnp.einsum("sec,sm->ecm", dispatch.astype(x.dtype), x)
    expert_out = expert_fn(expert_in)
    return jnp.einsum("sec,ecm->sm", combine.astype(x.dtype), expert_out)
