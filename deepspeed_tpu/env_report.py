"""`dstpu_report` — environment + capability report.

Counterpart of reference ``deepspeed/env_report.py`` (``ds_report``): prints
versions, devices, and which native/Pallas features are available, replacing
the reference's op-builder compatibility table with the TPU feature set.
"""

from __future__ import annotations

import sys


GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def _try(fn):
    try:
        return fn(), True
    except Exception as e:
        return str(e), False


def main():
    print("-" * 60)
    print("deepspeed_tpu environment report")
    print("-" * 60)

    import deepspeed_tpu

    print(f"deepspeed_tpu version ... {deepspeed_tpu.__version__}")
    print(f"python version .......... {sys.version.split()[0]}")

    ver, ok = _try(lambda: __import__("jax").__version__)
    print(f"jax ..................... {ver if ok else RED_NO}")
    ver, ok = _try(lambda: __import__("jaxlib").__version__)
    print(f"jaxlib .................. {ver if ok else RED_NO}")

    def devices():
        import jax

        return [(d.platform, getattr(d, "device_kind", "?")) for d in jax.devices()]

    devs, ok = _try(devices)
    print(f"devices ................. {devs if ok else RED_NO}")

    from deepspeed_tpu.accelerator import get_accelerator

    acc = get_accelerator()
    print(f"accelerator ............. {acc.name()}")

    feature_probes = {
        "pallas": lambda: __import__("jax.experimental.pallas", fromlist=["x"]),
        "flash_attention": lambda: __import__(
            "deepspeed_tpu.ops.flash_attention", fromlist=["flash_attention"]),
        "mesh collectives": lambda: __import__(
            "deepspeed_tpu.comm.comm", fromlist=["all_reduce"]),
        "orbax checkpoint": lambda: __import__("orbax.checkpoint", fromlist=["x"]),
    }
    print("-" * 60)
    print("feature availability:")
    for name, probe in feature_probes.items():
        _, ok = _try(probe)
        print(f"  {name:<22} {GREEN_OK if ok else RED_NO}")
    print("-" * 60)


if __name__ == "__main__":
    main()
