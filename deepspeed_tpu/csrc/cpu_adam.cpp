// Host-side fused optimizers for ZeRO-Offload.
//
// TPU-native counterpart of the reference's AVX-vectorized CPU optimizers
// (csrc/adam/cpu_adam_impl.cpp:299, csrc/adagrad/cpu_adagrad.cpp:243,
// csrc/lion/cpu_lion_impl.cpp:255 with csrc/includes/simd.h templates).
// The reference hand-writes AVX2/AVX512 intrinsics; here tight scalar loops
// with restrict pointers + -O3 -march=native let GCC auto-vectorize to the
// same width, and OpenMP splits the flat partition across host cores.
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).

#include <cmath>
#include <cstddef>
#include <cstdint>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// Adam/AdamW over flat fp32 arrays. adam_w_mode: decoupled weight decay.
// bias_correction uses step (1-based).
void ds_adam_step(float* __restrict params, const float* __restrict grads,
                  float* __restrict exp_avg, float* __restrict exp_avg_sq,
                  int64_t n, float lr, float beta1, float beta2, float eps,
                  float weight_decay, int adam_w_mode, int bias_correction,
                  int64_t step) {
    const float bc1 = bias_correction ? 1.0f - std::pow(beta1, (float)step) : 1.0f;
    const float bc2 = bias_correction ? 1.0f - std::pow(beta2, (float)step) : 1.0f;
    const float one_minus_b1 = 1.0f - beta1;
    const float one_minus_b2 = 1.0f - beta2;

#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        float p = params[i];
        if (weight_decay != 0.0f && !adam_w_mode) g += weight_decay * p;
        float m = exp_avg[i] = beta1 * exp_avg[i] + one_minus_b1 * g;
        float v = exp_avg_sq[i] = beta2 * exp_avg_sq[i] + one_minus_b2 * g * g;
        float update = (m / bc1) / (std::sqrt(v / bc2) + eps);
        if (weight_decay != 0.0f && adam_w_mode) update += weight_decay * p;
        params[i] = p - lr * update;
    }
}

void ds_adagrad_step(float* __restrict params, const float* __restrict grads,
                     float* __restrict exp_avg_sq, int64_t n, float lr,
                     float eps, float weight_decay) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i] + weight_decay * params[i];
        float v = exp_avg_sq[i] = exp_avg_sq[i] + g * g;
        params[i] -= lr * g / (std::sqrt(v) + eps);
    }
}

void ds_lion_step(float* __restrict params, const float* __restrict grads,
                  float* __restrict exp_avg, int64_t n, float lr, float beta1,
                  float beta2, float weight_decay) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        float p = params[i];
        float c = beta1 * exp_avg[i] + (1.0f - beta1) * g;
        float sign = (c > 0.0f) ? 1.0f : ((c < 0.0f) ? -1.0f : 0.0f);
        params[i] = p - lr * (sign + weight_decay * p);
        exp_avg[i] = beta2 * exp_avg[i] + (1.0f - beta2) * g;
    }
}

// fp32 <-> bf16 conversion helpers for the HBM<->host path (params travel
// as bf16, master copies stay fp32 — reference ZeRO-Offload data flow).
void ds_fp32_to_bf16(const float* __restrict src, uint16_t* __restrict dst,
                     int64_t n) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        uint32_t bits;
        __builtin_memcpy(&bits, &src[i], 4);
        // round-to-nearest-even
        uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
        dst[i] = (uint16_t)((bits + rounding) >> 16);
    }
}

void ds_bf16_to_fp32(const uint16_t* __restrict src, float* __restrict dst,
                     int64_t n) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        uint32_t bits = ((uint32_t)src[i]) << 16;
        __builtin_memcpy(&dst[i], &bits, 4);
    }
}

}  // extern "C"
