// Async tensor I/O engine for NVMe offload (ZeRO-Infinity tier).
//
// TPU-native counterpart of the reference's libaio stack
// (csrc/aio/common/deepspeed_aio_common.cpp:338, py_lib/
// deepspeed_py_aio_handle.cpp:298, deepspeed_aio_thread.cpp): a pool of
// worker threads services pread/pwrite requests split into block_size
// chunks against O_DIRECT-less fds (libaio/liburing are absent from this
// image; a thread pool over positioned I/O gives the same overlap of disk
// latency with device compute, which is what the swap pipeline needs).
// Plain C ABI for ctypes.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Request {
    bool is_read;
    std::string path;
    char* buffer;
    int64_t num_bytes;
    int64_t file_offset;
};

struct AioHandle {
    int64_t block_size;
    int n_threads;
    std::vector<std::thread> workers;
    std::deque<Request> queue;
    std::mutex mu;
    std::condition_variable cv;
    std::atomic<int64_t> inflight{0};
    std::atomic<int64_t> errors{0};
    std::condition_variable done_cv;
    bool shutdown = false;

    void worker() {
        for (;;) {
            Request req;
            {
                std::unique_lock<std::mutex> lock(mu);
                cv.wait(lock, [&] { return shutdown || !queue.empty(); });
                if (shutdown && queue.empty()) return;
                req = std::move(queue.front());
                queue.pop_front();
            }
            if (!run_one(req)) errors.fetch_add(1);
            if (inflight.fetch_sub(1) == 1) {
                std::lock_guard<std::mutex> lock(mu);
                done_cv.notify_all();
            }
        }
    }

    bool run_one(const Request& req) {
        int flags = req.is_read ? O_RDONLY : (O_WRONLY | O_CREAT);
        int fd = open(req.path.c_str(), flags, 0644);
        if (fd < 0) return false;
        int64_t done = 0;
        bool ok = true;
        while (done < req.num_bytes) {
            int64_t chunk = std::min(block_size, req.num_bytes - done);
            ssize_t r = req.is_read
                ? pread(fd, req.buffer + done, chunk, req.file_offset + done)
                : pwrite(fd, req.buffer + done, chunk, req.file_offset + done);
            if (r <= 0) { ok = false; break; }
            done += r;
        }
        close(fd);
        return ok;
    }
};

}  // namespace

extern "C" {

void* ds_aio_new(int64_t block_size, int n_threads) {
    auto* h = new AioHandle();
    h->block_size = block_size > 0 ? block_size : (1 << 20);
    h->n_threads = n_threads > 0 ? n_threads : 1;
    for (int i = 0; i < h->n_threads; ++i)
        h->workers.emplace_back([h] { h->worker(); });
    return h;
}

void ds_aio_free(void* handle) {
    auto* h = static_cast<AioHandle*>(handle);
    {
        std::lock_guard<std::mutex> lock(h->mu);
        h->shutdown = true;
    }
    h->cv.notify_all();
    for (auto& t : h->workers) t.join();
    delete h;
}

// Enqueue; returns immediately. Buffer must stay alive until ds_aio_wait.
void ds_aio_pread(void* handle, const char* path, char* buffer,
                  int64_t num_bytes, int64_t file_offset) {
    auto* h = static_cast<AioHandle*>(handle);
    h->inflight.fetch_add(1);
    {
        std::lock_guard<std::mutex> lock(h->mu);
        h->queue.push_back(Request{true, path, buffer, num_bytes, file_offset});
    }
    h->cv.notify_one();
}

void ds_aio_pwrite(void* handle, const char* path, char* buffer,
                   int64_t num_bytes, int64_t file_offset) {
    auto* h = static_cast<AioHandle*>(handle);
    h->inflight.fetch_add(1);
    {
        std::lock_guard<std::mutex> lock(h->mu);
        h->queue.push_back(Request{false, path, buffer, num_bytes, file_offset});
    }
    h->cv.notify_one();
}

// Block until all queued ops complete; returns number of failed ops since
// the last wait (0 == success).
int64_t ds_aio_wait(void* handle) {
    auto* h = static_cast<AioHandle*>(handle);
    std::unique_lock<std::mutex> lock(h->mu);
    h->done_cv.wait(lock, [&] { return h->inflight.load() == 0; });
    return h->errors.exchange(0);
}

int64_t ds_aio_inflight(void* handle) {
    return static_cast<AioHandle*>(handle)->inflight.load();
}

}  // extern "C"
