"""deepspeed_tpu — a TPU-native distributed training & inference framework
with DeepSpeed-class capabilities (reference: dc3671/DeepSpeed), built on
JAX/XLA/Pallas/pjit.

Public surface mirrors the reference's ``deepspeed/__init__.py``:
``initialize`` (:64), ``init_inference`` (:269), ``comm`` as the collective
module, plus the accelerator registry.
"""

__version__ = "0.1.0"

from . import comm  # noqa: F401
from .accelerator import get_accelerator, set_accelerator  # noqa: F401
from .runtime.config import DeepSpeedTpuConfig, load_config  # noqa: F401


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               distributed_port=29500,
               mesh=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               rng=None):
    """Create the training engine (reference deepspeed/__init__.py:64).

    ``model`` is a model description (see deepspeed_tpu.models) or any object
    exposing ``init(rng, batch) -> params`` and ``apply(params, batch) ->
    loss``; returns ``(engine, optimizer, dataloader, lr_scheduler)`` for
    API parity — the engine owns all four.
    """
    from .runtime.engine import DeepSpeedTpuEngine

    config = config if config is not None else config_params
    engine = DeepSpeedTpuEngine(args=args,
                                model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                mesh=mesh,
                                collate_fn=collate_fn,
                                config=config,
                                rng=rng)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, **kwargs):
    """Create the inference engine (reference deepspeed/__init__.py:269)."""
    from .inference.engine import InferenceEngine

    return InferenceEngine(model, config=config, **kwargs)


def init_distributed(dist_backend="xla", **kwargs):
    from .comm import init_distributed as _init

    return _init(dist_backend=dist_backend, **kwargs)
