"""deepspeed_tpu — a TPU-native distributed training & inference framework
with DeepSpeed-class capabilities (reference: dc3671/DeepSpeed), built on
JAX/XLA/Pallas/pjit.

Public surface mirrors the reference's ``deepspeed/__init__.py``:
``initialize`` (:64), ``init_inference`` (:269), ``comm`` as the collective
module, plus the accelerator registry.
"""

__version__ = "0.1.0"

from . import comm  # noqa: F401
from .accelerator import get_accelerator, set_accelerator  # noqa: F401
from .runtime.config import DeepSpeedTpuConfig, load_config  # noqa: F401


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               distributed_port=29500,
               mesh=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               rng=None):
    """Create the training engine (reference deepspeed/__init__.py:64).

    ``model`` is a model description (see deepspeed_tpu.models) or any object
    exposing ``init(rng, batch) -> params`` and ``apply(params, batch) ->
    loss``; returns ``(engine, optimizer, dataloader, lr_scheduler)`` for
    API parity — the engine owns all four.
    """
    from .runtime.engine import DeepSpeedTpuEngine

    config = config if config is not None else config_params

    # ZeRO-Infinity parameter streaming: params on NVMe/host DRAM, layer
    # groups paged through HBM (runtime/zero_infinity.py). Selected — like
    # the reference's swap-tensor path — by offload_param in the config.
    cfg_obj = load_config(config)
    op = cfg_obj.zero_optimization.offload_param
    if op is not None and str(op.device.value) in ("cpu", "nvme"):
        from .runtime.zero_infinity import ZeroInfinityEngine

        if cfg_obj.hybrid_engine.enabled:
            raise ValueError(
                "hybrid_engine is not supported with offload_param "
                "(ZeRO-Infinity streaming owns the parameter lifecycle)")

        unsupported = {"optimizer": optimizer, "training_data": training_data,
                       "lr_scheduler": lr_scheduler,
                       "model_parameters": model_parameters}
        bad = [k for k, v in unsupported.items() if v is not None]
        if bad:
            raise ValueError(
                f"offload_param (ZeRO-Infinity streaming) does not accept "
                f"{bad}; the streaming engine owns its optimizer and data "
                "path (runtime/zero_infinity.py)")
        if cfg_obj.zero_optimization.stage < 3:
            raise ValueError("offload_param requires zero_optimization.stage=3")
        if isinstance(model, str):
            from .models import build_model

            model = build_model(model)
        # Mesh composition: streaming runs under fsdp×data sharding (the
        # reference's NVMe swap runs under ZeRO-3 partitioning the same
        # way — stage3.py:72); other axes don't compose with streaming.
        from .parallel import topology as _topo

        mesh = None
        if "mesh" in cfg_obj.model_fields_set:
            # mesh requested explicitly → shard streaming over fsdp×data;
            # without a mesh block the engine stays single-device (the
            # pre-round-4 behavior)
            topo_obj = _topo.MeshTopology.build(cfg_obj.mesh)
            bad_axes = {a: topo_obj.axis_size(a)
                        for a in ("tensor", "pipe", "sequence", "expert")
                        if topo_obj.axis_size(a) > 1}
            if bad_axes:
                raise ValueError(
                    f"offload_param streaming composes with data/fsdp mesh "
                    f"axes only; got {bad_axes}")
            mesh = topo_obj.mesh
        engine = ZeroInfinityEngine(model, cfg_obj, rng=rng, mesh=mesh)
        return engine, None, None, None

    engine_cls = DeepSpeedTpuEngine
    if cfg_obj.hybrid_engine.enabled:
        # RLHF train↔generate engine (reference __init__.py:158 selects
        # DeepSpeedHybridEngine the same way)
        from .runtime.hybrid_engine import DeepSpeedTpuHybridEngine

        engine_cls = DeepSpeedTpuHybridEngine

    engine = engine_cls(args=args,
                        model=model,
                        optimizer=optimizer,
                        model_parameters=model_parameters,
                        training_data=training_data,
                        lr_scheduler=lr_scheduler,
                        mesh=mesh,
                        collate_fn=collate_fn,
                        config=config,
                        rng=rng)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, **kwargs):
    """Create the inference engine (reference deepspeed/__init__.py:269)."""
    from .inference.engine import InferenceEngine

    return InferenceEngine(model, config=config, **kwargs)


def init_distributed(dist_backend="xla", **kwargs):
    from .comm import init_distributed as _init

    return _init(dist_backend=dist_backend, **kwargs)
