"""NCCL-shaped communication frontend over XLA mesh collectives.

Counterpart of reference ``deepspeed/comm/comm.py`` (module-level collectives
:221-520, ``init_distributed`` :604, ``mpi_discovery`` :673) and its
``TorchBackend``. The TPU-native design has no NCCL communicators: a "process
group" is a named mesh axis (or tuple of axes) of the current
:class:`~deepspeed_tpu.parallel.topology.MeshTopology`, and collectives are
``jax.lax`` primitives that XLA lowers onto ICI/DCN.

Two calling conventions are provided:

1. **In-jit** (the hot path): call these functions inside ``shard_map``-ed /
   pjit-ed code with mesh axes bound — they emit ``lax.psum`` /
   ``lax.all_gather`` / ``lax.psum_scatter`` / ``lax.all_to_all`` /
   ``lax.ppermute`` directly. This is how the engine, ZeRO, MoE, Ulysses and
   pipeline layers communicate.

2. **Eager** (control plane / tests): the same op names callable from host
   code on stacked per-rank arrays (leading dim = group size). Each call is
   a cached ``jax.jit(shard_map(...))`` over the current mesh and is timed
   through the comms logger exactly like the reference's ``@timed_op``.
"""

from __future__ import annotations

import functools
import os
import time
from enum import Enum
from typing import Optional, Sequence, Union

from ..parallel import topology as topo
from ..utils.comms_logging import CommsLogger, get_msg_size_from_args
from ..utils.logging import logger

Group = Union[str, Sequence[str], None]

comms_logger = CommsLogger()

_initialized = False


def _routable_ip() -> str:
    """This host's routable IP for coordinator rendezvous.

    ``gethostbyname(gethostname())`` commonly resolves to 127.0.0.1 via
    /etc/hosts — other ranks would then rendezvous with their own
    loopback. Mirror the reference ``mpi_discovery`` (comm.py:673):
    ``hostname -I`` first entry, then the UDP-connect trick; the resolver
    result is the last resort (single-host setups where loopback is fine).
    """
    import socket
    import subprocess

    try:
        out = subprocess.run(["hostname", "-I"], capture_output=True,
                             text=True, timeout=5)
        for ip in out.stdout.split():
            if not ip.startswith("127.") and ":" not in ip:
                return ip
    except (OSError, subprocess.SubprocessError):
        pass
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            # no packet is sent; the kernel just picks the egress interface
            s.connect(("8.8.8.8", 80))
            ip = s.getsockname()[0]
        finally:
            s.close()
        if not ip.startswith("127."):
            return ip
    except OSError:
        pass
    return socket.gethostbyname(socket.gethostname())


class ReduceOp(Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVG = 4
    BAND = 5
    BOR = 6
    BXOR = 7
    UNUSED = 8


# --------------------------------------------------------------------------
# init / world info (reference comm/comm.py:604 init_distributed)
# --------------------------------------------------------------------------

def init_distributed(dist_backend: str = "xla",
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout=None,
                     init_method: Optional[str] = None,
                     dist_init_required: Optional[bool] = None,
                     config=None,
                     rank: int = -1,
                     world_size: int = -1) -> None:
    """Bring up the JAX multi-controller runtime if this is a multi-process
    job. Single-process (including single-host multi-chip TPU) needs no
    rendezvous — the PJRT client already sees all local devices.

    Env contract (mirrors torchrun's env:// + TPU pod conventions):
    ``COORDINATOR_ADDRESS`` (or ``MASTER_ADDR:MASTER_PORT``), ``RANK``/
    ``PROCESS_ID``, ``WORLD_SIZE``/``NUM_PROCESSES``. With
    ``auto_mpi_discovery`` (reference deepspeed/comm/comm.py:673
    ``mpi_discovery``), an ``mpirun``/``srun``-launched job fills
    rank/world from the OpenMPI/PMI env when the torchrun-style vars are
    absent — MPI as a *launch* vehicle works without the MPI-family
    multinode runners (docs/DIVERGENCES.md).
    """
    global _initialized
    if _initialized:
        return
    import jax

    coord = os.environ.get("COORDINATOR_ADDRESS")
    if coord is None and os.environ.get("MASTER_ADDR"):
        coord = f"{os.environ['MASTER_ADDR']}:{os.environ.get('MASTER_PORT', distributed_port)}"
    if world_size < 0:
        world_size = int(os.environ.get("WORLD_SIZE", os.environ.get("NUM_PROCESSES", "-1")))
    if rank < 0:
        rank = int(os.environ.get("RANK", os.environ.get("PROCESS_ID", "-1")))
    mpi_launched = False
    if auto_mpi_discovery and (rank < 0 or world_size < 0):
        # mpirun (OpenMPI) / PMI (MPICH, srun) launch conventions
        mpi_rank = os.environ.get("OMPI_COMM_WORLD_RANK",
                                  os.environ.get("PMI_RANK"))
        mpi_world = os.environ.get("OMPI_COMM_WORLD_SIZE",
                                   os.environ.get("PMI_SIZE"))
        mpi_launched = mpi_rank is not None
        if rank < 0 and mpi_rank is not None:
            rank = int(mpi_rank)
        if world_size < 0 and mpi_world is not None:
            world_size = int(mpi_world)
    if world_size < 0:
        world_size = 1
    if rank < 0:
        rank = 0
    if world_size > 1 and coord is None and mpi_launched:
        # mpirun sets no MASTER_ADDR; the reference's mpi_discovery
        # broadcasts rank 0's address over MPI (comm.py:673). Do the same
        # when mpi4py exists; otherwise fail loudly — the silent
        # "externally initialized" fallback would leave every process
        # seeing only its local devices (divergent training, no error).
        try:
            from mpi4py import MPI  # type: ignore

            addr = MPI.COMM_WORLD.bcast(
                _routable_ip() if rank == 0 else None, root=0)
            coord = f"{addr}:{distributed_port}"
        except ImportError:
            raise ValueError(
                f"MPI-launched job (rank {rank}/{world_size}) has no "
                "COORDINATOR_ADDRESS/MASTER_ADDR and mpi4py is not "
                "available to broadcast one — export MASTER_ADDR=<rank0 "
                "host> in the mpirun command") from None

    if world_size > 1 and coord is not None:
        if verbose:
            logger.info(
                f"Initializing jax.distributed: coordinator={coord} rank={rank}/{world_size}")
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=world_size,
                                   process_id=rank)
    elif verbose and world_size > 1:
        logger.warning("WORLD_SIZE>1 but no COORDINATOR_ADDRESS/MASTER_ADDR set; "
                       "assuming the JAX runtime was initialized externally")
    _initialized = True
    if config is not None:
        comms_logger.configure(config.comms_logger)


def is_initialized() -> bool:
    return _initialized


def get_rank(group: Group = None) -> int:
    """Process rank (host-level). For per-device rank inside jit use
    ``jax.lax.axis_index``."""
    import jax

    return jax.process_index()


def get_world_size(group: Group = None) -> int:
    """Size of ``group`` (mesh axis/axes); None = full device count."""
    if group is None:
        t = topo.get_topology()
        return t.world_size
    t = topo.get_topology()
    axes = (group,) if isinstance(group, str) else tuple(group)
    size = 1
    for a in axes:
        size *= t.axis_size(a)
    return size


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", "0"))


def barrier(group: Group = None) -> None:
    """Barrier (reference comm/comm.py:406). Multi-process: a true
    cross-host rendezvous via a zero-payload global collective
    (multihost_utils.sync_global_devices). Single-process: flush
    outstanding device work — there is no peer to wait for."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        global _barrier_count
        _barrier_count += 1
        multihost_utils.sync_global_devices(f"ds_tpu_barrier_{_barrier_count}")
    else:
        import jax.numpy as jnp

        jax.block_until_ready(jnp.zeros(()))


_barrier_count = 0


def _axes(group: Group):
    if group is None:
        return tuple(topo.get_topology().axis_names)
    return (group,) if isinstance(group, str) else tuple(group)


# --------------------------------------------------------------------------
# In-jit collectives — call under shard_map with mesh axes bound.
# Shapes follow the NCCL-shaped reference API (comm/comm.py:221-520).
# --------------------------------------------------------------------------

def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: Group = None):
    import jax.lax as lax

    axes = _axes(group)
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = lax.psum(tensor, axes)
        if op == ReduceOp.AVG:
            out = out / get_world_size(group)
        return out
    if op == ReduceOp.MAX:
        return lax.pmax(tensor, axes)
    if op == ReduceOp.MIN:
        return lax.pmin(tensor, axes)
    if op == ReduceOp.PRODUCT:
        import jax.numpy as jnp

        # all_gather + prod: exact for zeros/negatives (exp∘psum∘log is not)
        out = tensor
        for a in reversed(axes):
            out = lax.all_gather(out, a, axis=0, tiled=False)
            out = jnp.prod(out, axis=0)
        return out
    raise NotImplementedError(f"ReduceOp {op} not supported on TPU mesh collectives")


def inference_all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: Group = None):
    """Reference comm.py:500 — latency-optimized TP all-reduce; on TPU the
    same lax.psum is already the latency-optimal ICI collective."""
    return all_reduce(tensor, op, group)


def all_gather_into_tensor(output_unused, tensor, group: Group = None, axis: int = 0):
    """Flat all-gather along ``axis`` (reference comm.py:297). Returns the
    gathered tensor (JAX is functional; the output arg is accepted for API
    parity and ignored)."""
    import jax.lax as lax

    axes = _axes(group)
    out = tensor
    for a in reversed(axes):
        out = lax.all_gather(out, a, axis=axis, tiled=True)
    return out


def all_gather(tensor_list_unused, tensor, group: Group = None):
    """Returns [world, ...] stacked gather (reference all_gather into a list)."""
    import jax.lax as lax

    axes = _axes(group)
    out = lax.all_gather(tensor, axes, axis=0, tiled=False)
    return out


def reduce_scatter_tensor(output_unused, tensor, op: ReduceOp = ReduceOp.SUM,
                          group: Group = None, scatter_dim: int = 0):
    """Reduce + scatter equal chunks along ``scatter_dim`` (reference comm.py:280)."""
    import jax.lax as lax

    axes = _axes(group)
    out = lax.psum_scatter(tensor, axes, scatter_dimension=scatter_dim, tiled=True)
    if op == ReduceOp.AVG:
        out = out / get_world_size(group)
    return out


def all_to_all_single(output_unused, tensor, group: Group = None,
                      split_axis: int = 0, concat_axis: int = 0):
    """Chunked all-to-all (reference comm.py:331): splits ``tensor`` along
    ``split_axis`` into group-size chunks, exchanges chunk i with rank i,
    concatenates received chunks along ``concat_axis``."""
    import jax.lax as lax

    axes = _axes(group)
    if len(axes) != 1:
        raise ValueError("all_to_all_single requires a single mesh axis group")
    return lax.all_to_all(tensor, axes[0], split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def broadcast(tensor, src: int = 0, group: Group = None):
    """Broadcast from group-rank ``src`` (reference comm.py:221). Implemented
    as mask+psum, which XLA pattern-matches to an efficient collective."""
    import jax.lax as lax
    import jax.numpy as jnp

    axes = _axes(group)
    if len(axes) == 1:
        idx = lax.axis_index(axes[0])
    else:
        idx = _flat_axis_index(axes)
    # where (not multiply-by-mask): non-src buffers may hold inf/NaN garbage,
    # and 0 * inf = NaN would poison every rank.
    masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
    return lax.psum(masked, axes)


def _flat_axis_index(axes):
    import jax.lax as lax

    t = topo.get_topology()
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * t.axis_size(a) + lax.axis_index(a)
    return idx


def send(tensor, dst: int, src: int, group: Group = None):
    """P2P send inside a jitted program = ppermute moving ``src``'s shard to
    ``dst``. SPMD collectives need the *static* (src, dst) pair — a
    per-device "my rank" does not exist at trace time (lax.axis_index is a
    traced value and ppermute permutations must be static), so the caller
    names both endpoints, as the pipeline engine does for stage pairs.
    Returns the value this device receives (zeros on non-participants)."""
    return ppermute(tensor, [(src, dst)], group)


def recv(tensor_shape_like, src: int, dst: int, group: Group = None):
    """Symmetric to :func:`send` — same collective, receiver's view."""
    return ppermute(tensor_shape_like, [(src, dst)], group)


def ppermute(tensor, perm, group: Group = None):
    import jax.lax as lax

    axes = _axes(group)
    if len(axes) != 1:
        raise ValueError("ppermute requires a single mesh axis group")
    return lax.ppermute(tensor, axes[0], perm)


def reduce(tensor, dst: int, op: ReduceOp = ReduceOp.SUM, group: Group = None):
    """psum then mask to dst (XLA has no rooted reduce over ICI; the full
    reduction is the same cost on a torus)."""
    import jax.lax as lax
    import jax.numpy as jnp

    out = all_reduce(tensor, op, group)
    axes = _axes(group)
    idx = lax.axis_index(axes[0]) if len(axes) == 1 else _flat_axis_index(axes)
    return jnp.where(idx == dst, out, jnp.zeros_like(out))


def axis_index(group: Group = None):
    """Rank within group, inside jit (lax.axis_index over the group axes)."""
    axes = _axes(group)
    return _flat_axis_index(axes) if len(axes) > 1 else __import__("jax").lax.axis_index(axes[0])


# --------------------------------------------------------------------------
# Eager wrappers: stacked-rank convention. Input leading dim == group size
# (each slice is "that rank's tensor"); runs jit(shard_map) over the mesh.
# --------------------------------------------------------------------------

def _timed(op_name):
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if comms_logger.should_profile(op_name):
                import jax

                t0 = time.perf_counter()
                result = fn(*args, **kwargs)
                jax.block_until_ready(result)
                dt = time.perf_counter() - t0
                # group may be passed positionally (last arg, str/tuple)
                group = kwargs.get("group")
                if group is None:
                    for a in reversed(args):
                        if isinstance(a, (str, tuple)) and not hasattr(a, "shape"):
                            group = a
                            break
                n = get_world_size(group)
                # stacked convention: leading dim == group size, so the
                # per-rank payload is total/n
                comms_logger.append(op_name, op_name, dt,
                                    get_msg_size_from_args(*args) // max(n, 1),
                                    n)
                return result
            return fn(*args, **kwargs)
        return wrapper
    return deco


@functools.lru_cache(maxsize=256)
def _eager_collective(mesh, op_name: str, axis: str, n_extra_args: int, static):
    """Build and cache a jitted shard_map collective over ``mesh``."""
    import jax
    from jax.sharding import PartitionSpec as P

    def body(x):
        y = x[0]  # strip the stacked-rank leading dim: this rank's tensor
        if op_name == "all_reduce":
            out = all_reduce(y, ReduceOp(static), axis)
        elif op_name == "all_gather_into_tensor":
            out = all_gather_into_tensor(None, y, axis)
        elif op_name == "reduce_scatter_tensor":
            out = reduce_scatter_tensor(None, y, ReduceOp(static), axis)
        elif op_name == "all_to_all_single":
            out = all_to_all_single(None, y, axis)
        elif op_name == "broadcast":
            out = broadcast(y, static, axis)
        else:
            raise ValueError(op_name)
        return out[None]

    from ..compat import shard_map
    return jax.jit(shard_map(body, mesh=mesh, in_specs=P(axis), out_specs=P(axis)))


def _run_eager(op_name: str, stacked, group: Group, static=0):
    t = topo.get_topology()
    axes = _axes(group if group is not None else topo.DATA_AXIS)
    if len(axes) != 1:
        raise ValueError("eager collectives take a single-axis group")
    axis = axes[0]
    size = t.axis_size(axis)
    if stacked.shape[0] != size:
        raise ValueError(
            f"eager collective expects leading dim == group size {size}, got {stacked.shape}")
    fn = _eager_collective(t.mesh, op_name, axis, 0, static)
    return fn(stacked)


@_timed("all_reduce")
def eager_all_reduce(stacked, op: ReduceOp = ReduceOp.SUM, group: Group = None):
    return _run_eager("all_reduce", stacked, group, op.value)


@_timed("all_gather_into_tensor")
def eager_all_gather(stacked, group: Group = None):
    return _run_eager("all_gather_into_tensor", stacked, group)


@_timed("reduce_scatter_tensor")
def eager_reduce_scatter(stacked, op: ReduceOp = ReduceOp.SUM, group: Group = None):
    return _run_eager("reduce_scatter_tensor", stacked, group, op.value)


@_timed("all_to_all_single")
def eager_all_to_all(stacked, group: Group = None):
    return _run_eager("all_to_all_single", stacked, group)


@_timed("broadcast")
def eager_broadcast(stacked, src: int = 0, group: Group = None):
    return _run_eager("broadcast", stacked, group, src)


def log_summary(show_straggler: bool = False):
    """Reference comm.py:422 — dump the comms logger summary."""
    return comms_logger.log_all(print_log=True, show_straggler=show_straggler)


# Capability probes (reference comm.py:239,308,467) — always true here.
def has_all_gather_into_tensor() -> bool:
    return True


def has_reduce_scatter_tensor() -> bool:
    return True


def has_coalescing_manager() -> bool:
    return True
