"""Elastic training: compatible batch-size / chip-count computation.

Counterpart of reference ``elasticity/elasticity.py`` (``compute_elastic_config``
:233, ``_get_compatible_gpus_v01`` :83, ``_get_compatible_gpus_v02`` :126) and
``elasticity/config.py``. The contract: given a max acceptable global batch
and a set of candidate micro-batch sizes, pick ONE global batch size that is
simultaneously reachable (micro × gas × chips) on as many chip counts as
possible — then a job can scale up/down across those chip counts *without
changing the global batch*, so training convergence is unaffected; gradient
accumulation absorbs the difference.

TPU-native notes: "GPUs" in the reference maps to TPU chips; v0.2's
``num_gpus_per_node`` maps to chips-per-host (a v5e host has 4 or 8).
Restart-based elasticity pairs this with the universal checkpoint
(``runtime/checkpointing.py``): a run checkpointed on mesh A resumes on any
mesh B whose chip count is in ``valid_chips`` — the engine re-derives
micro/gas from the fixed global batch (reference's DSElasticAgent restart
role; no torch-elastic agent is needed in the restart model).
"""

from __future__ import annotations

import math
import numbers
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils.logging import logger


class ElasticityError(Exception):
    """Base error for elasticity problems (reference config.py:10)."""


class ElasticityConfigError(ElasticityError):
    """Bad or missing elasticity configuration."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """Current chip count is not in the valid set for the elastic config."""


LATEST_VERSION = 0.2
SUPPORTED_VERSIONS = (0.1, LATEST_VERSION)


def highly_composite_numbers(limit: int) -> List[int]:
    """All highly composite numbers ≤ limit (1, 2, 4, 6, 12, 24, ...) — the
    scaling factors used to grow a base batch while keeping many divisors
    (⇒ many compatible chip counts). Computed, not tabulated."""
    out, best = [], 0
    n = 1
    while n <= limit:
        d = _n_divisors(n)
        if d > best:
            out.append(n)
            best = d
        n += 1
    return out


def _n_divisors(n: int) -> int:
    count, i = 1, 2
    while i * i <= n:
        if n % i == 0:
            e = 0
            while n % i == 0:
                n //= i
                e += 1
            count *= e + 1
        i += 1
    if n > 1:
        count *= 2
    return count


def _candidate_batch_sizes(bases: Sequence[int], max_batch: int) -> List[int]:
    """Largest HCN multiple of each base that stays ≤ max_batch."""
    hcn = highly_composite_numbers(max(1, max_batch // max(1, min(bases))))
    out = set()
    for base in bases:
        if base >= max_batch:
            out.add(base)
            continue
        factor = 1
        for h in hcn:
            if h * base <= max_batch:
                factor = h
            else:
                break
        out.add(factor * base)
    return sorted(out)


def _valid_chips(batch_size: int, micro_batches: Sequence[int],
                 min_chips: int, max_chips: int) -> List[int]:
    """All chip counts n with batch_size = micro × gas × n for some micro in
    the candidate set and integer gas ≥ 1, within [min_chips, max_chips]."""
    valid = set()
    for micro in micro_batches:
        if batch_size % micro:
            continue
        slots = batch_size // micro      # micro-batch slots = chips × gas
        for n in range(1, int(math.isqrt(slots)) + 1):
            if slots % n == 0:
                for c in (n, slots // n):
                    if min_chips <= c <= max_chips:
                        valid.add(c)
    return sorted(valid)


def get_compatible_chips_v01(micro_batches: Sequence[int],
                             max_acceptable_batch_size: int,
                             min_chips: Optional[int] = None,
                             max_chips: Optional[int] = None,
                             prefer_larger: bool = True
                             ) -> Tuple[int, List[int]]:
    """v0.1 (reference :83): among candidate batch sizes (each micro batch
    and their lcm, scaled by highly composite factors), pick the one valid
    on the most chip counts; prefer_larger breaks ties."""
    min_chips = min_chips or 1
    max_chips = max_chips or max_acceptable_batch_size // min(micro_batches)
    if any(mb > max_acceptable_batch_size for mb in micro_batches):
        raise ElasticityConfigError(
            "every micro batch must be <= max_acceptable_batch_size "
            f"({max_acceptable_batch_size}); got {list(micro_batches)}")

    lcm = math.lcm(*[int(m) for m in micro_batches])
    candidates = _candidate_batch_sizes(list(micro_batches) + [lcm],
                                        max_acceptable_batch_size)
    best_batch, best_chips = min(micro_batches), []
    for batch in candidates:
        chips = _valid_chips(batch, micro_batches, min_chips, max_chips)
        better = len(chips) > len(best_chips) or (
            len(chips) == len(best_chips)
            and (batch > best_batch if prefer_larger else batch < best_batch))
        if better:
            best_batch, best_chips = batch, chips
    return int(best_batch), best_chips


def get_compatible_chips_v02(micro_batches: Sequence[int],
                             max_acceptable_batch_size: int,
                             current_num_chips: int,
                             min_chips: Optional[int] = None,
                             max_chips: Optional[int] = None,
                             prefer_larger: bool = True,
                             chips_per_host: int = 1,
                             model_parallel_size: int = 1
                             ) -> Tuple[int, List[int], Optional[int]]:
    """v0.2 (reference :126): host-granular scaling with model parallelism —
    chips are added/removed whole hosts at a time and the DP world is
    chips / model_parallel_size. Returns (batch, valid_chip_counts, micro)."""
    if chips_per_host % model_parallel_size:
        raise ElasticityConfigError(
            f"chips_per_host ({chips_per_host}) must be divisible by "
            f"model_parallel_size ({model_parallel_size})")
    dp_per_host = chips_per_host // model_parallel_size

    def pick_micro(batch: int) -> Optional[int]:
        chosen = None
        for micro in micro_batches:
            if (batch // current_num_chips) % micro == 0:
                if chosen is None or (prefer_larger and micro > chosen):
                    chosen = micro
        return chosen

    batch, valid_hosts = get_compatible_chips_v01(
        micro_batches,
        int(max_acceptable_batch_size / dp_per_host),
        int((min_chips or 1) / chips_per_host) or 1,
        int((max_chips or 10**6) / chips_per_host) or 1,
        prefer_larger=prefer_larger)
    batch = int(batch) * dp_per_host
    valid_dp = [h * dp_per_host for h in valid_hosts]
    if current_num_chips // model_parallel_size in valid_dp:
        return batch, valid_dp, pick_micro(batch)

    # Current world not in the preferred set: fall back to the largest
    # batch ≤ max reachable on exactly this world (reference :206).
    current_dp = (current_num_chips // chips_per_host) * dp_per_host
    fallback = [int(max_acceptable_batch_size // (m * current_dp)) * m *
                current_dp
                for m in micro_batches if m * current_dp
                <= max_acceptable_batch_size]
    if not fallback:
        raise ElasticityIncompatibleWorldSize(
            f"no micro batch in {list(micro_batches)} fits "
            f"max_acceptable_batch_size={max_acceptable_batch_size} on "
            f"{current_num_chips} chips")
    batch = max(fallback) if prefer_larger else min(fallback)
    return batch, [int(current_dp)], pick_micro(batch)


def elasticity_enabled(ds_config: Dict[str, Any]) -> bool:
    return bool(ds_config.get("elasticity", {}).get("enabled", False))


def _as_int(value) -> Optional[int]:
    """Integral value as int, else None. Accepts 2000, 2000.0, and numpy
    scalars alike — JSON/YAML float literals for whole numbers and
    array-derived configs must not break what the batch arithmetic always
    handled — but never bools or 2.5."""
    if isinstance(value, bool) or type(value).__name__ == "bool_":
        return None
    if isinstance(value, numbers.Integral):
        return int(value)
    if isinstance(value, numbers.Real):
        f = float(value)
        if math.isfinite(f) and f == int(f):
            return int(f)
    return None


def validate_elastic_config(ec: Dict[str, Any]) -> None:
    """Reject an inconsistent ``elasticity`` block with a descriptive
    error BEFORE any batch math runs (reference elasticity/config.py
    field assertions). Called by :func:`compute_elastic_config`, which
    the engine invokes at ``initialize()`` time — a bad elastic config
    fails the job at construction, not mid-run on a resize."""
    raw_micro = ec.get("micro_batch_sizes", [2, 4, 6])
    micro = ([_as_int(m) for m in raw_micro]
             if isinstance(raw_micro, (list, tuple)) else [])
    if not micro or any(m is None or m <= 0 for m in micro):
        raise ElasticityConfigError(
            "elasticity.micro_batch_sizes must be a non-empty list of "
            f"positive ints, got {raw_micro!r}")
    max_batch = _as_int(ec.get("max_train_batch_size", 2000))
    if max_batch is None or max_batch < max(micro):
        raise ElasticityConfigError(
            f"elasticity.max_train_batch_size "
            f"({ec.get('max_train_batch_size')!r}) must be an int >= the "
            f"largest micro batch ({max(micro)}) — no global batch could "
            "otherwise hold one micro batch")
    min_g = _as_int(ec.get("min_gpus", 1))
    max_g = _as_int(ec.get("max_gpus", 10000))
    if min_g is None or min_g < 1:
        raise ElasticityConfigError(
            f"elasticity.min_gpus ({ec.get('min_gpus')!r}) must be an "
            "int >= 1")
    if max_g is None or max_g < min_g:
        raise ElasticityConfigError(
            f"elasticity.max_gpus ({ec.get('max_gpus')!r}) must be an "
            f"int >= min_gpus ({min_g})")
    try:
        version = float(ec.get("version", LATEST_VERSION))
    except (TypeError, ValueError):
        raise ElasticityConfigError(
            f"elasticity.version ({ec.get('version')!r}) is not a number")
    if version not in SUPPORTED_VERSIONS:
        supported = ", ".join(str(v) for v in SUPPORTED_VERSIONS)
        raise ElasticityConfigError(
            f"elasticity.version {version} is unknown "
            f"(supported: {supported})")
    mp = _as_int(ec.get("model_parallel_size", 1))
    if mp is None or mp < 1:
        raise ElasticityConfigError(
            f"elasticity.model_parallel_size "
            f"({ec.get('model_parallel_size')!r}) must be an int >= 1")
    if mp > 1 and version != 0.2:
        raise ElasticityConfigError(
            f"elasticity v{version} does not support model parallelism "
            f"(model_parallel_size={mp} needs version 0.2)")
    gpn = _as_int(ec.get("num_gpus_per_node", 1))
    if gpn is None or gpn < 1:
        raise ElasticityConfigError(
            f"elasticity.num_gpus_per_node "
            f"({ec.get('num_gpus_per_node')!r}) must be an int >= 1")
    if version == 0.2 and gpn % mp:
        raise ElasticityConfigError(
            f"elasticity.num_gpus_per_node ({gpn}) must be divisible by "
            f"model_parallel_size ({mp}) — hosts are the scaling unit in "
            "v0.2 and a host must hold whole model replicas")


def compute_elastic_config(ds_config: Dict[str, Any],
                           world_size: int = 0,
                           return_microbatch: bool = False):
    """Reference :233. Given a config with an ``elasticity`` block, return
    (final_batch_size, valid_chips[, micro_batch]). Deterministic for a
    given config so schedulers and the runtime agree."""
    if not isinstance(ds_config, dict):
        raise ValueError(f"expected a config dict, got {type(ds_config)}")
    if "elasticity" not in ds_config:
        raise ElasticityConfigError(
            "'elasticity' is missing from the config; add it to run an "
            "elastic job")
    ec = ds_config["elasticity"]
    if not ec.get("enabled", False):
        raise ElasticityConfigError("elasticity.enabled is false")
    validate_elastic_config(ec)
    version = float(ec.get("version", 0.2))
    micro_batches = ec.get("micro_batch_sizes", [2, 4, 6])
    max_batch = ec.get("max_train_batch_size", 2000)
    mp_size = int(ec.get("model_parallel_size", 1))

    if world_size == 0 and os.environ.get("WORLD_SIZE", "").isnumeric():
        world_size = int(os.environ["WORLD_SIZE"])

    if version == 0.1:
        batch, valid = get_compatible_chips_v01(
            micro_batches, max_batch,
            ec.get("min_gpus", 1), ec.get("max_gpus", 10000),
            prefer_larger=ec.get("prefer_larger_batch", True))
        micro = None
        if world_size > 0:
            if world_size not in valid:
                raise ElasticityIncompatibleWorldSize(
                    f"world size {world_size} not in valid chip counts "
                    f"{valid}")
            micro = next(m for m in sorted(micro_batches, reverse=True)
                         if batch % (m * world_size) == 0)
    else:
        if world_size == 0:
            raise ElasticityConfigError(
                "elasticity v0.2 needs the current world size (argument or "
                "WORLD_SIZE env)")
        batch, valid, micro = get_compatible_chips_v02(
            micro_batches, max_batch, world_size,
            ec.get("min_gpus", 1), ec.get("max_gpus", 10000),
            prefer_larger=ec.get("prefer_larger_batch", True),
            chips_per_host=int(ec.get("num_gpus_per_node", 1)),
            model_parallel_size=mp_size)
    logger.info(f"elasticity: batch={batch} valid_chips={valid} "
                f"micro={micro}")
    if return_microbatch:
        return batch, valid, micro
    return batch, valid
