"""Elastic training (reference ``deepspeed/elasticity/``)."""

from .elasticity import (ElasticityConfigError, ElasticityError,
                         ElasticityIncompatibleWorldSize,
                         compute_elastic_config, elasticity_enabled,
                         get_compatible_chips_v01, get_compatible_chips_v02,
                         validate_elastic_config)

__all__ = [
    "ElasticityError", "ElasticityConfigError",
    "ElasticityIncompatibleWorldSize", "compute_elastic_config",
    "elasticity_enabled", "get_compatible_chips_v01",
    "get_compatible_chips_v02", "validate_elastic_config",
]
