"""``python -m deepspeed_tpu`` — the launcher CLI (reference ``bin/deepspeed``).

Subcommand-free: forwards to the launcher's main (hostfile parse,
include/exclude filters, ssh fan-out, ``--autotune``). ``--report`` prints
the environment report (reference ``bin/ds_report``)."""

import os
import sys


def main():
    if os.environ.get("JAX_PLATFORMS"):
        # site plugins (axon) can pin jax_platforms at interpreter start;
        # honor the user's env override before any device query (same
        # workaround as tests/conftest.py)
        try:
            import jax

            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass
    if "--report" in sys.argv[1:2]:
        from .env_report import main as report_main

        return report_main()
    if "--elastic" in sys.argv[1:2]:
        # reference bin/ds_elastic: print the elastic batch + valid chip
        # counts for a config
        import json

        from .elasticity import compute_elastic_config

        args = sys.argv[2:]
        if not args:
            print("usage: python -m deepspeed_tpu --elastic CONFIG.json "
                  "[WORLD_SIZE]", file=sys.stderr)
            return 2
        with open(args[0]) as fh:
            cfg = json.load(fh)
        world = int(args[1]) if len(args) > 1 else 0
        out = compute_elastic_config(cfg, world_size=world,
                                     return_microbatch=world > 0)
        if world > 0:
            batch, valid, micro = out
            print(json.dumps({"final_batch_size": batch,
                              "valid_chips": valid, "micro_batch": micro}))
        else:
            batch, valid = out
            print(json.dumps({"final_batch_size": batch,
                              "valid_chips": valid}))
        return 0
    from .launcher.runner import main as runner_main

    return runner_main()


if __name__ == "__main__":
    sys.exit(main())
