"""``python -m deepspeed_tpu`` — the launcher CLI (reference ``bin/deepspeed``).

Subcommand-free: forwards to the launcher's main (hostfile parse,
include/exclude filters, ssh fan-out, ``--autotune``). ``--report`` prints
the environment report (reference ``bin/ds_report``)."""

import os
import sys


def main():
    if os.environ.get("JAX_PLATFORMS"):
        # site plugins (axon) can pin jax_platforms at interpreter start;
        # honor the user's env override before any device query (same
        # workaround as tests/conftest.py)
        try:
            import jax

            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass
    if "--report" in sys.argv[1:2]:
        from .env_report import main as report_main

        return report_main()
    from .launcher.runner import main as runner_main

    return runner_main()


if __name__ == "__main__":
    main()
