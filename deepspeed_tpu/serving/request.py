"""Typed request surface of the serving frontend.

A submitted request carries its QoS contract — priority, deadline,
max_new_tokens — and returns a :class:`RequestHandle` whose stream side is
a thread-safe iterator of :class:`TokenEvent` terminated by one
:class:`DoneEvent`. Overload is an *explicit* outcome: a frontend that
cannot take the request raises :class:`Rejected` with a machine-readable
reason instead of queueing unboundedly (the SLO contract — bounded latency
or a fast no).
"""

from __future__ import annotations

import dataclasses
import enum
import queue
import threading
import time
from typing import Iterator, List, Optional, Union

from ..utils.locks import RankedLock


class Priority(enum.IntEnum):
    """Lower value = served first (heap order)."""
    HIGH = 0
    NORMAL = 1
    LOW = 2


class RequestState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    CANCELLED = "cancelled"
    REJECTED = "rejected"     # shed at admission (overloaded/draining)
    EXPIRED = "expired"       # deadline passed before completion
    FAILED = "failed"         # replica died / engine error


class FinishReason:
    EOS = "eos"
    LENGTH = "length"
    CANCELLED = "cancelled"
    DEADLINE = "deadline"
    ERROR = "error"            # engine fault / replica died mid-request
    NO_REPLICAS = "no_replicas"   # nothing healthy to dispatch to
    BROWNOUT = "brownout"      # shed by the degraded-capacity queue


class Rejected(Exception):
    """Load-shed signal: the request was NOT admitted. ``reason`` is one of
    "overloaded" (queue full), "draining" (frontend shutting down),
    "too_long" (prompt cannot ever fit)."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(f"request rejected: {reason}"
                         + (f" ({detail})" if detail else ""))


@dataclasses.dataclass(frozen=True)
class TokenEvent:
    uid: int
    token: int
    index: int            # 0-based position in the generated sequence
    t: float              # monotonic emission time


@dataclasses.dataclass(frozen=True)
class DoneEvent:
    uid: int
    reason: str           # a FinishReason value
    t: float


StreamEvent = Union[TokenEvent, DoneEvent]


class ServingRequest:
    """Internal per-request record; user code holds the RequestHandle."""

    _seq_lock = RankedLock("serving.request.seq")
    _seq = 0

    def __init__(self, prompt_tokens: List[int], max_new_tokens: int,
                 priority: int, deadline_s: Optional[float],
                 eos_token_id: Optional[int], *,
                 request_class: str = "interactive", shed_rank: int = 0,
                 tenant: str = "default", model_id: str = "default"):
        with ServingRequest._seq_lock:
            ServingRequest._seq += 1
            self.uid = ServingRequest._seq
        self.prompt_tokens = list(prompt_tokens)
        self.max_new_tokens = int(max_new_tokens)
        self.priority = int(priority)
        # request class (docs/SERVING.md "Disaggregated serving"):
        # labels per-class metrics and orders brownout victim selection
        # (higher shed_rank sheds first — batch before interactive)
        self.request_class = str(request_class)
        self.shed_rank = int(shed_rank)
        # multi-tenant / multi-model serving (docs/SERVING.md
        # "Multi-model & multi-tenant serving"): the tenant labels
        # fair-share accounting and per-tenant metrics; model_id pins
        # routing to that model's replica pool. Both default to
        # "default" — single-model, tenancy-off traffic never names them.
        self.tenant = str(tenant)
        self.model_id = str(model_id)
        self.eos_token_id = eos_token_id
        self.arrival_t = time.monotonic()
        # absolute monotonic deadline; None = no SLO
        self.deadline_t = (self.arrival_t + deadline_s
                           if deadline_s is not None else None)
        self.admitted_t: Optional[float] = None   # popped from the queue
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.state = RequestState.QUEUED
        self.finish_reason: Optional[str] = None
        self.cancel_requested = threading.Event()
        self.replica_id: Optional[int] = None
        self.n_generated = 0
        # fault tolerance (docs/SERVING.md "Fault tolerance"): delivered
        # tokens are kept so a replica death can resume the request on
        # another replica from prompt + generated-so-far (lossless under
        # greedy decoding); ``attempts`` counts replica assignments
        self.generated_tokens: List[int] = []
        self.attempts = 1
        # disaggregated serving (docs/SERVING.md "Disaggregated
        # serving"): a prefill-role replica stages the finished prompt's
        # exported KV here for the decode-role replica to import;
        # ``_staged_release`` frees the staging-buffer slot (idempotent,
        # called from take_staged AND finish so a cancelled/expired/shed
        # staged request can never pin the buffer). ``no_prefill`` marks
        # a request whose handoff fell back to recompute: it must run
        # its full path on a decode-capable replica (a prefill-only
        # replica would just hand it off again). ``handoffs`` counts
        # completed prefill→decode transfers for the trace.
        self.staged_kv: Optional[dict] = None
        self._staged_release = None
        self.no_prefill = False
        self.handoff_t: Optional[float] = None
        self.handoffs = 0
        # per-attempt prefill charge the owning replica's load split
        # accounting holds (serving/replica.py)
        self._charged_prefill = 0
        self._events: "queue.Queue[StreamEvent]" = queue.Queue()
        self._done = threading.Event()
        # telemetry (docs/OBSERVABILITY.md): the frontend sets both when
        # its tracer is enabled; None otherwise so disabled telemetry
        # allocates nothing per request
        self.trace_id: Optional[str] = None
        self.spans: Optional[dict] = None

    # ------------------------------------------------------------- ordering
    @property
    def order_key(self):
        """Admission order: priority class first, then earliest deadline
        (requests without a deadline sort after all deadlined peers of the
        same priority), then FIFO by uid."""
        dl = self.deadline_t if self.deadline_t is not None else float("inf")
        return (self.priority, dl, self.uid)

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline_t is None:
            return False
        return (now if now is not None else time.monotonic()) > self.deadline_t

    @property
    def outstanding_tokens(self) -> int:
        """Work remaining: unprocessed prompt + undelivered generation
        budget (the router's least-outstanding-tokens load signal)."""
        return max(0, len(self.prompt_tokens) + self.max_new_tokens
                   - self.n_generated)

    @property
    def shed_key(self):
        """Brownout victim order (docs/SERVING.md "Disaggregated
        serving"): class shed rank FIRST (batch sheds before interactive
        regardless of priority), then lowest urgency within the class —
        the maximum over queued sheddable entries is the victim."""
        return (self.shed_rank,) + tuple(self.order_key)

    def take_staged(self) -> Optional[dict]:
        """Consume the staged KV handoff payload (one-shot): returns it
        and frees the staging-buffer slot. None when nothing is staged —
        the caller takes the re-prefill path."""
        payload, self.staged_kv = self.staged_kv, None
        self._release_staged()
        return payload

    def _release_staged(self) -> None:
        self.staged_kv = None
        rel, self._staged_release = self._staged_release, None
        if rel is not None:
            rel()

    # --------------------------------------------------------- failover
    @property
    def remaining_new_tokens(self) -> int:
        """Generation budget still owed to the stream (resume semantics:
        tokens already delivered are never re-generated)."""
        return max(0, self.max_new_tokens - self.n_generated)

    def resume_prompt(self) -> List[int]:
        """The prefix a retry must prefill: original prompt + every token
        already delivered. Greedy decoding of this prefix continues the
        stream byte-identically, so failover is lossless (and composes
        with the prefix cache — the re-prefill hits the shared index)."""
        return self.prompt_tokens + self.generated_tokens

    # ------------------------------------------------------------ telemetry
    def begin_span(self, tracer, name: str, attrs: Optional[dict] = None):
        """Open the next stage span of this request's trace (no-op when
        telemetry was off at submit). Parented under the root ``request``
        span; stages end their predecessor explicitly, and ``finish``
        closes whatever stage the request died in (``end`` is
        idempotent)."""
        if self.spans is None:
            return None
        sp = tracer.begin(name, trace_id=self.trace_id,
                          parent=self.spans.get("request"), attrs=attrs)
        self.spans[name] = sp
        return sp

    def end_span(self, name: str) -> None:
        sp = self.spans.get(name) if self.spans is not None else None
        if sp is not None:
            sp.end()

    # ------------------------------------------------------------ streaming
    def push_token(self, token: int) -> None:
        now = time.monotonic()
        if self.first_token_t is None:
            self.first_token_t = now
        self.last_token_t = now
        self._events.put(TokenEvent(self.uid, int(token),
                                    self.n_generated, now))
        self.generated_tokens.append(int(token))
        self.n_generated += 1

    def finish(self, state: RequestState, reason: str) -> None:
        if self._done.is_set():
            return
        # a terminal request can never consume its staged KV handoff —
        # drop the payload and free the staging slot
        self._release_staged()
        self.state = state
        self.finish_reason = reason
        self.finished_t = time.monotonic()
        if self.spans is not None:
            # terminal close-out: stamp the outcome on the root span and
            # end every stage still open (whichever stage the request
            # died in — end() is idempotent for stages already closed)
            root = self.spans.get("request")
            if root is not None:
                root.set("state", state.value).set("finish_reason", reason)
                root.set("generated", self.n_generated)
                root.set("attempts", self.attempts)
                if self.handoffs:
                    root.set("handoffs", self.handoffs)
            for sp in self.spans.values():
                sp.end()
        self._events.put(DoneEvent(self.uid, reason, self.finished_t))
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        """True once the request reached a terminal state (the tenancy
        ledger's reconcile predicate for releasing KV charges)."""
        return self._done.is_set()


class RequestHandle:
    """User-facing view: stream tokens, wait for the result, cancel."""

    def __init__(self, req: ServingRequest, frontend):
        self._req = req
        self._frontend = frontend

    @property
    def uid(self) -> int:
        return self._req.uid

    @property
    def state(self) -> RequestState:
        return self._req.state

    @property
    def finish_reason(self) -> Optional[str]:
        return self._req.finish_reason

    @property
    def attempts(self) -> int:
        """Replica assignments this request took (1 = no failover; >1 =
        the stream was spliced across replica deaths transparently)."""
        return self._req.attempts

    def cancel(self) -> None:
        self._frontend.cancel(self)

    def stream(self, timeout: Optional[float] = None) -> Iterator[TokenEvent]:
        """Yield TokenEvents as they arrive; returns on the DoneEvent.
        ``timeout`` bounds the wait for EACH event (raises queue.Empty)."""
        while True:
            ev = self._req._events.get(timeout=timeout)
            if isinstance(ev, DoneEvent):
                return
            yield ev

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until terminal; returns the generated tokens."""
        if not self._req.wait(timeout):
            raise TimeoutError(f"request {self.uid} not finished "
                               f"within {timeout}s")
        return [ev.token for ev in self.drain()]

    def drain(self) -> List[TokenEvent]:
        """Non-blocking: all TokenEvents buffered so far."""
        out = []
        while True:
            try:
                ev = self._req._events.get_nowait()
            except queue.Empty:
                return out
            if isinstance(ev, DoneEvent):
                # keep terminal visible to later drains/streams
                self._req._events.put(ev)
                return out
            out.append(ev)
