"""ServingFrontend — the production request surface over InferenceEngineV2.

Composes the whole serving stack::

    submit()/stream()/cancel()
        └─ AdmissionQueue   (bounded; sheds with Rejected("overloaded"))
             └─ ReplicaRouter (least-outstanding-tokens, health/drain)
                  └─ Replica × N (thread-per-replica Dynamic SplitFuse
                       loops over InferenceEngineV2; streaming delivery,
                       cancel → immediate KV free)

All telemetry lands in one :class:`MetricsRegistry` (TTFT/TPOT/queue
histograms, shed/cancel/complete counters) that fans out through the
``monitor/`` backends via :meth:`publish_metrics` and feeds ``bench.py``'s
serving phase.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..telemetry import FlightRecorder  # noqa: F401  (re-export surface)
from ..telemetry.fleet import FleetJournal
from ..telemetry.journal import OpsJournal
from ..telemetry.slo import AlertEngine
from ..telemetry.windowed import WindowedMetrics
from ..utils.locks import RankedLock
from ..utils.logging import logger
from .config import ServingConfig
from .metrics import MetricsRegistry, serving_metrics
from .queue import AdmissionQueue
from .replica import Replica
from .request import (FinishReason, Rejected, RequestHandle,
                      RequestState, ServingRequest)
from .router import ReplicaRouter


class _PeerRef:
    """Engine-factory sentinel for a fabric peer slot: the supervisor's
    restart path calls ``engine_factory(rid)`` then
    ``replica_factory(rid, engine)`` — for a remote slot the "engine"
    is the peer address, and the replica factory builds a fresh
    RemoteHandle (dial + server-side engine reset) instead."""

    def __init__(self, address: str):
        self.address = address


def apply_engine_serving_config(engine, config: ServingConfig) -> None:
    """Stamp the engine-level serving blocks (weight_quant → kv_quant →
    prefix_cache → kv_tier → admission, in dependency order) onto a
    built engine — the one configuration path shared by every replica
    build site: the frontend's boot/restart/grow paths AND the fabric
    replica server (fabric/server.py), so a remote engine is configured
    exactly as a local one would be."""
    if config.weight_quant.enabled:
        # applied FIRST and BEFORE any traffic (quantizing is lossy and
        # retraces the forward, both only legal with no tracked
        # sequences — true on every build path: boot, supervisor
        # restart, autoscaler grow, fabric server reset)
        configure = getattr(engine, "configure_weight_quant", None)
        if configure is not None:
            wq = config.weight_quant
            configure(True, dtype=wq.dtype, block=wq.block,
                      skip=list(wq.skip))
    if config.kv_quant.enabled:
        # re-allocates the pools — only legal with no tracked sequences
        configure = getattr(engine, "configure_kv_quant", None)
        if configure is not None:
            configure(True, config.kv_quant.dtype,
                      config.kv_quant.scale_granularity)
    if config.prefix_cache.enabled:
        # safe on a built engine: matching simply starts now
        configure = getattr(engine, "configure_prefix_cache", None)
        if configure is not None:
            configure(True, config.prefix_cache.max_cached_blocks or None)
    if config.kv_tier.enabled:
        # AFTER the prefix cache (the tier requires it — the engine
        # raises on a tier without the cache, better caught at boot)
        configure = getattr(engine, "configure_kv_tier", None)
        if configure is not None:
            kt = config.kv_tier
            configure(True, host_bytes=kt.host_max_bytes,
                      disk_path=kt.disk_path, disk_bytes=kt.disk_max_bytes)
    if config.admission.active:
        # stamped BEFORE the replica builds its scheduler (schedulers
        # read engine config at construction)
        configure = getattr(engine, "configure_admission", None)
        if configure is not None:
            adm = config.admission
            configure(adm.reservation,
                      oversubscription_factor=adm.oversubscription_factor,
                      preemption_enabled=adm.preemption.enabled,
                      victim_policy=adm.preemption.victim_policy,
                      max_preemptions_per_seq=(
                          adm.preemption.max_preemptions_per_seq))


def engine_from_model_spec(spec):
    """Build one InferenceEngineV2 from a
    :class:`~deepspeed_tpu.serving.config.ModelSpec` — the same
    ``{model, engine, seed, checkpoint}`` shape
    ``scripts/serve_replica.py`` serves from, so one dict describes a
    model pool whether its replicas run in-process or behind the fabric
    (seeded init / checkpoint loading yields identical weights on both
    sides, which is what makes cross-process per-model parity
    testable)."""
    import jax

    from ..inference.v2.engine_v2 import (InferenceEngineV2,
                                          RaggedInferenceEngineConfig)
    from ..models.transformer import CausalLM, TransformerConfig

    model = CausalLM(TransformerConfig(**dict(spec.model)))
    if spec.checkpoint:
        from ..runtime.checkpointing import load_params_for_model

        params = load_params_for_model(model, spec.checkpoint)
    else:
        params = model.init(jax.random.PRNGKey(int(spec.seed)))
    return InferenceEngineV2(
        model, params=params,
        config=RaggedInferenceEngineConfig(**dict(spec.engine)))


class ServingFrontend:
    # lock discipline (docs/CONCURRENCY.md): membership admin state is
    # written under the fleet lock. ``_closed``, ``_role_overrides``
    # and ``_replica_models`` are writes-only guarded — their readers
    # (submit's fast-path check, the supervisor's restart-time role /
    # model lookup) take lock-free last-write-wins snapshots by design.
    _GUARDED_BY = {
        "_closed": "_fleet_lock:writes",
        "_next_replica_id": "_fleet_lock",
        "_role_overrides": "_fleet_lock:writes",
        "_replica_models": "_fleet_lock:writes",
    }

    def __init__(self, engines: Sequence, config: Optional[ServingConfig] = None,
                 sample_fn: Optional[Callable] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 engine_factory: Optional[Callable[[int], object]] = None,
                 model_engine_factories: Optional[Dict[str, Callable]] = None):
        """``engines``: one InferenceEngineV2 per replica (the caller owns
        model/param placement; replicas never share an engine — each owns
        its KV pool and scheduler). ``engine_factory(replica_id)``, when
        given, is how the supervisor builds FRESH engines for restarted
        replicas (docs/SERVING.md "Fault tolerance"); without it a
        restart reuses the dead replica's engine when that is safe."""
        self.config = config or ServingConfig()
        # cross-process serving fabric (docs/SERVING.md "Multi-host
        # serving"): peers are replica server processes adopted as
        # RemoteHandle replicas, ids allocated after the local engines.
        # None when disabled — no handles, no transport, the in-process
        # stack byte for byte.
        fab = self.config.fabric
        self._fabric = fab if fab.enabled else None
        peer_addrs = list(fab.peers) if self._fabric is not None else []
        # multi-model registry (docs/SERVING.md "Multi-model &
        # multi-tenant serving"): named ModelSpecs add heterogeneous
        # replica pools — local engines built from each spec (or a
        # caller-supplied ``model_engine_factories[name]``, which wins)
        # plus fabric peers hosting that model. Empty = the historical
        # single-pool stack, every replica model_id "default".
        self._models = dict(self.config.models)
        self._default_model = self.config.resolve_default_model()
        model_peer_count = sum(len(s.peers) for s in self._models.values())
        fed_peer_count = (len(fab.federation.peers)
                          if self._fabric is not None
                          and fab.federation.enabled else 0)
        if not engines and not peer_addrs and not self._models \
                and not fed_peer_count:
            # an edge frontend with NO local chips is a legitimate
            # federation topology: it serves entirely off peers' exports
            raise ValueError("ServingFrontend needs at least one engine "
                             "(or fabric.peers, fabric.federation.peers, "
                             "or a models: registry)")
        if (peer_addrs or model_peer_count or fed_peer_count) \
                and sample_fn is not None:
            # a frontend-level callable cannot cross the wire: remote
            # replicas would silently fall back to greedy sampling while
            # local ones use the custom sampler — same request,
            # different tokens depending on routing. Refuse loudly.
            raise ValueError(
                "fabric.peers is incompatible with a custom sample_fn — "
                "a sampler callable cannot cross the process boundary "
                "(configure sampling in the replica servers' specs "
                "instead)")
        # the registry pre-declares every per-class series for the
        # CONFIGURED classes — and every per-tenant series for the
        # configured tenants — so custom classes and tenants expose
        # zero-valued Prometheus series before first traffic too
        self.metrics = metrics or serving_metrics(
            sorted(self.config.classes),
            tenants=sorted(self.config.tenants))
        # telemetry (docs/OBSERVABILITY.md): one tracer for the whole
        # frontend — request stage spans begin here at submit, the
        # router/replicas/scheduler continue the chain — plus a flight
        # recorder over it. Both are no-ops when ``telemetry.enabled`` is
        # false; debug_dump() still works (metrics only, no spans).
        self.tracer = self.config.telemetry.build_tracer()
        self.recorder = self.config.telemetry.build_recorder(
            self.tracer, metrics=self.metrics)
        # SLO observability (docs/OBSERVABILITY.md "SLOs and burn-rate
        # alerts"). The journal and the windowed-metrics ring are always
        # on: both are passive bounded buffers (an incident record you
        # have to remember to enable is one you won't have), and neither
        # touches the request hot path — the windowed ring is fed by the
        # router's ~1/s tick. The AlertEngine exists only under
        # ``slo.enabled``.
        slo = self.config.slo
        self.journal = OpsJournal(capacity=slo.journal_capacity,
                                  source="serving",
                                  path=slo.journal_path)
        # fleet observability (docs/OBSERVABILITY.md "Fleet
        # observability"): the FleetJournal wraps the local journal with
        # per-source rings for the remote journal batches the status
        # streams carry (replica servers, federation peers). Passive and
        # bounded like the journal itself — always on; it holds nothing
        # until a remote source actually forwards.
        self.fleet = FleetJournal(self.journal)
        self.windowed = WindowedMetrics(self.metrics,
                                        bucket_s=slo.window_bucket_s,
                                        history_s=slo.window_history_s)
        # KV-tier pressure journaling state (docs/SERVING.md "KV
        # tiering"): per-replica-slot counter baselines as of the last
        # EMITTED event + the ~1/s cadence gate — must exist before the
        # router tick can fire
        self._tier_journal_t = 0.0
        self._tier_last: dict = {}
        self.alerts = None
        if slo.enabled:
            self.alerts = AlertEngine(slo, self.windowed,
                                      metrics=self.metrics,
                                      journal=self.journal,
                                      recorder=self.recorder)
        if self.config.ttft_buckets_s:
            self.metrics.histogram("ttft_s", self.config.ttft_buckets_s,
                                   reset=True)
        # multi-tenant fair share / quotas (docs/SERVING.md "Multi-model
        # & multi-tenant serving"): one ledger per frontend, consulted
        # by the queue's DWF pop and the router's KV-budget filter. None
        # when no ``tenants:`` block — every path byte-identical.
        self._tenancy = None
        if self.config.tenants:
            from .tenancy import TenantLedger

            self._tenancy = TenantLedger(self.config.tenants,
                                         metrics=self.metrics,
                                         journal=self.journal)
        ft = self.config.fault_tolerance
        self.admission = AdmissionQueue(
            self.config.max_queue_depth, self.metrics,
            brownout_threshold=(ft.brownout_threshold if ft.enabled
                                else 0.0),
            journal=self.journal, tenancy=self._tenancy)
        # elastic autoscaling (docs/SERVING.md "Elastic autoscaling"):
        # dynamic membership state. Replica ids are allocated
        # monotonically and never reused; role overrides (set by
        # add_replica / set_replica_role) win over the static
        # disaggregation.roles list; the fleet lock serializes
        # membership mutations (the controller issues one at a time,
        # but the API must be safe for direct callers too).
        self._engine_factory = engine_factory
        # replica-id layout: caller engines, global fabric peers, then
        # each named model pool (locals before peers) in sorted-name
        # order — ids stay monotonic and are never reused either way
        self._peer_addrs = {len(engines) + i: addr
                            for i, addr in enumerate(peer_addrs)}
        # rid -> model_id for every slot outside the unnamed-default
        # pool (absent = "default"); with a models: registry the
        # caller's plain engines serve the default model's pool
        self._replica_models: Dict[int, str] = {}
        if self._models:
            for rid in range(len(engines) + len(peer_addrs)):
                self._replica_models[rid] = self._default_model
        next_rid = len(engines) + len(peer_addrs)
        self._model_factories: Dict[str, Callable] = {}
        model_locals = []                       # (rid, model name)
        for name in sorted(self._models):
            spec = self._models[name]
            fac = (model_engine_factories or {}).get(name)
            if fac is None:
                if not spec.model:
                    raise ValueError(
                        f"models.{name} has no model kwargs and no "
                        f"model_engine_factories[{name!r}] entry — "
                        f"nothing to build its pool from")

                def fac(spec=spec):
                    return engine_from_model_spec(spec)
            self._model_factories[name] = fac
            for _ in range(spec.replicas):
                model_locals.append((next_rid, name))
                self._replica_models[next_rid] = name
                next_rid += 1
            for addr in spec.peers:
                self._peer_addrs[next_rid] = addr
                self._replica_models[next_rid] = name
                next_rid += 1
        self._next_replica_id = next_rid
        self._role_overrides: dict = {}
        self._fleet_lock = RankedLock("serving.frontend.fleet")
        # frontend federation (docs/SERVING.md "Frontend federation"):
        # a two-tier fleet — this frontend EXPORTS a slice of its local
        # pool on fabric.listen and ADOPTS peers' exports as routable
        # members. All None/empty when disabled: no identity derived,
        # no listener bound, no peers dialed — the historical stack
        # byte for byte. The server starts BEFORE peer adoption so a
        # misconfigured self-peer gets the typed refusal, not a
        # connection error.
        self._federation = None
        self._federation_server = None
        self._federation_peers: list = []
        self._federated_refs: dict = {}
        if self._fabric is not None and fab.federation.enabled:
            from .fabric.federation import (FederationServer,
                                            derive_epoch,
                                            derive_frontend_id)

            self._federation = fab.federation
            self._federation_id = (fab.federation.frontend_id
                                   or derive_frontend_id())
            self._federation_epoch = derive_epoch()
            self._federation_server = FederationServer(
                self, listen=fab.listen,
                frontend_id=self._federation_id,
                epoch=self._federation_epoch)
            self._federation_server.start()
        # evacuated KV rides the same bounded host-RAM staging budget
        # as disagg handoffs (built lazily when no handoff stager
        # exists) — a removal of a fully-loaded replica must not
        # balloon host RAM; over-budget payloads drop to re-prefill
        self._evac_stager = None
        # speculative decoding is applied per replica: each Replica builds
        # its own proposer from the block (draft state is per-engine)
        self._sample_fn = sample_fn
        self._spec = (self.config.speculative
                      if self.config.speculative.enabled else None)
        self._replica_recorder = (self.recorder
                                  if self.config.telemetry.dump_on_error
                                  else None)
        # deterministic fault injection (test-only; serving/faults.py) —
        # None when the ``faults:`` block is off: no hooks, no proxies
        self.injector = self.config.faults.build_injector()
        # deterministic NETWORK fault injection (test-only;
        # serving/fabric/chaos.py) — installed process-wide so every
        # connection dialed or accepted from here on interposes its
        # matching schedule; None when the ``chaos:`` block is off: the
        # transport never sees a shim (byte-for-byte, asserted)
        self.net_chaos = self.config.chaos.build_injector()
        if self.net_chaos is not None:
            from .fabric import chaos as _net_chaos

            _net_chaos.install(self.net_chaos)
        # disaggregated prefill/decode serving (docs/SERVING.md
        # "Disaggregated serving"): role-split replicas + host-RAM KV
        # handoff staging. None when disabled — no role enforcement, no
        # handoff hooks, the historical single-role stack byte for byte.
        dis = self.config.disaggregation
        self._disagg = dis if dis.enabled else None
        self._stager = None
        if self._disagg is not None:
            self._validate_disaggregation(self._next_replica_id)
            if dis.handoff.enabled:
                from .handoff import HandoffStager

                self._stager = HandoffStager(dis.handoff.max_staged,
                                             self.metrics)
        replicas = [self._build_replica(i, eng)
                    for i, eng in enumerate(engines)]
        replicas += [self._build_replica(rid, self._model_factories[name]())
                     for rid, name in model_locals]
        replicas += [self._build_remote(rid, addr)
                     for rid, addr in sorted(self._peer_addrs.items())]
        replicas += self._adopt_federation_peers()
        # ~1/s observability tick on the router loop: windowed-metrics
        # snapshots always; SLO alert evaluation when enabled
        tick_hooks = [self._observability_tick]
        # fleet KV locality (docs/SERVING.md "Fleet KV locality"):
        # prefix-affinity routing state — digests refresh on the router
        # tick, pick(req) scores overlap as a prefill-token credit.
        # None when disabled: the cache-blind pick path byte for byte.
        self._affinity = None
        if self.config.affinity.enabled:
            from .affinity import AffinityState

            self._affinity = AffinityState(self.config.affinity,
                                           metrics=self.metrics)
        self.router = ReplicaRouter(replicas, self.admission, self.metrics,
                                    tracer=self.tracer,
                                    recorder=self.recorder,
                                    disaggregation=self._disagg,
                                    tick_hooks=tick_hooks,
                                    tenancy=self._tenancy,
                                    affinity=self._affinity)
        self.supervisor = None
        if ft.enabled:
            from .supervisor import ReplicaSupervisor

            # with fabric peers, the supervisor's engine source resolves
            # peer slots to _PeerRef sentinels (restart = fresh handle +
            # server-side engine reset), federated slots to their
            # _ExportRef (restart = re-adoption over the same export),
            # and local slots to the caller's factory
            self.supervisor = ReplicaSupervisor(
                self.router, self._build_replica,
                (self._engine_source
                 if (self._peer_addrs or self._model_factories
                     or self._federated_refs)
                 else engine_factory),
                config=ft, metrics=self.metrics, tracer=self.tracer,
                recorder=self.recorder, journal=self.journal)
            self.router.supervisor = self.supervisor
        # elastic autoscaling (docs/SERVING.md "Elastic autoscaling"):
        # the FleetController rides the router tick; its actuation
        # (engine builds, evacuation waits) runs on its own worker.
        # replicas_target is pinned to the boot size either way, so
        # dashboards see the fleet shape pre-traffic.
        self.metrics.gauge("replicas_target").set(len(engines))
        self.autoscaler = None
        asc = self.config.autoscaler
        if asc.enabled:
            if engine_factory is None and not self._model_factories:
                raise ValueError(
                    "autoscaler.enabled requires an engine_factory — a "
                    "fleet with no way to build engines cannot grow "
                    "(use ServingFrontend.from_engine_factory, pass "
                    "engine_factory=, or configure a models: registry "
                    "whose specs are buildable)")
            from .autoscaler import FleetController

            self.autoscaler = FleetController(
                asc, self, metrics=self.metrics, journal=self.journal)
            self.router.tick_hooks.append(self.autoscaler.maybe_tick)
        self._closed = False
        self.router.start()
        if self.supervisor is not None:
            self.supervisor.start()
        # fleet ops surface (docs/OBSERVABILITY.md "Fleet
        # observability"): the scrape endpoint binds LAST — its routes
        # read the live frontend (health_report/debug_dump), so nothing
        # may be reachable before the router runs. None when disabled:
        # no listener, no thread, the endpoint-less stack byte for byte.
        self._obs_endpoint = None
        obs = self.config.observability
        if obs.enabled:
            from ..telemetry.fleet import ObsEndpoint

            self._obs_endpoint = ObsEndpoint(self, listen=obs.listen)
            self.journal.emit("obs_listen",
                              address=self._obs_endpoint.address)

    def _validate_disaggregation(self, n_engines: int) -> None:
        """Reject role maps that cannot serve (docs/SERVING.md
        "Disaggregated serving"): unknown roles, a role list that does
        not match the fleet, a fleet with no decode-capable replica
        (prefill-only replicas can never emit a token), and prefill
        roles without the handoff path (their finished prompts would
        have nowhere to go)."""
        dis = self.config.disaggregation
        roles = list(dis.roles)
        bad = [r for r in roles if r not in ("prefill", "decode", "mixed")]
        if bad:
            raise ValueError(f"disaggregation.roles has unknown roles "
                             f"{bad} (expected prefill/decode/mixed)")
        if roles and len(roles) != n_engines:
            raise ValueError(
                f"disaggregation.roles lists {len(roles)} roles for "
                f"{n_engines} replicas — one role per replica")
        if roles and not any(r in ("decode", "mixed") for r in roles):
            raise ValueError("disaggregation.roles needs at least one "
                             "decode-capable (decode/mixed) replica")
        if "prefill" in roles and not dis.handoff.enabled:
            raise ValueError("disaggregation with prefill-role replicas "
                             "requires handoff.enabled")

    def _role_of(self, replica_id: int) -> str:
        override = self._role_overrides.get(replica_id)
        if override is not None:
            return override
        if self._disagg is None:
            return "mixed"
        return self._disagg.role_of(replica_id)

    def _engine_source(self, replica_id: int):
        """Supervisor-facing engine factory when fabric peers or model
        pools exist: peer slots resolve to :class:`_PeerRef` sentinels
        (the restart builds a fresh RemoteHandle against the same
        server), named-model slots to that model's spec factory (a
        restarted pool member must host ITS model, not the default
        one), local default slots to the caller's factory — or ``None``
        when there is no factory, which tells the supervisor to take
        its historical salvage-engine path (a mixed fleet without a
        factory must keep the same local-restart behavior it had before
        fabric)."""
        ref = self._federated_refs.get(replica_id)
        if ref is not None:
            # federated slot: restart = a fresh mirror over the SAME
            # export on the SAME peer (the exporter owns the replica)
            return ref
        addr = self._peer_addrs.get(replica_id)
        if addr is not None:
            return _PeerRef(addr)
        fac = self._model_factories.get(
            self._replica_models.get(replica_id, "default"))
        if fac is not None:
            return fac()
        if self._engine_factory is None:
            return None
        return self._engine_factory(replica_id)

    def _build_remote(self, replica_id: int, address: str,
                      reset: bool = False):
        """One RemoteHandle over a fabric peer with this frontend's full
        wiring — the boot path AND the supervisor's restart path
        (``reset=True`` additionally rebuilds the server-side engine, so
        a restarted remote replica is as fresh as a restarted local
        one). The server applies the engine-level config blocks itself
        (``apply_engine_serving_config`` from ITS spec) — the role is
        the one thing the frontend dictates."""
        from .fabric.remote import RemoteHandle

        ft = self.config.fault_tolerance
        handle = RemoteHandle(
            replica_id, address, self.config.fabric,
            role=self._role_of(replica_id), metrics=self.metrics,
            tracer=self.tracer, recorder=self._replica_recorder,
            journal=self.journal, fleet=self.fleet,
            model_id=self._replica_models.get(replica_id, "default"),
            on_failover=self._failover if ft.enabled else None,
            on_handoff=self._handoff_remote)
        handle.connect(reset=reset)
        return handle

    def _adopt_federation_peers(self) -> list:
        """Dial each ``fabric.federation.peers`` frontend, run the
        bootstrap hello (identity exchange + export discovery) and
        build a :class:`FederatedHandle` router member per adopted
        export. Typed peering refusals (self-peering, stale epoch)
        raise — they are config bugs; an unreachable peer is logged
        and skipped — edge frontends boot independently. Exports of
        models this frontend does not serve are skipped: a request can
        only route to pools its submit() validates."""
        fed = self._federation
        if fed is None or not fed.peers:
            return []
        from .fabric.federation import (FederationPeer, FederationRefused,
                                        _ExportRef)
        from .fabric.transport import FabricError

        handles = []
        known = set(self._models) if self._models else {"default"}
        for addr in fed.peers:
            peer = FederationPeer(addr, self.config.fabric,
                                  frontend_id=self._federation_id,
                                  epoch=self._federation_epoch)
            try:
                peer.connect()
            except FederationRefused:
                raise               # config/topology bug: loud
            except (OSError, FabricError) as e:
                logger.warning(f"federation peer {addr} unreachable at "
                               f"boot ({e!r}); continuing without it")
                continue
            self._federation_peers.append(peer)
            for exp in peer.exports:
                mid = str(exp.get("model_id", "default"))
                if mid not in known:
                    logger.warning(
                        f"federation peer {addr} exports replica "
                        f"{exp.get('export')} of unknown model {mid!r}; "
                        "skipping")
                    continue
                with self._fleet_lock:
                    rid = self._next_replica_id
                    self._next_replica_id += 1
                    if self._models:
                        self._replica_models[rid] = mid
                ref = _ExportRef(addr, exp, peer)
                self._federated_refs[rid] = ref
                handles.append(self._build_federated(rid, ref))
        return handles

    def _build_federated(self, replica_id: int, ref,
                         reset: bool = False):
        """One FederatedHandle over a peer frontend's exported replica
        — the boot path AND the supervisor's restart path. The evacuate
        hand-back is ALWAYS wired (unlike plain remotes, where removal
        sets it): the exporter's autoscaler can spontaneously evacuate
        the shared replica, and those hand-backs must land in this
        frontend's requeue path, not drop."""
        from .fabric.federation import FederatedHandle

        ft = self.config.fault_tolerance
        handle = FederatedHandle(
            replica_id, ref.address, self.config.fabric,
            export=ref.export, frontend_id=self._federation_id,
            epoch=self._federation_epoch, peer=ref.peer,
            metrics=self.metrics, tracer=self.tracer,
            recorder=self._replica_recorder, journal=self.journal,
            fleet=self.fleet,
            on_failover=self._failover if ft.enabled else None,
            on_handoff=self._handoff_remote)
        handle._evac_handback = self._evacuate_handback
        handle.connect(reset=reset)
        if ref.peer is not None:
            ref.peer.register(handle)
        return handle

    def _build_replica(self, replica_id: int, engine) -> Replica:
        """One replica over ``engine`` with this frontend's full wiring —
        the constructor path AND the supervisor's restart path, so a
        restarted replica is indistinguishable from a first-boot one
        (prefix cache applied, proposer built, telemetry attached).
        A :class:`_PeerRef` "engine" builds a RemoteHandle instead —
        the supervisor's restart path for fabric peer slots."""
        if isinstance(engine, _PeerRef):
            return self._build_remote(replica_id, engine.address,
                                      reset=True)
        from .fabric.federation import _ExportRef

        if isinstance(engine, _ExportRef):
            # federated slot restart: fresh mirror, same export (the
            # peer ignores the reset bit — it owns the engine)
            return self._build_federated(replica_id, engine, reset=True)
        # engine-level config blocks (weight/kv quant, prefix cache,
        # tier, admission) — the shared path also used by the fabric
        # replica server, so local and remote engines configure alike
        apply_engine_serving_config(engine, self.config)
        ft = self.config.fault_tolerance
        role = self._role_of(replica_id)
        cls = Replica
        if self._fabric is not None:
            # fabric fleets name their in-process workers LocalHandle —
            # an EMPTY Replica subclass (fabric/handle.py), so behavior
            # is identical by construction; disabled fabric keeps plain
            # Replica, the byte-for-byte historical path
            from .fabric.handle import LocalHandle

            cls = LocalHandle
        return cls(replica_id, engine, self.metrics, self._sample_fn,
                       wedge_timeout_s=self.config.wedge_timeout_s,
                       speculative=self._spec, tracer=self.tracer,
                       recorder=self._replica_recorder,
                       faults=self.injector,
                       on_failover=self._failover if ft.enabled else None,
                       role=role,
                       model_id=self._replica_models.get(replica_id,
                                                         "default"),
                       decode_reserve_tokens=(
                           self._disagg.decode_reserve_tokens
                           if self._disagg is not None else 0),
                       on_handoff=(self._handoff if role == "prefill"
                                   else None),
                       journal=self.journal)

    @property
    def federation_address(self) -> Optional[str]:
        """host:port of this frontend's federation listener (None when
        federation is disabled) — what peers put in
        ``fabric.federation.peers``."""
        srv = self._federation_server
        return srv.address if srv is not None else None

    @classmethod
    def from_engine_factory(cls, engine_factory: Callable[[int], object],
                            config: Optional[ServingConfig] = None,
                            **kwargs) -> "ServingFrontend":
        """Build the replica fleet from the config:
        ``engine_factory(replica_id)`` is called ``config.num_replicas``
        times (the config-driven path for the ``serving: {...}`` block)."""
        config = config or ServingConfig()
        engines = [engine_factory(i)
                   for i in range(max(1, config.num_replicas))]
        # the factory doubles as the supervisor's fresh-engine source for
        # restarted replicas (unless the caller passed its own)
        kwargs.setdefault("engine_factory", engine_factory)
        return cls(engines, config, **kwargs)

    # ---------------------------------------------------------------- submit
    def submit(self, prompt_tokens: List[int],
               max_new_tokens: Optional[int] = None,
               priority: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               eos_token_id: Optional[int] = None,
               request_class: Optional[str] = None,
               model: Optional[str] = None,
               tenant: Optional[str] = None) -> RequestHandle:
        """Admit a request. Raises :class:`Rejected` when shed (full queue,
        draining frontend, or a prompt no replica could ever schedule).
        ``priority``/``deadline_ms``/``max_new_tokens`` default from the
        config (``default_priority`` etc.). ``request_class`` selects an
        entry of ``config.classes`` (default ``config.default_class``):
        its policy fills priority/deadline when the caller passes
        neither, labels the per-class TTFT/TPOT/queue metrics, and
        orders brownout shedding (docs/SERVING.md "Disaggregated
        serving"). ``model`` selects an entry of ``config.models``
        (default ``config.resolve_default_model()``) — the request only
        routes to replicas of that pool; ``tenant`` selects an entry of
        ``config.tenants`` (default ``"default"``) for fair-share /
        quota accounting (docs/SERVING.md "Multi-model & multi-tenant
        serving"). Both default so every pre-tenancy call site behaves
        byte-identically."""
        cfg = self.config
        cls = request_class if request_class is not None else cfg.default_class
        policy = cfg.classes.get(cls)
        if policy is None:
            # caller bug, not traffic: reject BEFORE requests_submitted
            # so the submitted/admitted/shed balance stays honest
            raise ValueError(f"unknown request class {cls!r} "
                             f"(configured: {sorted(cfg.classes)})")
        # unknown model / tenant are caller bugs too, refused before any
        # counter moves for the same reason
        model_id = model if model is not None else self._default_model
        known_models = set(self._models) if self._models else {"default"}
        if model_id not in known_models:
            raise ValueError(f"unknown model {model_id!r} "
                             f"(configured: {sorted(known_models)})")
        tenant_id = tenant if tenant is not None else "default"
        if self._tenancy is not None and not self._tenancy.known(tenant_id):
            raise ValueError(f"unknown tenant {tenant_id!r} "
                             f"(configured: {self._tenancy.tenant_names})")
        if self._tenancy is None:
            # no tenants: config, no tenant namespace — a named tenant
            # is accepted (so call sites are portable across deployments
            # with tenancy on and off) but normalized to "default", or
            # replicas would mint per-tenant latency series the registry
            # never declared and the tenancy-off metrics snapshot would
            # stop being byte-identical to the historical one
            tenant_id = "default"
        self.metrics.counter("requests_submitted").inc()
        # per-class submit counter: the denominator of the SLO engine's
        # windowed availability burn rate (docs/OBSERVABILITY.md "SLOs
        # and burn-rate alerts"); the per-tenant twin is the denominator
        # of the per-tenant availability rule
        self.metrics.counter(f"requests_submitted_class_{cls}").inc()
        if self._tenancy is not None:
            self.metrics.counter(
                f"requests_submitted_tenant_{tenant_id}").inc()
        if self._closed:
            self.metrics.counter("requests_shed").inc()
            self.metrics.counter(f"requests_shed_class_{cls}").inc()
            if self._tenancy is not None:
                self.metrics.counter(
                    f"requests_shed_tenant_{tenant_id}").inc()
            raise Rejected("draining", "frontend is shut down")
        if priority is None:
            priority = (policy.priority if policy.priority is not None
                        else cfg.default_priority)
        if deadline_ms is None:
            deadline_ms = (policy.deadline_ms
                           if policy.deadline_ms is not None
                           else cfg.default_deadline_ms)
        req = ServingRequest(
            prompt_tokens,
            max_new_tokens if max_new_tokens is not None
            else cfg.default_max_new_tokens,
            priority, deadline_ms / 1e3 if deadline_ms is not None else None,
            eos_token_id,
            request_class=cls, shed_rank=policy.shed_rank,
            tenant=tenant_id, model_id=model_id)
        if self.tracer.enabled:
            # root of this request's trace + the first stage (queue wait).
            # Rejection paths below close both via req.finish.
            req.trace_id = f"req-{req.uid}"
            req.spans = {"request": self.tracer.begin(
                "request", trace_id=req.trace_id,
                attrs={"uid": req.uid,
                       "prompt_tokens": len(req.prompt_tokens),
                       "max_new_tokens": req.max_new_tokens,
                       "priority": req.priority,
                       "class": req.request_class})}
            req.begin_span(self.tracer, "queue")
        # length bound over the request's OWN pool: heterogeneous pools
        # may have different max_seq_len, and a request must not be shed
        # for exceeding a bound only some other model's replicas have
        pool_lens = [r.engine.model.cfg.max_seq_len
                     for r in self.router.replicas
                     if getattr(r, "model_id", "default") == req.model_id]
        max_len = min(pool_lens) if pool_lens else 0
        if len(req.prompt_tokens) + req.max_new_tokens > max_len:
            self.metrics.counter("requests_shed").inc()
            self.metrics.counter(f"requests_shed_class_{cls}").inc()
            if self._tenancy is not None:
                self.metrics.counter(
                    f"requests_shed_tenant_{tenant_id}").inc()
            req.finish(RequestState.REJECTED, "too_long")
            raise Rejected("too_long",
                           f"{len(req.prompt_tokens)}+{req.max_new_tokens} "
                           f"tokens > max_seq_len {max_len}")
        self.admission.offer(req, block=cfg.shed_policy == "block")
        return RequestHandle(req, self)

    # ------------------------------------------------------------ handoff
    def _handoff(self, req: ServingRequest, sreq, engine,
                 replica_id: int) -> None:
        """Prefill-role completion hand-back (docs/SERVING.md
        "Disaggregated serving"). Runs on the prefill replica's worker
        thread (race-free engine access): export the finished prompt's
        KV blocks to host RAM, flush them from the source engine, stage
        the payload on the request, and re-queue it for a decode-role
        replica. Export failure or a full staging buffer degrades to the
        recompute fallback — the request re-prefills on a decode-capable
        replica (the PR 5 resume path), never crashes. Cancel, deadline,
        and shutdown races settle here before any staging."""
        if (self._closed or req.cancel_requested.is_set()
                or req.expired()):
            try:
                engine.flush(req.uid)
            except Exception:
                pass
            if req.cancel_requested.is_set():
                req.finish(RequestState.CANCELLED, FinishReason.CANCELLED)
                self.metrics.counter("requests_cancelled").inc()
            elif req.expired():
                req.finish(RequestState.EXPIRED, FinishReason.DEADLINE)
                self.metrics.counter("requests_expired").inc()
            else:
                req.finish(RequestState.REJECTED, "draining")
                self.metrics.counter("requests_shed").inc()
            return
        payload = None
        try:
            # block-granularity streamed export (docs/SERVING.md
            # "Multi-host serving"): chunk_blocks > 0 dispatches every
            # chunk's host copy before any materializes (overlapped
            # copies, host-RAM payload) in units the import/wire side
            # streams one at a time
            payload = engine.export_sequence(
                req.uid, chunk_blocks=self._disagg.handoff.chunk_blocks)
        except Exception as e:
            logger.warning(f"serving replica {replica_id}: KV export for "
                           f"request {req.uid} failed ({e!r}); falling "
                           "back to re-prefill on a decode-capable replica")
        finally:
            try:
                engine.flush(req.uid)
            except Exception:
                pass
        if payload is not None:
            # last_logits rides the payload: the decode replica samples
            # its first token from the source's final prompt position —
            # the byte-losslessness hinge
            payload["last_logits"] = sreq.last_logits
        self._stage_handoff(req, payload, replica_id)

    def _handoff_remote(self, req: ServingRequest, payload,
                        replica_id: int) -> None:
        """Remote-prefill completion (docs/SERVING.md "Multi-host
        serving"): the export and flush already ran in the replica
        server process; settle the cancel/deadline/shutdown races here
        and stage/requeue exactly like the local path (``payload`` None
        = server-side export failed or broke the frame bound → the same
        recompute fallback)."""
        if (self._closed or req.cancel_requested.is_set()
                or req.expired()):
            if req.cancel_requested.is_set():
                req.finish(RequestState.CANCELLED, FinishReason.CANCELLED)
                self.metrics.counter("requests_cancelled").inc()
            elif req.expired():
                req.finish(RequestState.EXPIRED, FinishReason.DEADLINE)
                self.metrics.counter("requests_expired").inc()
            else:
                req.finish(RequestState.REJECTED, "draining")
                self.metrics.counter("requests_shed").inc()
            return
        self._stage_handoff(req, payload, replica_id)

    def _stage_handoff(self, req: ServingRequest, payload,
                       replica_id: int) -> None:
        """Shared tail of the prefill→decode handoff (local export and
        remote payload alike): stage under the host-RAM budget and
        requeue for a decode-capable replica, or degrade to the
        recompute fallback."""
        # the "handoff" span covers staging + queue wait + import; it is
        # ended by the decode replica at import (or by req.finish)
        req.begin_span(self.tracer, "handoff",
                       attrs={"from_replica": replica_id,
                              "blocks": (payload or {}).get("n_blocks", 0)})
        if payload is not None and self._stager is not None \
                and self._stager.try_stage(req, payload):
            self.metrics.counter("handoffs_started").inc()
            self.journal.emit("handoff_staged", uid=req.uid,
                              from_replica=replica_id,
                              blocks=payload.get("n_blocks", 0))
            req.handoff_t = time.monotonic()
        else:
            # every degraded handoff counts — export failure AND a full
            # staging buffer — or a fleet whose exports always fail
            # would be indistinguishable from one that never handed off
            self.metrics.counter("handoff_fallbacks").inc()
            self.journal.emit(
                "handoff_fallback", uid=req.uid,
                where=("export" if payload is None else "staging_full"),
                from_replica=replica_id)
            # recompute fallback: must not land on a prefill-only
            # replica (it would just hand off again — or loop forever
            # when handoff keeps failing)
            req.no_prefill = True
        req.state = RequestState.QUEUED
        req.replica_id = None
        if not self.admission.requeue(req):
            # queue closed mid-handoff: shutdown — terminal, slot freed
            req.finish(RequestState.REJECTED, "draining")
            self.metrics.counter("requests_shed").inc()

    # ----------------------------------------------------------- failover
    def _failover(self, req: ServingRequest) -> bool:
        """Replica-death hand-back (docs/SERVING.md "Fault tolerance").
        Returns True when the request was handled here — re-enqueued for
        another attempt (the stream stays open and resumes on a healthy
        replica from prompt + delivered tokens, lossless under greedy
        decoding) or completed because nothing more was owed. False →
        the caller fails it terminally (retries exhausted, deadline
        passed, cancellation, or shutdown)."""
        if getattr(req, "_federated", False) \
                and self._federation_server is not None:
            # federated mirror (docs/SERVING.md "Frontend federation"):
            # the real stream and the retry budget live on the ADOPTING
            # frontend — send the ordered failover marker back over the
            # federation channel instead of requeueing into THIS
            # frontend's admission queue
            return self._federation_server.detach_failover(req)
        ft = self.config.fault_tolerance
        if self._closed or req.cancel_requested.is_set() or req.expired():
            return False
        if req.attempts > ft.max_retries:
            return False          # attempts = 1 + retries already taken
        ended_eos = (req.eos_token_id is not None and req.generated_tokens
                     and req.generated_tokens[-1] == req.eos_token_id)
        if req.remaining_new_tokens <= 0 or ended_eos:
            # the crash raced the finish: every owed token was delivered
            # (budget exhausted, or the EOS token itself already reached
            # the stream — resuming would generate past EOS)
            req.finish(RequestState.FINISHED,
                       FinishReason.EOS if ended_eos else FinishReason.LENGTH)
            self.metrics.counter("requests_completed").inc()
            return True
        req.attempts += 1
        req.state = RequestState.QUEUED
        req.replica_id = None
        if req.spans is not None:
            root = req.spans.get("request")
            if root is not None:
                root.set("attempts", req.attempts)
            # the span chain re-enters the queue stage; the attempt
            # number distinguishes the retry's stages in the trace
            req.begin_span(self.tracer, "queue",
                           attrs={"attempt": req.attempts})
        if not self.admission.requeue(req):
            return False          # queue closed mid-failover: shutdown
        self.metrics.counter("requests_failed_over").inc()
        self.journal.emit("request_failover", uid=req.uid,
                          attempt=req.attempts)
        return True

    # ------------------------------------------------- dynamic membership
    def add_replica(self, role: str = "mixed",
                    model_id: Optional[str] = None) -> int:
        """Grow the fleet by one replica built from the stored
        ``engine_factory`` — or, with ``model_id``, from that model
        pool's spec factory, so a grown pool member hosts the right
        model (docs/SERVING.md "Elastic autoscaling" / "Multi-model &
        multi-tenant serving"). Returns the new replica id (monotonic,
        never reused). Specialized roles require a role-split fleet:
        "prefill" additionally requires the handoff path (a prefill-only
        replica with nowhere to send its KV could never finish a
        request)."""
        fac = (self._model_factories.get(model_id)
               if model_id is not None else None)
        if model_id is not None and fac is None:
            raise ValueError(f"unknown model {model_id!r} (configured: "
                             f"{sorted(self._model_factories)})")
        if self._engine_factory is None and fac is None:
            raise RuntimeError("add_replica requires an engine_factory")
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(f"unknown replica role {role!r} "
                             "(expected prefill/decode/mixed)")
        if role != "mixed" and self._disagg is None:
            raise ValueError(f"role {role!r} requires "
                             "disaggregation.enabled — a single-role "
                             "fleet routes every replica as mixed")
        if role == "prefill" and not self._disagg.handoff.enabled:
            raise ValueError("adding a prefill-role replica requires "
                             "handoff.enabled")
        with self._fleet_lock:
            if self._closed:
                raise RuntimeError("frontend is shut down")
            rid = self._next_replica_id
            self._next_replica_id += 1
            self._role_overrides[rid] = role
            if model_id is not None:
                self._replica_models[rid] = model_id
            try:
                engine = (fac() if fac is not None
                          else self._engine_factory(rid))
                replica = self._build_replica(rid, engine)
                # restore-before-rotation (docs/SERVING.md "Fleet KV
                # locality"): warm the new replica's prefix cache from
                # a donor BEFORE the router can route to it; a warm-up
                # failure or timeout degrades to the historical cold
                # start, never fails the grow
                self._warmup_replica(rid, replica)
                self.router.add_replica(replica)
            except Exception:
                self._role_overrides.pop(rid, None)
                self._replica_models.pop(rid, None)
                raise
            if self.supervisor is not None:
                self.supervisor.register_slot(rid)
        return rid

    def _warmup_replica(self, rid: int, replica) -> None:
        """Pre-populate a grown replica's prefix cache with the
        FLEET-hottest blocks merged across ALL accepting local donors of
        its model pool (docs/SERVING.md "Fleet KV locality"): every
        donor exports its MRU-first blocks device→host, the per-donor
        streams are interleaved by hotness rank (each donor's warmest
        block before any donor's second-warmest), deduplicated by chain
        key, capped at ``warmup_max_blocks``, and scattered into the new
        engine before the router can route to it — so the replica's
        first shared-prefix request hits instead of paying full prefill,
        regardless of which sibling owned the prefix. Remote donors are
        skipped (their KV would need a new RPC — the status-stream
        digest is advisory only) and everything is exception-isolated:
        warm-up can delay a grow by at most ``warmup_timeout_s``, never
        fail it."""
        aff = self.config.affinity
        if not (aff.enabled and aff.warmup_enabled):
            return
        imp = getattr(getattr(replica, "engine", None),
                      "import_prefix_blocks", None)
        if imp is None or getattr(replica, "is_remote", False):
            return
        t0 = time.monotonic()
        self.metrics.gauge("replicas_warming").inc()
        try:
            mid = self._replica_models.get(rid, "default")
            donors = []                 # (warmth, replica) — all of them
            for r in self.router.replicas:
                if getattr(r, "is_remote", False) or not r.accepting:
                    continue
                if getattr(r, "model_id", "default") != mid:
                    continue
                fn = getattr(r, "prefix_digest", None)
                if fn is None:
                    continue
                w = len(fn(aff.digest_max_entries))
                if w > 0:
                    donors.append((w, r))
            if not donors:
                return                  # whole fleet cold: nothing to copy
            # warmest donor first so rank ties resolve toward the
            # busiest cache; each donor exports at most the full budget
            # (dedup below may discard shared prefixes)
            donors.sort(key=lambda p: (-p[0], p[1].replica_id))
            exports = []                # (donor_id, MRU-first entries)
            for _, donor in donors:
                if time.monotonic() - t0 > aff.warmup_timeout_s:
                    break               # donors too slow: ship what we have
                got = donor.engine.export_prefix_blocks(
                    aff.warmup_max_blocks)
                if got:
                    exports.append((donor.replica_id, got))
            # merge hottest-first: rank i of every donor before rank i+1
            # of any, first exporter of a duplicate chain key wins
            seen, entries, sources = set(), [], set()
            for i in range(max((len(e) for _, e in exports), default=0)):
                for donor_id, got in exports:
                    if len(entries) >= aff.warmup_max_blocks:
                        break
                    if i < len(got) and got[i][0] not in seen:
                        seen.add(got[i][0])
                        entries.append(got[i])
                        sources.add(donor_id)
                if len(entries) >= aff.warmup_max_blocks:
                    break
            if time.monotonic() - t0 > aff.warmup_timeout_s:
                entries = []            # donors too slow: cold start
            blocks = imp(entries) if entries else 0
            warmup_s = time.monotonic() - t0
            self.metrics.histogram("replica_warmup_s").observe(warmup_s)
            self.journal.emit("replica_warmup", replica=rid,
                              blocks=blocks, source=sorted(sources),
                              warmup_s=warmup_s)
        except Exception as e:
            logger.error(f"replica {rid} prefix warm-up failed: {e!r}")
        finally:
            self.metrics.gauge("replicas_warming").dec()

    def remove_replica(self, replica_id: int, reason: str = "scale_down",
                       timeout_s: float = 30.0) -> bool:
        """Shrink the fleet by one (docs/SERVING.md "Elastic
        autoscaling"). Order matters for safety: the supervisor slot is
        retired FIRST (a pending restart is cancelled; one already
        building drops its replacement — no resurrection race), then
        the replica drains WITH evacuation — resident sequences are
        handed back with their KV staged for re-import elsewhere (or
        re-prefilled from prompt + delivered tokens), lossless under
        greedy decoding either way — and only then is it unlinked and
        stopped. Refuses to remove the last (or last accepting, or last
        accepting decode-capable) replica: all-replicas-removed is
        impossible by construction."""
        with self._fleet_lock:
            if self._closed:
                raise RuntimeError("frontend is shut down")
            target = self.router.replica_by_id(replica_id)
            if target is None:
                raise KeyError(f"no replica {replica_id}")
            others = [r for r in self.router.replicas if r is not target]
            if not others:
                raise ValueError("cannot remove the last replica")
            if self._models:
                mid = getattr(target, "model_id", "default")
                if not any(getattr(r, "model_id", "default") == mid
                           for r in others):
                    raise ValueError("cannot remove the last replica of "
                                     f"model {mid!r}")
            if target.accepting:
                if not any(r.accepting for r in others):
                    raise ValueError("cannot remove the last accepting "
                                     "replica")
                if self._disagg is not None \
                        and target.role in ("decode", "mixed") \
                        and not any(r.accepting
                                    and r.role in ("decode", "mixed")
                                    for r in others):
                    raise ValueError("cannot remove the last accepting "
                                     "decode-capable replica")
            if self.supervisor is not None:
                self.supervisor.retire_slot(replica_id)
            self._drain_out(target, timeout_s)
            # stop what the unlink actually removed: a supervisor
            # restart that squeaked past the retired check may have
            # swapped a STARTED replacement into the slot since the
            # lookup above — stopping only ``target`` would leak it
            removed = self.router.remove_replica(replica_id)
            removed.stop(timeout=1.0)
            if removed is not target:
                target.stop(timeout=1.0)
            self._role_overrides.pop(replica_id, None)
            self._replica_models.pop(replica_id, None)
        return True

    def set_replica_role(self, replica_id: int, role: str,
                         timeout_s: float = 30.0) -> bool:
        """Re-role one replica prefill<->decode(<->mixed) in place
        (docs/SERVING.md "Elastic autoscaling"): drain WITH evacuation
        (cheap — staged handoff + kv_tier keep KV portable), rebuild
        the Replica over the same engine (fresh one only if the worker
        wedged) with the new role's scheduler shape, and swap it into
        the same slot. Supervision is suspended for the slot during the
        swap and re-registered after. False when the replica already
        has the role."""
        if self._disagg is None:
            raise ValueError("set_replica_role requires "
                             "disaggregation.enabled")
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(f"unknown replica role {role!r}")
        if role == "prefill" and not self._disagg.handoff.enabled:
            raise ValueError("re-roling to prefill requires "
                             "handoff.enabled")
        with self._fleet_lock:
            if self._closed:
                raise RuntimeError("frontend is shut down")
            target = self.router.replica_by_id(replica_id)
            if target is None:
                raise KeyError(f"no replica {replica_id}")
            old_role = target.role
            if old_role == role:
                return False
            if old_role in ("decode", "mixed") and role == "prefill" \
                    and not any(r.accepting
                                and r.role in ("decode", "mixed")
                                for r in self.router.replicas
                                if r is not target):
                raise ValueError("re-role would leave no accepting "
                                 "decode-capable replica")
            suspended = (self.supervisor.retire_slot(replica_id)
                         if self.supervisor is not None else False)
            self._role_overrides[replica_id] = role
            try:
                self._drain_out(target, timeout_s)
                if getattr(target, "is_remote", False):
                    # fabric peer: the engine lives server-side — a
                    # fresh handle re-attaches with the new role (the
                    # server rebuilds its replica on the role change)
                    replacement = self._build_remote(
                        replica_id, self._peer_addrs[replica_id])
                else:
                    if target.thread.is_alive():
                        # wedged mid-drain: the stuck thread owns the
                        # old engine — only a fresh one is safe
                        if self._engine_factory is None:
                            raise RuntimeError(
                                f"replica {replica_id} wedged during "
                                "re-role drain and no engine_factory "
                                "exists")
                        engine = self._engine_factory(replica_id)
                    else:
                        engine = getattr(target.engine, "_ft_inner",
                                         target.engine)
                    replacement = self._build_replica(replica_id, engine)
                displaced = self.router.replace_replica(replica_id,
                                                        replacement)
                # the slot is retired during the swap, so nothing else
                # can have removed it; stop whatever was displaced (and
                # the drained target, if a racing swap displaced it
                # first)
                if displaced is not None:
                    displaced.stop(timeout=1.0)
                if displaced is not target:
                    target.stop(timeout=1.0)
            except Exception:
                self._role_overrides[replica_id] = old_role
                raise
            finally:
                if suspended:
                    self.supervisor.register_slot(replica_id)
        return True

    def _drain_out(self, replica, timeout_s: float) -> None:
        """Evacuate + wait for a replica's worker to exit (no-op for a
        DEAD/STOPPED replica — its requests already failed over)."""
        from .replica import ReplicaState

        if replica.state in (ReplicaState.DEAD, ReplicaState.STOPPED):
            return
        replica.request_evacuation(self._evacuate_handback)
        deadline = time.monotonic() + max(0.0, timeout_s)
        while replica.thread.is_alive() \
                and time.monotonic() < deadline:
            time.sleep(0.005)

    def _evacuate_handback(self, req: ServingRequest, payload,
                           replica_id: int) -> None:
        """Evacuation hand-back (runs on the draining replica's worker
        thread): re-queue the request — with its exported KV staged for
        import on the destination when available, marked so the import
        side keeps it out of the disagg handoff counters — or settle it
        if cancel/deadline/shutdown already claimed it."""
        if getattr(req, "_federated", False) \
                and self._federation_server is not None:
            # federated mirror: stream the exported KV back to the
            # adopting frontend (its requeue path stages or re-prefills
            # — lossless either way), never into this one's queue
            self._federation_server.return_evacuated(req, payload)
            return
        if (self._closed or req.cancel_requested.is_set()
                or req.expired()):
            if req.cancel_requested.is_set():
                req.finish(RequestState.CANCELLED, FinishReason.CANCELLED)
                self.metrics.counter("requests_cancelled").inc()
            elif req.expired():
                req.finish(RequestState.EXPIRED, FinishReason.DEADLINE)
                self.metrics.counter("requests_expired").inc()
            else:
                req.finish(RequestState.REJECTED, "draining")
                self.metrics.counter("requests_shed").inc()
            return
        if payload is not None:
            payload["evacuated"] = True
            if self._evacuation_stager().try_stage(req, payload):
                req.handoff_t = time.monotonic()
            # else: staging budget full — the payload is dropped and
            # the request re-prefills (recompute fallback, still
            # lossless), exactly the disagg handoff degradation
        self.metrics.counter("requests_evacuated").inc()
        if req.spans is not None:
            req.begin_span(self.tracer, "queue",
                           attrs={"evacuated_from": replica_id})
        req.state = RequestState.QUEUED
        req.replica_id = None
        if not self.admission.requeue(req):
            # queue closed mid-evacuation: shutdown — terminal
            req.finish(RequestState.REJECTED, "draining")
            self.metrics.counter("requests_shed").inc()

    def _evacuation_stager(self):
        """Staging budget for evacuated KV: the disagg handoff stager
        when one exists (one shared host-RAM bound + the
        ``handoff_staged`` gauge), else a lazily-built stager with the
        same configured budget."""
        if self._stager is not None:
            return self._stager
        if self._evac_stager is None:
            from .handoff import HandoffStager

            self._evac_stager = HandoffStager(
                self.config.disaggregation.handoff.max_staged,
                self.metrics)
        return self._evac_stager

    def fleet_signals(self):
        """One consistent elasticity-signal snapshot for the
        :class:`~deepspeed_tpu.serving.autoscaler.FleetController`."""
        from .autoscaler import FleetSignals, ReplicaInfo

        parked = (set(self.supervisor.parked_ids())
                  if self.supervisor is not None else set())
        infos = tuple(
            ReplicaInfo(r.replica_id, getattr(r, "role", "mixed"),
                        r.accepting, r.replica_id in parked,
                        r.outstanding_prefill_tokens,
                        r.outstanding_decode_tokens,
                        remote=bool(getattr(r, "is_remote", False)),
                        federated=bool(getattr(r, "is_federated", False)),
                        model_id=getattr(r, "model_id", "default"))
            for r in self.router.replicas)
        burn = 0.0
        if self.alerts is not None:
            for s in self.alerts.status().values():
                burn = max(burn, s["burn_slow"])
        dis = self._disagg
        # per-model pool bounds, a ModelSpec's None ends resolved
        # against the global autoscaler min/max (docs/SERVING.md
        # "Multi-model & multi-tenant serving")
        asc = self.config.autoscaler
        bounds = tuple(
            (name,
             spec.min_replicas if spec.min_replicas is not None
             else asc.min_replicas,
             spec.max_replicas if spec.max_replicas is not None
             else asc.max_replicas)
            for name, spec in sorted(self._models.items()))
        depth = len(self.admission)
        # predictive scaling (docs/SERVING.md "Fleet KV locality"):
        # project the queue depth predict_horizon_s ahead from the
        # windowed submit-minus-completion rate. window_rate is None
        # until the ring has history — the controller then runs pure
        # watermarks, byte for byte (and predicted_load stays 0).
        predicted = None
        aff = self.config.affinity
        if aff.enabled and aff.predictive:
            w = aff.predict_window_s
            sub = self.windowed.window_rate("requests_submitted", w)
            if sub is not None:
                done = 0.0
                for name in ("requests_completed", "requests_failed",
                             "requests_shed", "requests_expired",
                             "requests_cancelled"):
                    done += self.windowed.window_rate(name, w) or 0.0
                predicted = (depth + aff.predict_horizon_s
                             * max(0.0, sub - done))
                self.metrics.gauge("predicted_load").set(predicted)
        return FleetSignals(
            queue_depth=depth, replicas=infos,
            burn_slow_max=burn,
            prefill_token_cost=(dis.prefill_token_cost
                                if dis is not None else 1.0),
            decode_token_cost=(dis.decode_token_cost
                               if dis is not None else 1.0),
            disaggregated=dis is not None,
            model_bounds=bounds,
            predicted_queue_depth=predicted)

    def set_proactive_brownout(self, fraction: Optional[float]) -> None:
        """Autoscaler brownout actuator: degrade (or restore, with
        ``None``) the admission queue's effective capacity fraction."""
        self.admission.set_proactive_fraction(fraction)

    # ---------------------------------------------------------- lifecycle
    def stream(self, handle: RequestHandle, timeout: Optional[float] = None):
        return handle.stream(timeout=timeout)

    def cancel(self, handle: RequestHandle) -> None:
        """Request cancellation. A still-queued request is removed from
        the admission queue immediately (freeing its depth slot for new
        traffic); a dispatched one is cancelled by its replica between
        scheduler steps, which frees its KV blocks promptly."""
        req = handle._req
        req.cancel_requested.set()
        if self.admission.remove(req):
            req.finish(RequestState.CANCELLED, FinishReason.CANCELLED)
            self.metrics.counter("requests_cancelled").inc()
            return
        # cross-process cancel (docs/SERVING.md "Multi-host serving"):
        # a local replica polls the flag between scheduler steps, but a
        # remote replica's worker reads ITS copy of the request — the
        # flag must cross the wire. No-op for local replicas (no
        # notify_cancel attribute).
        rep = (self.router.replica_by_id(req.replica_id)
               if req.replica_id is not None else None)
        notify = getattr(rep, "notify_cancel", None)
        if notify is not None:
            notify(req)

    def wait_all(self, handles: Sequence[RequestHandle],
                 timeout: Optional[float] = None) -> bool:
        deadline = (time.monotonic() + timeout) if timeout is not None else None
        for h in handles:
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            if not h._req.wait(left):
                return False
        return True

    # ------------------------------------------------------------- metrics
    def _observability_tick(self) -> None:
        """Router-tick hook (~1/s): feed the windowed-metrics ring and,
        with ``slo.enabled``, run the burn-rate alert state machines.
        Both are cadence-gated internally; the router exception-isolates
        the call."""
        self.windowed.maybe_tick()
        if self.alerts is not None:
            self.alerts.maybe_evaluate()
        self._maybe_journal_tier_pressure()
        self._refresh_admission_gauges()
        if self._federation is not None:
            # distinct live peer frontends, both directions (adopted
            # FROM + connected TO this exporter) — identity-deduped so
            # mutual peering counts each peer once
            ids = {p.peer_id for p in self._federation_peers
                   if p.alive and p.peer_id}
            if self._federation_server is not None:
                ids |= self._federation_server.live_peer_ids()
            self.metrics.gauge("federation_peers").set(len(ids))
        # distinct remote journal sources currently held (0 on fleets
        # with no remote members — the gauge exists either way)
        self.metrics.gauge("fleet_telemetry_sources").set(
            len(self.fleet.sources()))

    def _refresh_admission_gauges(self) -> None:
        """Sum the fleet's reservation shortfall and parked-sequence
        footprint into the ``queue_wait_blocks`` /
        ``preempted_resident_blocks`` gauges, and feed the queue's
        preempt-pressure flag (labels overload sheds; docs/SERVING.md
        "Admission and preemption"). Cheap no-ops — both reads are
        plain ints — when admission is off."""
        shortfall = parked = 0
        for rep in self.router.replicas:
            sched = getattr(rep, "scheduler", None)
            if sched is None:
                continue
            fn = getattr(sched, "reserve_shortfall_blocks", None)
            if fn is not None:
                shortfall += fn()
            fn = getattr(sched, "preempted_resident_blocks", None)
            if fn is not None:
                parked += fn()
        self.metrics.gauge("queue_wait_blocks").set(shortfall)
        self.metrics.gauge("preempted_resident_blocks").set(parked)
        self.admission.set_preempt_pressure(shortfall > 0 or parked > 0)

    def _maybe_journal_tier_pressure(self) -> None:
        """Journal a ``kv_tier_pressure`` event when the fleet's KV tier
        churned since the last EMITTED event (spills or drops — the
        signals that the device pool is too small for the working set
        and, on drops, that the tier itself is too). Cadence-gated to
        ~1/s; silent while the tier is idle or absent.

        Deltas are per replica SLOT against the slot's last-emitted
        baseline, with Prometheus-style reset detection (a counter
        below its baseline means the supervisor swapped in a fresh
        engine — baseline drops to zero, not negative deltas), and the
        baselines advance only when an event is emitted — restores that
        happen in quiet windows are carried into the next event instead
        of being silently absorbed."""
        now = time.monotonic()
        if now - self._tier_journal_t < 1.0:
            return
        self._tier_journal_t = now
        deltas = {"spilled": 0, "restored": 0, "dropped": 0}
        host_bytes = 0
        current: dict = {}
        found = False
        for rep in self.router.replicas:
            fn = getattr(getattr(rep, "engine", None), "tier_stats", None)
            if fn is None:
                continue
            try:
                t = fn()
            except Exception:
                continue
            found = True
            slot = getattr(rep, "replica_id", id(rep))
            base = self._tier_last.get(slot)
            if base is None or any(t.get(k, 0) < base[k] for k in deltas):
                base = {k: 0 for k in deltas}    # fresh engine: reset
            for k in deltas:
                deltas[k] += t.get(k, 0) - base[k]
            current[slot] = {k: t.get(k, 0) for k in deltas}
            host_bytes += t.get("host_bytes", 0)
        if not found:
            return
        if deltas["spilled"] > 0 or deltas["dropped"] > 0:
            self.journal.emit("kv_tier_pressure",
                              spilled=deltas["spilled"],
                              restored=deltas["restored"],
                              dropped=deltas["dropped"],
                              host_bytes=int(host_bytes))
            # MERGE, don't replace: a slot whose stats read transiently
            # failed this tick must keep its baseline, or its lifetime
            # totals would re-emit as a phantom burst next tick
            self._tier_last.update(current)

    def _refresh_kv_gauges(self) -> None:
        """Sum KV-pool occupancy over the fleet into the
        ``kv_blocks_in_use`` / ``kv_bytes_in_use`` gauges (docs/SERVING.md
        "KV quantization" / OBSERVABILITY.md). One consistent read per
        replica from ``engine.occupancy()`` — the single snapshot that
        replaced the ad-hoc block counts (BlockedAllocator.occupancy)."""
        self._refresh_admission_gauges()
        blocks = total_bytes = 0
        host_blocks = host_bytes = disk_blocks = disk_bytes = 0
        pbytes_total = pbytes_quant = 0
        role_blocks: dict = {}
        found = False
        for rep in self.router.replicas:
            occ_fn = getattr(getattr(rep, "engine", None), "occupancy", None)
            if occ_fn is None:
                continue
            try:
                occ = occ_fn()
            except Exception:
                continue
            found = True
            # resident param bytes (docs/SERVING.md "Weight
            # quantization"): fleet-summed from engine.param_stats(),
            # the replicas-per-host capacity ledger weight quantization
            # moves — zero quantized share on full-precision engines
            stats_fn = getattr(rep.engine, "param_stats", None)
            if stats_fn is not None:
                try:
                    ps = stats_fn()
                    pbytes_total += int(ps.get("param_bytes_total", 0))
                    pbytes_quant += int(ps.get("param_bytes_quantized", 0))
                except Exception:
                    pass
            blocks += occ.get("in_use_blocks", 0)
            total_bytes += occ.get("bytes_in_use", 0)
            # tiered KV residency (docs/SERVING.md "KV tiering"); zero
            # on engines without a tier — same occupancy schema
            host_blocks += occ.get("kv_blocks_host_tier", 0)
            host_bytes += occ.get("kv_bytes_host_tier", 0)
            disk_blocks += occ.get("kv_blocks_disk_tier", 0)
            disk_bytes += occ.get("kv_bytes_disk_tier", 0)
            role = getattr(rep, "role", "mixed")
            role_blocks[role] = (role_blocks.get(role, 0)
                                 + occ.get("in_use_blocks", 0))
        if found:
            self.metrics.gauge("kv_blocks_in_use").set(blocks)
            self.metrics.gauge("kv_bytes_in_use").set(total_bytes)
            self.metrics.gauge("kv_blocks_host_tier").set(host_blocks)
            self.metrics.gauge("kv_blocks_disk_tier").set(disk_blocks)
            self.metrics.gauge("kv_tier_bytes_host").set(host_bytes)
            self.metrics.gauge("kv_tier_bytes_disk").set(disk_bytes)
            self.metrics.gauge("param_bytes_total").set(pbytes_total)
            self.metrics.gauge("param_bytes_quantized").set(pbytes_quant)
            # per-role split (docs/SERVING.md "Disaggregated serving"):
            # handoff pressure — decode pools filling while prefill
            # pools stay light — is visible in flight-recorder metric
            # snapshots via these gauges
            for role, n in role_blocks.items():
                self.metrics.gauge(f"kv_blocks_in_use_role_{role}").set(n)

    def metrics_snapshot(self) -> dict:
        self._refresh_kv_gauges()
        snap = self.metrics.snapshot()
        submitted = snap.get("requests_submitted", 0.0) or 0.0
        snap["shed_rate"] = (snap.get("requests_shed", 0.0) / submitted
                             if submitted else 0.0)
        return snap

    def publish_metrics(self, monitor, step: int = 0) -> None:
        """Fan the registry out through a monitor/ backend (MonitorMaster,
        CSVMonitor, ...)."""
        self._refresh_kv_gauges()
        self.metrics.publish(monitor, step)

    def render_prometheus(self) -> str:
        """Prometheus text exposition of the serving registry — hand this
        to whatever scrapes/serves /metrics (docs/OBSERVABILITY.md)."""
        return self.metrics.render_prometheus()

    @property
    def observability_address(self) -> Optional[str]:
        """``host:port`` of the scrape endpoint (resolved — port 0 in
        the config binds a free port), or ``None`` when
        ``observability:`` is disabled."""
        ep = getattr(self, "_obs_endpoint", None)
        return ep.address if ep is not None else None

    # --------------------------------------------------------- health report
    def health_report(self, window_s: float = 60.0,
                      recent_events: int = 20) -> dict:
        """One queryable fleet-health answer (docs/OBSERVABILITY.md
        "The health report"): SLO status + active alerts, windowed
        latency summaries per class, replica states, queue depths
        (total and per class), KV occupancy, headline counters, and the
        recent ops-journal tail — merged into a single dict. Works with
        every feature off (the SLO block is then ``None`` and the window
        summaries cover whatever history the passive ring holds)."""
        self._refresh_kv_gauges()
        # forced tick: the report reads up-to-the-moment. Safe at any
        # poll rate — faster-than-cadence ticks refresh the ring head
        # instead of appending, so a fast dashboard can't shrink the
        # window history (windowed.tick docstring).
        self.windowed.tick()
        snap = self.metrics.snapshot()
        classes = sorted(self.config.classes)
        tenants = sorted(self.config.tenants)
        hist_names = (["ttft_s", "tpot_s", "queue_wait_s",
                       "kv_tier_restore_s", "preempt_spill_s",
                       "preempt_resume_s"]
                      + [f"ttft_s_class_{c}" for c in classes]
                      + [f"tpot_s_class_{c}" for c in classes]
                      + [f"ttft_s_tenant_{t}" for t in tenants]
                      + [f"tpot_s_tenant_{t}" for t in tenants])
        report = {
            "wall_time": time.time(),
            "replicas": [{"id": r.replica_id, "state": r.state.value,
                          "role": getattr(r, "role", "mixed"),
                          "model": getattr(r, "model_id", "default"),
                          "outstanding_tokens": r.outstanding_tokens}
                         for r in self.router.replicas],
            "replicas_healthy": snap.get("replicas_healthy", 0.0),
            "replicas_parked": snap.get("replicas_parked", 0.0),
            "queue": {
                "depth": snap.get("queue_depth", 0.0),
                "per_class": {c: snap.get(f"queue_depth_class_{c}", 0.0)
                              for c in classes},
                "brownout_active": bool(snap.get("brownout_active", 0.0)),
            },
            "occupancy": {
                "kv_blocks_in_use": snap.get("kv_blocks_in_use", 0.0),
                "kv_bytes_in_use": snap.get("kv_bytes_in_use", 0.0),
                "kv_blocks_host_tier": snap.get("kv_blocks_host_tier", 0.0),
                "kv_tier_bytes_host": snap.get("kv_tier_bytes_host", 0.0),
                "kv_tier_bytes_disk": snap.get("kv_tier_bytes_disk", 0.0),
                "handoff_staged": snap.get("handoff_staged", 0.0),
                "outstanding_tokens": snap.get("outstanding_tokens", 0.0),
                "preempted_resident_blocks": snap.get(
                    "preempted_resident_blocks", 0.0),
                "queue_wait_blocks": snap.get("queue_wait_blocks", 0.0),
            },
            "counters": {k: snap.get(k, 0.0) for k in (
                "requests_submitted", "requests_completed",
                "requests_shed", "requests_expired", "requests_failed",
                "requests_failed_over", "replica_restarts",
                "handoffs_completed", "handoff_fallbacks",
                "sequences_preempted", "sequences_resumed")},
            "window_s": window_s,
            "window": self.windowed.summary(hist_names, window_s),
            # per-tenant fair-share/quota books (docs/SERVING.md
            # "Multi-model & multi-tenant serving"); None = tenancy off
            "tenants": (self._tenancy.snapshot()
                        if self._tenancy is not None else None),
            "slo": (self.alerts.status() if self.alerts is not None
                    else None),
            "alerts_firing": (self.alerts.firing()
                              if self.alerts is not None else []),
            # elastic autoscaling (docs/SERVING.md "Elastic
            # autoscaling"): what the controller wants vs has, its
            # action tally and cost ledger; None on static fleets
            "autoscaler": (dict(self.autoscaler.stats(),
                                replicas_target=snap.get("replicas_target",
                                                         0.0),
                                brownout_proactive=bool(snap.get(
                                    "brownout_proactive_active", 0.0)))
                           if self.autoscaler is not None else None),
            "events": self.journal.events(limit=recent_events),
        }
        # fleet observability (docs/OBSERVABILITY.md "Fleet
        # observability"): per-remote-replica transport/clock/recency
        # status, federation peer books, and the FleetJournal's
        # per-source tallies. All empty/None on a purely local fleet —
        # the report shape is stable either way.
        remotes = [r.ops_status() for r in self.router.replicas
                   if hasattr(r, "ops_status")]
        report["remotes"] = remotes
        fed = None
        if self._federation is not None:
            peers = []
            now = time.monotonic()
            for p in self._federation_peers:
                ages = [now - h._last_status_t
                        for h in p._handles.values() if h._last_status_t]
                peers.append({
                    "address": p.address,
                    "peer_id": p.peer_id,
                    "alive": p.alive,
                    "inflight": p.inflight(),
                    "exports_adopted": sum(
                        1 for rid in self._federated_refs
                        if self._federated_refs[rid].peer is p),
                    "last_status_age_s": min(ages) if ages else None})
            fed = {
                "frontend_id": self._federation_id,
                "epoch": self._federation_epoch,
                "listen": (self._federation_server.address
                           if self._federation_server is not None
                           else None),
                "peers": peers,
                "peers_live": sorted(
                    self._federation_server.live_peer_ids()
                    if self._federation_server is not None else []),
            }
        report["federation"] = fed
        report["fleet_journal"] = self.fleet.sources()
        report["observability_address"] = self.observability_address
        return report

    def health_report_text(self, window_s: float = 60.0,
                           recent_events: int = 10) -> str:
        """The health report rendered for a terminal/incident channel."""
        r = self.health_report(window_s=window_s,
                               recent_events=recent_events)
        lines = [
            "== serving health ==",
            "replicas: " + " ".join(
                f"{rep['id']}:{rep['state']}({rep['role']})"
                for rep in r["replicas"])
            + (f"  [{int(r['replicas_parked'])} parked]"
               if r["replicas_parked"] else ""),
            f"queue: depth={r['queue']['depth']:.0f} "
            + " ".join(f"{c}={d:.0f}"
                       for c, d in sorted(r["queue"]["per_class"].items()))
            + ("  BROWNOUT" if r["queue"]["brownout_active"] else ""),
            f"kv: blocks={r['occupancy']['kv_blocks_in_use']:.0f} "
            f"bytes={r['occupancy']['kv_bytes_in_use']:.0f} "
            f"staged={r['occupancy']['handoff_staged']:.0f}",
        ]
        c = r["counters"]
        lines.append(
            f"requests: submitted={c['requests_submitted']:.0f} "
            f"completed={c['requests_completed']:.0f} "
            f"shed={c['requests_shed']:.0f} "
            f"failed={c['requests_failed']:.0f} "
            f"failed_over={c['requests_failed_over']:.0f}")
        for rem in r.get("remotes") or []:
            age = rem.get("last_status_age_s")
            lines.append(
                f"remote {rem['replica']} ({rem['source']}): "
                + ("up" if rem["connected"] else "DOWN")
                + f" rpc={rem['rpc_calls']}"
                f"@{rem['rpc_avg_s'] * 1e3:.1f}ms "
                f"clk={rem['clock_offset_s'] * 1e3:+.1f}ms "
                f"active={rem['active']} "
                + (f"status_age={age:.1f}s" if age is not None
                   else "status_age=-"))
        if r.get("federation") is not None:
            f = r["federation"]
            lines.append(
                f"federation {f['frontend_id']}: "
                f"peers_connected={len(f['peers_live'])} "
                f"adopted_from={sum(1 for p in f['peers'] if p['alive'])}"
                f"/{len(f['peers'])}")
            for p in f["peers"]:
                age = p.get("last_status_age_s")
                lines.append(
                    f"  peer {p['peer_id'] or p['address']}: "
                    + ("up" if p["alive"] else "DOWN")
                    + f" exports={p['exports_adopted']} "
                    f"seats_in_use={p['inflight']} "
                    + (f"status_age={age:.1f}s" if age is not None
                       else "status_age=-"))
        if r.get("tenants"):
            for name, t in sorted(r["tenants"].items()):
                lines.append(
                    f"tenant {name}: w={t['weight']:g} "
                    f"service={t['service']:.1f} "
                    f"window_tokens={t['window_tokens']:.0f}"
                    + (f"  THROTTLED({t['throttled']})"
                       if t["throttled"] else ""))
        for name, w in sorted(r["window"].items()):
            if w.get("count"):
                lines.append(
                    f"window[{window_s:.0f}s] {name}: n={w['count']} "
                    f"p50={w['p50'] * 1e3:.1f}ms p95={w['p95'] * 1e3:.1f}ms")
        if r["autoscaler"] is not None:
            a = r["autoscaler"]
            lines.append(
                f"autoscaler: target={a['replicas_target']:.0f} "
                f"ups={a['scale_ups']} downs={a['scale_downs']} "
                f"reroles={a['reroles']} "
                f"replica_s={a['replica_seconds']:.1f}"
                + ("  PROACTIVE-BROWNOUT" if a["brownout_proactive"]
                   else ""))
        if r["slo"] is not None:
            for name, s in sorted(r["slo"].items()):
                state = "FIRING" if s["firing"] else "ok"
                lines.append(
                    f"slo {name}: {state} burn_fast={s['burn_fast']} "
                    f"burn_slow={s['burn_slow']} "
                    f"budget_spent={s['budget_spent_frac']}")
        if r["events"]:
            lines.append("recent events:")
            lines.append(self.journal.render_text(limit=recent_events))
        return "\n".join(lines)

    # ------------------------------------------------------------ telemetry
    def debug_dump(self, dump_dir: Optional[str] = None) -> dict:
        """On-demand FLEET flight-recorder dump (docs/OBSERVABILITY.md
        "Fleet observability"): the local recorder dump (recent spans,
        open ones included, + metric snapshots, as raw JSON and Chrome
        ``trace_event`` JSON) plus one bounded ``dump`` RPC per remote
        replica, each written alongside as
        ``fleet_<source>_<pid>.json``. Returns ``{"json": path,
        "chrome_trace": path, "remotes": {source: path | None}}`` —
        ``None`` marks a remote whose dump RPC failed (the local dump
        never blocks on a sick peer). Works with telemetry disabled too
        (metrics only; the span lists are empty)."""
        import json as _json

        self.recorder.snapshot_metrics()
        out = self.recorder.dump(dump_dir=dump_dir, reason="debug")
        d = self.recorder._resolve_dir(dump_dir)
        remotes: Dict[str, Optional[str]] = {}
        for rep in self.router.replicas:
            fn = getattr(rep, "pull_dump", None)
            if fn is None:
                continue
            dump = fn()
            src = (dump or {}).get("source") or getattr(
                rep, "_source", f"replica-{rep.replica_id}")
            if dump is None:
                remotes[str(src)] = None
                continue
            safe = str(src).replace("/", "_").replace(":", "_")
            path = os.path.join(d, f"fleet_{safe}_{dump.get('pid')}.json")
            with open(path, "w") as f:
                _json.dump(dump, f)
            remotes[str(src)] = path
        if remotes:
            out = dict(out, remotes=remotes)
            self.journal.emit("fleet_dump",
                              sources=sorted(remotes), dir=d)
        return out

    # ------------------------------------------------------------ shutdown
    def shutdown(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """drain=True: stop admitting, let the queue flow through the
        replicas and in-flight work finish (within ``timeout``); whatever
        remains is failed with "draining". drain=False: fail everything
        still queued and stop."""
        if self._closed:
            return
        # the closed flip happens under the fleet lock: a membership
        # change already in flight (add_replica building an engine on
        # the autoscaler worker) completes and installs BEFORE the flag
        # flips — its replica is then in the list the teardown below
        # stops — while any later attempt sees _closed and aborts. A
        # post-shutdown install that would leak a live worker is
        # impossible either way.
        with self._fleet_lock:
            if self._closed:
                return
            self._closed = True
        # scrape endpoint first: no HTTP reader may observe (or block
        # on) a half-torn frontend
        if getattr(self, "_obs_endpoint", None) is not None:
            self._obs_endpoint.stop()
        if self.autoscaler is not None:
            # no membership changes may race the teardown below
            self.autoscaler.stop()
        timeout = timeout if timeout is not None else self.config.drain_timeout_s
        deadline = time.monotonic() + timeout
        if drain:
            while len(self.admission) and time.monotonic() < deadline:
                time.sleep(0.01)
        for req in self.admission.close():
            req.finish(RequestState.REJECTED, "draining")
            self.metrics.counter("requests_shed").inc()
        self.router.stop(drain=drain,
                         timeout=max(1.0, deadline - time.monotonic()))
        # federation teardown LAST: in-flight federated mirrors on the
        # exported replicas were settled by the router stop above, and
        # closing the bootstrap connections is what signals peer_lost
        # to the adopters
        if self._federation_server is not None:
            self._federation_server.stop()
        for peer in self._federation_peers:
            peer.close()
        if self.net_chaos is not None:
            # uninstall only OUR injector: a test running two frontends
            # must not have the survivor's schedule torn down by the
            # first shutdown
            from .fabric import chaos as _net_chaos

            if _net_chaos.installed() is self.net_chaos:
                _net_chaos.uninstall()
