"""ServingFrontend — the production request surface over InferenceEngineV2.

Composes the whole serving stack::

    submit()/stream()/cancel()
        └─ AdmissionQueue   (bounded; sheds with Rejected("overloaded"))
             └─ ReplicaRouter (least-outstanding-tokens, health/drain)
                  └─ Replica × N (thread-per-replica Dynamic SplitFuse
                       loops over InferenceEngineV2; streaming delivery,
                       cancel → immediate KV free)

All telemetry lands in one :class:`MetricsRegistry` (TTFT/TPOT/queue
histograms, shed/cancel/complete counters) that fans out through the
``monitor/`` backends via :meth:`publish_metrics` and feeds ``bench.py``'s
serving phase.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence

from ..telemetry import FlightRecorder
from .config import ServingConfig
from .metrics import MetricsRegistry, serving_metrics
from .queue import AdmissionQueue
from .replica import Replica
from .request import (FinishReason, Rejected, RequestHandle,
                      RequestState, ServingRequest)
from .router import ReplicaRouter


class ServingFrontend:
    def __init__(self, engines: Sequence, config: Optional[ServingConfig] = None,
                 sample_fn: Optional[Callable] = None,
                 metrics: Optional[MetricsRegistry] = None):
        """``engines``: one InferenceEngineV2 per replica (the caller owns
        model/param placement; replicas never share an engine — each owns
        its KV pool and scheduler)."""
        if not engines:
            raise ValueError("ServingFrontend needs at least one engine")
        self.config = config or ServingConfig()
        self.metrics = metrics or serving_metrics()
        # telemetry (docs/OBSERVABILITY.md): one tracer for the whole
        # frontend — request stage spans begin here at submit, the
        # router/replicas/scheduler continue the chain — plus a flight
        # recorder over it. Both are no-ops when ``telemetry.enabled`` is
        # false; debug_dump() still works (metrics only, no spans).
        self.tracer = self.config.telemetry.build_tracer()
        self.recorder = self.config.telemetry.build_recorder(
            self.tracer, metrics=self.metrics)
        if self.config.ttft_buckets_s:
            self.metrics.histogram("ttft_s", self.config.ttft_buckets_s,
                                   reset=True)
        if self.config.prefix_cache.enabled:
            # config-driven prefix caching: flip it on every engine that
            # supports it (enabling on a built engine is safe — matching
            # simply starts now). Engines the caller already enabled
            # directly are left alone when the config block is off.
            for eng in engines:
                configure = getattr(eng, "configure_prefix_cache", None)
                if configure is not None:
                    configure(True,
                              self.config.prefix_cache.max_cached_blocks
                              or None)
        self.admission = AdmissionQueue(self.config.max_queue_depth,
                                        self.metrics)
        # speculative decoding is applied per replica: each Replica builds
        # its own proposer from the block (draft state is per-engine)
        spec = (self.config.speculative
                if self.config.speculative.enabled else None)
        recorder = (self.recorder
                    if self.config.telemetry.dump_on_error else None)
        replicas = [Replica(i, eng, self.metrics, sample_fn,
                            wedge_timeout_s=self.config.wedge_timeout_s,
                            speculative=spec, tracer=self.tracer,
                            recorder=recorder)
                    for i, eng in enumerate(engines)]
        self.router = ReplicaRouter(replicas, self.admission, self.metrics,
                                    tracer=self.tracer,
                                    recorder=self.recorder)
        self._closed = False
        self.router.start()

    @classmethod
    def from_engine_factory(cls, engine_factory: Callable[[int], object],
                            config: Optional[ServingConfig] = None,
                            **kwargs) -> "ServingFrontend":
        """Build the replica fleet from the config:
        ``engine_factory(replica_id)`` is called ``config.num_replicas``
        times (the config-driven path for the ``serving: {...}`` block)."""
        config = config or ServingConfig()
        engines = [engine_factory(i)
                   for i in range(max(1, config.num_replicas))]
        return cls(engines, config, **kwargs)

    # ---------------------------------------------------------------- submit
    def submit(self, prompt_tokens: List[int],
               max_new_tokens: Optional[int] = None,
               priority: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               eos_token_id: Optional[int] = None) -> RequestHandle:
        """Admit a request. Raises :class:`Rejected` when shed (full queue,
        draining frontend, or a prompt no replica could ever schedule).
        ``priority``/``deadline_ms``/``max_new_tokens`` default from the
        config (``default_priority`` etc.)."""
        self.metrics.counter("requests_submitted").inc()
        if self._closed:
            self.metrics.counter("requests_shed").inc()
            raise Rejected("draining", "frontend is shut down")
        cfg = self.config
        if priority is None:
            priority = cfg.default_priority
        if deadline_ms is None:
            deadline_ms = cfg.default_deadline_ms
        req = ServingRequest(
            prompt_tokens,
            max_new_tokens if max_new_tokens is not None
            else cfg.default_max_new_tokens,
            priority, deadline_ms / 1e3 if deadline_ms is not None else None,
            eos_token_id)
        if self.tracer.enabled:
            # root of this request's trace + the first stage (queue wait).
            # Rejection paths below close both via req.finish.
            req.trace_id = f"req-{req.uid}"
            req.spans = {"request": self.tracer.begin(
                "request", trace_id=req.trace_id,
                attrs={"uid": req.uid,
                       "prompt_tokens": len(req.prompt_tokens),
                       "max_new_tokens": req.max_new_tokens,
                       "priority": req.priority})}
            req.begin_span(self.tracer, "queue")
        max_len = min(r.engine.model.cfg.max_seq_len
                      for r in self.router.replicas)
        if len(req.prompt_tokens) + req.max_new_tokens > max_len:
            self.metrics.counter("requests_shed").inc()
            req.finish(RequestState.REJECTED, "too_long")
            raise Rejected("too_long",
                           f"{len(req.prompt_tokens)}+{req.max_new_tokens} "
                           f"tokens > max_seq_len {max_len}")
        self.admission.offer(req, block=cfg.shed_policy == "block")
        return RequestHandle(req, self)

    # ---------------------------------------------------------- lifecycle
    def stream(self, handle: RequestHandle, timeout: Optional[float] = None):
        return handle.stream(timeout=timeout)

    def cancel(self, handle: RequestHandle) -> None:
        """Request cancellation. A still-queued request is removed from
        the admission queue immediately (freeing its depth slot for new
        traffic); a dispatched one is cancelled by its replica between
        scheduler steps, which frees its KV blocks promptly."""
        req = handle._req
        req.cancel_requested.set()
        if self.admission.remove(req):
            req.finish(RequestState.CANCELLED, FinishReason.CANCELLED)
            self.metrics.counter("requests_cancelled").inc()

    def wait_all(self, handles: Sequence[RequestHandle],
                 timeout: Optional[float] = None) -> bool:
        deadline = (time.monotonic() + timeout) if timeout is not None else None
        for h in handles:
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            if not h._req.wait(left):
                return False
        return True

    # ------------------------------------------------------------- metrics
    def metrics_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        submitted = snap.get("requests_submitted", 0.0) or 0.0
        snap["shed_rate"] = (snap.get("requests_shed", 0.0) / submitted
                             if submitted else 0.0)
        return snap

    def publish_metrics(self, monitor, step: int = 0) -> None:
        """Fan the registry out through a monitor/ backend (MonitorMaster,
        CSVMonitor, ...)."""
        self.metrics.publish(monitor, step)

    def render_prometheus(self) -> str:
        """Prometheus text exposition of the serving registry — hand this
        to whatever scrapes/serves /metrics (docs/OBSERVABILITY.md)."""
        return self.metrics.render_prometheus()

    # ------------------------------------------------------------ telemetry
    def debug_dump(self, dump_dir: Optional[str] = None) -> dict:
        """On-demand flight-recorder dump: recent spans (open ones
        included) + metric snapshots, written as raw JSON and Chrome
        ``trace_event`` JSON (chrome://tracing / Perfetto). Returns
        ``{"json": path, "chrome_trace": path}``. Works with telemetry
        disabled too (metrics only; the span list is empty)."""
        self.recorder.snapshot_metrics()
        return self.recorder.dump(dump_dir=dump_dir, reason="debug")

    # ------------------------------------------------------------ shutdown
    def shutdown(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """drain=True: stop admitting, let the queue flow through the
        replicas and in-flight work finish (within ``timeout``); whatever
        remains is failed with "draining". drain=False: fail everything
        still queued and stop."""
        if self._closed:
            return
        self._closed = True
        timeout = timeout if timeout is not None else self.config.drain_timeout_s
        deadline = time.monotonic() + timeout
        if drain:
            while len(self.admission) and time.monotonic() < deadline:
                time.sleep(0.01)
        for req in self.admission.close():
            req.finish(RequestState.REJECTED, "draining")
            self.metrics.counter("requests_shed").inc()
        self.router.stop(drain=drain,
                         timeout=max(1.0, deadline - time.monotonic()))
